"""graftlint schema engine: wire-schema compatibility vs a committed lock.

Parity: no single reference counterpart — reference dlrover's wire
compatibility lives in `proto/elastic_training.proto:14-29` (protobuf's
field numbering makes removals/renames structurally visible at build
time); this repo's typed-JSON codec (`common/serialize.py:1`) has no such
artifact, so every ADD-ONLY contract was enforced by hand-written pin
tests scattered across six suites.  This engine is the TPU redesign of
the proto file: it EXTRACTS the full wire surface from the AST and diffs
it against a committed lockfile (`analysis/schema.lock.json`), making a
PR's schema delta reviewable in its diff and removals a build-time error.

Like the ast/protocol/concurrency engines this imports no jax — it runs
in the `__graft_entry__.py` pre-flight before any backend exists.

The extracted surface (canonical sorted-keys JSON, field order
preserved inside lists):

- ``messages``: every ``@message`` dataclass in `common/messages.py` —
  field names IN DECLARATION ORDER, each with its default's canonical
  repr and sentinel-ness.  The codec decodes with unknown-field
  filtering (`serialize._decode_value`), so mixed-generation decode
  works iff every field has a default — a new field without one is
  `schema-field-no-sentinel`.
- ``registries``: the ADD-ONLY tuples (`LEDGER_STATES`,
  `SERVE_STATES`/`SERVE_COUNTERS`, `PERF_SNAPSHOT_KEYS`/
  `PERF_EVENT_KEYS`, `TIMELINE_EVENT_KEYS`, `TRACE_ENV_VARS`).
- ``verbs``: the protocol engine's JOURNALED/IDEM sets plus the client
  verb classes recovered from `_call_buffered`/`_call_polling` call
  sites (`agent/master_client.py`).
- ``journal_kinds``: kinds WRITTEN (`self._journal("k", ...)` in the
  servicer, `*.journal.append("k", ...)` in the master,
  `self.append("k", ...)` in journal.py) vs kinds REPLAYED
  (`kind == "k"` comparisons in `_apply_entry` + journal.py's
  ``frame.get("kind") == "k"``).  A written kind with no replay branch
  is `journal-kind-unreplayed` — silent state loss at the next
  failover; a replayed kind removed from the lock is `schema-removed` —
  old journals become undecodable.
- ``snapshot_keys``: `_journal_state()`'s export dict literal vs the
  keys `_restore_snapshot` actually reads — `snapshot-asymmetric`
  (warning) when they drift.

Lockfile lifecycle: additions are legal but require ``--update-lock``
(deterministic sorted-keys JSON, atomic tmp+rename) so the delta is a
reviewed diff; a MISSING lock is the fresh-repo bootstrap (no finding);
a CORRUPT lock re-extracts with `schema-lock-corrupt` (warning), never
fatal; any other drift without ``--update-lock`` is `schema-lock-stale`
(error).  Removal/rename/default-change against the lock are errors —
an old peer or journal can no longer decode.
"""

from __future__ import annotations

import ast
import json
import os
import tempfile
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .findings import Finding, is_suppressed
from .protocol_engine import _dotted, _terminal

SURFACE_SCHEMA_VERSION = 1

#: package-relative source of the @message dataclasses.
MESSAGES_FILE = "common/messages.py"

#: package-relative file -> ADD-ONLY registry tuple names to extract.
REGISTRY_SPECS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("telemetry/ledger.py", ("LEDGER_STATES",)),
    ("telemetry/serving.py", ("SERVE_STATES", "SERVE_COUNTERS")),
    ("telemetry/perf.py", ("PERF_SNAPSHOT_KEYS", "PERF_EVENT_KEYS")),
    ("telemetry/timeline.py", ("TIMELINE_EVENT_KEYS",)),
    ("auto/compile_cache.py", ("TRACE_ENV_VARS",)),
)

#: where the journaled/idem verb-class sets live (set literals).
VERB_SETS_FILE = "analysis/protocol_engine.py"
VERB_SET_NAMES = ("JOURNALED_VERBS", "IDEM_VERBS")

#: the typed client facade — buffered/polling classes recovered from
#: `_call_buffered(msg.X(...), ...)` / `_call_polling(verb, msg.X(...))`.
CLIENT_FILE = "agent/master_client.py"

#: files scanned for journal-kind WRITE sites and REPLAY branches.
JOURNAL_WRITE_FILES = ("master/servicer.py", "master/master.py",
                       "master/journal.py")
JOURNAL_REPLAY_FILES = ("master/master.py", "master/journal.py")

#: the snapshot export/restore pair.
SNAPSHOT_FILE = "master/master.py"
SNAPSHOT_EXPORT_FUNC = "_journal_state"
SNAPSHOT_RESTORE_FUNC = "_restore_snapshot"

LOCK_BASENAME = "schema.lock.json"


def default_pkg_root() -> str:
    """The dlrover_wuqiong_tpu package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_lock_path(pkg_root: Optional[str] = None) -> str:
    root = pkg_root or default_pkg_root()
    return os.path.join(root, "analysis", LOCK_BASENAME)


# ------------------------------------------------------------- extraction


class _Source:
    """One parsed source file: tree + lines + display path."""

    __slots__ = ("rel", "path", "tree", "lines")

    def __init__(self, rel: str, path: str, tree: ast.Module,
                 lines: List[str]):
        self.rel = rel
        self.path = path
        self.tree = tree
        self.lines = lines


def _load_sources(pkg_root: str,
                  rels: Sequence[str]) -> Dict[str, _Source]:
    """Parse the spec'd files that exist; missing files are skipped so
    fixture mini-packages (tests) extract partial surfaces."""
    out: Dict[str, _Source] = {}
    for rel in rels:
        path = os.path.join(pkg_root, rel)
        if not os.path.exists(path):
            continue
        try:
            source = open(path).read()
            tree = ast.parse(source)
        except (OSError, SyntaxError):
            continue
        try:
            disp = os.path.relpath(path)
        except ValueError:  # different drive (windows)
            disp = path
        out[rel] = _Source(rel, disp, tree, source.splitlines())
    return out


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _default_repr(node: Optional[ast.AST]) -> Optional[str]:
    """Canonical string for a field default (None = no default)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant):
        return repr(node.value)
    if isinstance(node, ast.Call) and _terminal(node.func) == "field":
        for kw in node.keywords:
            if kw.arg == "default_factory":
                name = _terminal(kw.value) or ast.unparse(kw.value)
                return f"factory:{name}"
            if kw.arg == "default":
                return _default_repr(kw.value)
        return "field:?"
    return ast.unparse(node)


def _extract_messages(src: _Source,
                      anchors: Dict[Tuple, Tuple[str, int]]) -> Dict:
    """@message dataclasses -> {name: {"fields": [{name, default,
    sentinel}...]}} with declaration order preserved."""
    messages: Dict[str, Dict] = {}
    for node in src.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        if not any(_terminal(d) == "message" for d in node.decorator_list):
            continue
        fields: List[Dict[str, Any]] = []
        for stmt in node.body:
            if not (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                continue
            default = _default_repr(stmt.value)
            fields.append({"name": stmt.target.id, "default": default,
                           "sentinel": default is not None})
            anchors[("field", node.name, stmt.target.id)] = (
                src.rel, stmt.lineno)
        messages[node.name] = {"fields": fields}
        anchors[("message", node.name)] = (src.rel, node.lineno)
    return messages


def _extract_registries(sources: Dict[str, _Source],
                        anchors: Dict[Tuple, Tuple[str, int]]) -> Dict:
    registries: Dict[str, List[str]] = {}
    for rel, names in REGISTRY_SPECS:
        src = sources.get(rel)
        if src is None:
            continue
        for node in src.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if not (isinstance(target, ast.Name)
                        and target.id in names):
                    continue
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    members = [m for m in
                               (_const_str(e) for e in node.value.elts)
                               if m is not None]
                    registries[target.id] = members
                    anchors[("registry", target.id)] = (rel, node.lineno)
    return registries


def _extract_verb_sets(src: Optional[_Source]) -> Dict[str, List[str]]:
    found: Dict[str, List[str]] = {}
    if src is not None:
        for node in src.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if isinstance(target, ast.Name) and \
                        target.id in VERB_SET_NAMES and \
                        isinstance(node.value, (ast.Set, ast.Tuple,
                                                ast.List)):
                    found[target.id] = sorted(
                        m for m in (_const_str(e)
                                    for e in node.value.elts)
                        if m is not None)
    return {"journaled": found.get("JOURNALED_VERBS", []),
            "idem": found.get("IDEM_VERBS", [])}


def _msg_constructors(node: ast.AST) -> List[str]:
    """Message type names constructed under `node` (msg.X(...) calls)."""
    out: List[str] = []
    for child in ast.walk(node):
        if isinstance(child, ast.Call) and \
                isinstance(child.func, ast.Attribute) and \
                isinstance(child.func.value, ast.Name) and \
                child.func.value.id == "msg":
            out.append(child.func.attr)
    return out


def _extract_client_verbs(src: Optional[_Source]) -> Dict[str, List[str]]:
    buffered: set = set()
    polling: set = set()
    if src is not None:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            term = _terminal(node.func)
            if term == "_call_buffered" and node.args:
                buffered.update(_msg_constructors(node.args[0]))
            elif term == "_call_polling" and len(node.args) > 1:
                polling.update(_msg_constructors(node.args[1]))
    return {"buffered": sorted(buffered), "polling": sorted(polling)}


def _extract_journal_kinds(sources: Dict[str, _Source],
                           anchors: Dict[Tuple, Tuple[str, int]]) -> Dict:
    written: Dict[str, None] = {}
    for rel in JOURNAL_WRITE_FILES:
        src = sources.get(rel)
        if src is None:
            continue
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            term = _terminal(node.func)
            dotted = _dotted(node.func) or ""
            kind = _const_str(node.args[0])
            if kind is None:
                continue
            is_write = (term == "_journal"
                        or (term == "append"
                            and ("journal" in dotted
                                 or dotted == "self.append")))
            if is_write:
                written.setdefault(kind)
                anchors.setdefault(("written", kind), (rel, node.lineno))
    replayed: Dict[str, None] = {}
    for rel in JOURNAL_REPLAY_FILES:
        src = sources.get(rel)
        if src is None:
            continue
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Compare)
                    and len(node.ops) == 1
                    and isinstance(node.ops[0], ast.Eq)
                    and len(node.comparators) == 1):
                continue
            for a, b in ((node.left, node.comparators[0]),
                         (node.comparators[0], node.left)):
                if _is_kind_expr(a):
                    kind = _const_str(b)
                    if kind is not None:
                        replayed.setdefault(kind)
                        anchors.setdefault(("replayed", kind),
                                           (rel, node.lineno))
    return {"written": sorted(written), "replayed": sorted(replayed)}


def _is_kind_expr(node: ast.AST) -> bool:
    """`kind` name or `<x>.get("kind")` — a replay-dispatch discriminant."""
    if isinstance(node, ast.Name) and node.id == "kind":
        return True
    return (isinstance(node, ast.Call)
            and _terminal(node.func) == "get"
            and bool(node.args)
            and _const_str(node.args[0]) == "kind")


def _extract_snapshot_keys(src: Optional[_Source],
                           anchors: Dict[Tuple, Tuple[str, int]]) -> Dict:
    exported: List[str] = []
    restored: Dict[str, None] = {}
    if src is not None:
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name == SNAPSHOT_EXPORT_FUNC:
                anchors[("exported",)] = (src.rel, node.lineno)
                for child in ast.walk(node):
                    if isinstance(child, ast.Return) and \
                            isinstance(child.value, ast.Dict):
                        for k in child.value.keys:
                            key = _const_str(k) if k is not None else None
                            if key is not None and key not in exported:
                                exported.append(key)
            elif node.name == SNAPSHOT_RESTORE_FUNC:
                anchors[("restored",)] = (src.rel, node.lineno)
                state_arg = ""
                args = node.args.args
                if len(args) > 1:
                    state_arg = args[1].arg   # (self, state)
                elif args:
                    state_arg = args[0].arg
                for child in ast.walk(node):
                    key = None
                    if isinstance(child, ast.Call) and \
                            _terminal(child.func) == "get" and \
                            isinstance(child.func, ast.Attribute) and \
                            isinstance(child.func.value, ast.Name) and \
                            child.func.value.id == state_arg and \
                            child.args:
                        key = _const_str(child.args[0])
                    elif isinstance(child, ast.Subscript) and \
                            isinstance(child.value, ast.Name) and \
                            child.value.id == state_arg:
                        key = _const_str(child.slice)
                    if key is not None:
                        restored.setdefault(key)
    return {"exported": exported, "restored": sorted(restored)}


def extract_surface(pkg_root: Optional[str] = None
                    ) -> Tuple[Dict, Dict[Tuple, Tuple[str, int]],
                               Dict[str, _Source]]:
    """(surface, anchors, sources) — the canonical wire projection plus
    file:line anchors for findings and parsed sources for suppression
    checks."""
    root = pkg_root or default_pkg_root()
    rels = ([MESSAGES_FILE, VERB_SETS_FILE, CLIENT_FILE, SNAPSHOT_FILE]
            + [rel for rel, _ in REGISTRY_SPECS]
            + list(JOURNAL_WRITE_FILES) + list(JOURNAL_REPLAY_FILES))
    sources = _load_sources(root, sorted(set(rels)))
    anchors: Dict[Tuple, Tuple[str, int]] = {}
    msgs_src = sources.get(MESSAGES_FILE)
    surface = {
        "schema": SURFACE_SCHEMA_VERSION,
        "messages": (_extract_messages(msgs_src, anchors)
                     if msgs_src else {}),
        "registries": _extract_registries(sources, anchors),
        "verbs": {**_extract_verb_sets(sources.get(VERB_SETS_FILE)),
                  **_extract_client_verbs(sources.get(CLIENT_FILE))},
        "journal_kinds": _extract_journal_kinds(sources, anchors),
        "snapshot_keys": _extract_snapshot_keys(
            sources.get(SNAPSHOT_FILE), anchors),
    }
    return surface, anchors, sources


# --------------------------------------------------------------- lockfile


def canonical_json(surface: Dict) -> str:
    """Deterministic lock serialization: sorted keys, stable indent,
    trailing newline — `--update-lock` is byte-identical on a clean
    tree."""
    return json.dumps(surface, sort_keys=True, indent=2) + "\n"


def load_lock(path: str) -> Tuple[Optional[Dict], str]:
    """(lock, status): status is "ok" | "missing" | "corrupt"."""
    if not os.path.exists(path):
        return None, "missing"
    try:
        with open(path) as f:
            lock = json.load(f)
        if not isinstance(lock, dict):
            return None, "corrupt"
        return lock, "ok"
    except (OSError, ValueError):
        return None, "corrupt"


def write_lock(path: str, surface: Dict) -> None:
    """Atomic tmp+rename publish (the commit-file discipline — a torn
    lockfile would read as corrupt and silently skip the diff)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=".schema.lock.", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(canonical_json(surface))
            f.flush()
            os.fsync(f.fileno())
        os.chmod(tmp, 0o644)  # mkstemp's 0600 is wrong for a committed file
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


# ------------------------------------------------------------------ rules


def _anchored(findings: List[Finding], sources: Dict[str, _Source],
              anchors: Dict[Tuple, Tuple[str, int]], key: Tuple,
              checker: str, message: str,
              fallback: Tuple[str, int] = ("", 0)) -> None:
    """Append a finding at its anchor unless an inline disable covers
    that line (the v2 suppression grammar applies to every engine)."""
    rel, line = anchors.get(key, fallback)
    src = sources.get(rel)
    path = src.path if src else rel
    if src is not None and line and is_suppressed(src.lines, line,
                                                  checker):
        return
    findings.append(Finding(checker, message, path, line))


def check_internal(surface: Dict,
                   anchors: Dict[Tuple, Tuple[str, int]],
                   sources: Dict[str, _Source]) -> List[Finding]:
    """Lock-independent consistency rules over the live surface."""
    findings: List[Finding] = []
    for name, spec in surface["messages"].items():
        for f in spec["fields"]:
            if not f["sentinel"]:
                _anchored(
                    findings, sources, anchors,
                    ("field", name, f["name"]), "schema-field-no-sentinel",
                    f"message field {name}.{f['name']} has no default — "
                    f"the codec drops unknown fields on decode, so a "
                    f"sentinel-less field breaks mixed-generation decode "
                    f"(give it a no-change default like 0/-1/'')")
    kinds = surface["journal_kinds"]
    for kind in kinds["written"]:
        if kind not in kinds["replayed"]:
            _anchored(
                findings, sources, anchors, ("written", kind),
                "journal-kind-unreplayed",
                f"journal kind {kind!r} is written but has no replay "
                f"branch in _apply_entry — every frame of it is silent "
                f"state loss at the next master failover")
    snap = surface["snapshot_keys"]
    for key in snap["exported"]:
        if key not in snap["restored"]:
            _anchored(
                findings, sources, anchors, ("exported",),
                "snapshot-asymmetric",
                f"snapshot key {key!r} is exported by "
                f"{SNAPSHOT_EXPORT_FUNC} but never read by "
                f"{SNAPSHOT_RESTORE_FUNC} — the state it carries "
                f"silently vanishes on restore")
    for key in snap["restored"]:
        if key not in snap["exported"]:
            _anchored(
                findings, sources, anchors, ("restored",),
                "snapshot-asymmetric",
                f"snapshot key {key!r} is read by "
                f"{SNAPSHOT_RESTORE_FUNC} but never exported by "
                f"{SNAPSHOT_EXPORT_FUNC} — the restore branch is dead "
                f"code (or the export was dropped)")
    return findings


def _diff_ordered(findings: List[Finding], sources: Dict[str, _Source],
                  anchors: Dict[Tuple, Tuple[str, int]],
                  anchor_key: Tuple, what: str,
                  locked: Sequence[str], live: Sequence[str]) -> None:
    """Removal/rename findings for an ordered name list (registry
    members, message field names).  A locked name missing from the live
    list whose ordinal slot now holds a NEW name is a rename; otherwise
    a removal."""
    live_set = set(live)
    locked_set = set(locked)
    for i, name in enumerate(locked):
        if name in live_set:
            continue
        if i < len(live) and live[i] not in locked_set:
            _anchored(
                findings, sources, anchors, anchor_key, "schema-renamed",
                f"{what} {name!r} was renamed to {live[i]!r} — old peers "
                f"and journals still send/hold the old name; add the new "
                f"name alongside instead (ADD-ONLY)")
        else:
            _anchored(
                findings, sources, anchors, anchor_key, "schema-removed",
                f"{what} {name!r} was removed — an old-generation peer "
                f"or journal that carries it can no longer decode "
                f"(ADD-ONLY: removals are never legal)")


def diff_lock(surface: Dict, lock: Dict,
              anchors: Dict[Tuple, Tuple[str, int]],
              sources: Dict[str, _Source],
              lock_display: str) -> List[Finding]:
    """Compatibility diff: lock (old generation) vs surface (this tree)."""
    findings: List[Finding] = []
    live_msgs = surface["messages"]
    for name, locked_spec in (lock.get("messages") or {}).items():
        if name not in live_msgs:
            _anchored(
                findings, sources, anchors, ("message", name),
                "schema-removed",
                f"wire message {name} was removed — old peers still "
                f"send it and old journals still hold it",
                fallback=(MESSAGES_FILE, 0))
            continue
        locked_fields = locked_spec.get("fields") or []
        live_fields = live_msgs[name]["fields"]
        _diff_ordered(findings, sources, anchors, ("message", name),
                      f"{name} field", [f["name"] for f in locked_fields],
                      [f["name"] for f in live_fields])
        live_by_name = {f["name"]: f for f in live_fields}
        for lf in locked_fields:
            cur = live_by_name.get(lf["name"])
            if cur is None or not cur["sentinel"]:
                continue  # removal/rename or no-sentinel already fired
            if lf.get("sentinel") and lf.get("default") != cur["default"]:
                _anchored(
                    findings, sources, anchors,
                    ("field", name, lf["name"]), "schema-default-changed",
                    f"default of {name}.{lf['name']} changed "
                    f"{lf.get('default')} -> {cur['default']} — frames "
                    f"from old peers omit the field and now decode to a "
                    f"DIFFERENT value than they meant")
    live_regs = surface["registries"]
    for reg, locked_members in (lock.get("registries") or {}).items():
        if reg not in live_regs:
            _anchored(findings, sources, anchors, ("registry", reg),
                      "schema-removed",
                      f"ADD-ONLY registry {reg} was removed entirely",
                      fallback=("", 0))
            continue
        _diff_ordered(findings, sources, anchors, ("registry", reg),
                      f"{reg} member", locked_members, live_regs[reg])
    live_verbs = surface["verbs"]
    for cls, locked_members in (lock.get("verbs") or {}).items():
        live = live_verbs.get(cls, [])
        for verb in locked_members:
            if verb not in live:
                _anchored(
                    findings, sources, anchors, ("verb", cls, verb),
                    "schema-removed",
                    f"verb {verb} left the {cls!r} class — its durability"
                    f"/retry contract (journaling, idem keys, buffering) "
                    f"changed under old peers",
                    fallback=(VERB_SETS_FILE
                              if cls in ("journaled", "idem")
                              else CLIENT_FILE, 0))
    live_replayed = surface["journal_kinds"]["replayed"]
    for kind in (lock.get("journal_kinds") or {}).get("replayed", []):
        if kind not in live_replayed:
            _anchored(
                findings, sources, anchors, ("replayed", kind),
                "schema-removed",
                f"journal kind {kind!r} lost its replay branch — "
                f"existing journals hold frames of it that a new master "
                f"can no longer apply",
                fallback=(SNAPSHOT_FILE, 0))
    live_restored = surface["snapshot_keys"]["restored"]
    for key in (lock.get("snapshot_keys") or {}).get("restored", []):
        if key not in live_restored:
            _anchored(
                findings, sources, anchors, ("restored",),
                "schema-removed",
                f"snapshot key {key!r} lost its restore branch — "
                f"existing journal snapshots carry state a new master "
                f"silently drops",
                fallback=(SNAPSHOT_FILE, 0))
    if canonical_json(surface) != canonical_json(lock):
        findings.append(Finding(
            "schema-lock-stale",
            f"extracted wire surface differs from {lock_display} — "
            f"additions are legal but must be locked in the same PR: "
            f"run `python -m dlrover_wuqiong_tpu.analysis --engine "
            f"schema --update-lock` and commit the lockfile diff",
            lock_display, 0))
    return findings


# ------------------------------------------------------------ entry point


def surface_counts(surface: Dict) -> Dict:
    """Add-only summary block for the CLI JSON line."""
    return {
        "messages": len(surface["messages"]),
        "fields": sum(len(m["fields"])
                      for m in surface["messages"].values()),
        "registries": len(surface["registries"]),
        "registry_members": sum(len(v)
                                for v in surface["registries"].values()),
        "verbs": {cls: len(v) for cls, v in surface["verbs"].items()},
        "journal_kinds_written": len(surface["journal_kinds"]["written"]),
        "journal_kinds_replayed": len(
            surface["journal_kinds"]["replayed"]),
        "snapshot_exported": len(surface["snapshot_keys"]["exported"]),
        "snapshot_restored": len(surface["snapshot_keys"]["restored"]),
    }


def run_schema(pkg_root: Optional[str] = None,
               update_lock: bool = False,
               lock_path: Optional[str] = None
               ) -> Tuple[List[Finding], Dict]:
    """Run the schema engine; (findings, summary).

    summary = {"surface": <counts>, "lock": "ok" | "missing" |
    "corrupt" | "stale" | "updated"} — rides the CLI JSON line's
    add-only ``schema`` section.
    """
    root = pkg_root or default_pkg_root()
    path = lock_path or default_lock_path(root)
    surface, anchors, sources = extract_surface(root)
    findings = check_internal(surface, anchors, sources)
    try:
        lock_display = os.path.relpath(path)
    except ValueError:
        lock_display = path
    if update_lock:
        # regenerate instead of diffing: the delta becomes the lockfile's
        # own git diff (reviewed), and internal-consistency errors above
        # still gate — --update-lock never launders a broken surface.
        write_lock(path, surface)
        return findings, {"surface": surface_counts(surface),
                          "lock": "updated"}
    lock, status = load_lock(path)
    if status == "corrupt":
        findings.append(Finding(
            "schema-lock-corrupt",
            f"{lock_display} is unreadable — diff skipped this run "
            f"(re-extracted surface stands alone); regenerate with "
            f"--update-lock",
            lock_display, 0))
    elif status == "ok" and lock is not None:
        diff = diff_lock(surface, lock, anchors, sources, lock_display)
        if diff:
            status = "stale"
            findings.extend(diff)
    # status "missing" is the fresh-repo bootstrap: no finding — the
    # first --update-lock commit creates the contract.
    return findings, {"surface": surface_counts(surface), "lock": status}

"""graftlint Engine B — Python-AST checks over the package and tests.

Parity: reference `dlrover/python/diagnosis/inferencechain/` precheck
operators (node_check.py:1, error_monitor.py:1 run AFTER a failure);
redesign: the four costliest TPU bug classes in this codebase are visible
in the source text, so they are enforced BEFORE a chip is touched:

- ``env-at-trace``    — a ``DWT_*`` env read inside a function of a
  compute-path module changes the emitted HLO at TRACE time; any such
  toggle must be folded into the framework cache key
  (auto/compile_cache.py:52 ``TRACE_ENV_VARS``), else two processes with
  different values claim each other's warm entries (CLAUDE.md).
- ``donated-reuse``   — ``train_step`` / ``apply_sparse_update`` DONATE
  their state inputs; code that reads the same variable after passing it
  in observes a dead buffer (CLAUDE.md: copy first in tests).
- ``control-plane-hygiene`` — the agent↔master frame path
  (common/comm.py, messages.py, serialize.py) is typed JSON, never
  pickle; and JAX-initialized processes must spawn, never fork
  (data/shm_loader.py:127).
- ``docstring-citation`` — every package module docstring cites the
  reference files it matches (``file:line``) or carries a ``Parity:``
  note, the repo's documented convention.
- ``blocking-readback`` — an UNCONDITIONAL ``float(...)`` /
  ``np.asarray(...)`` / ``device_get`` on a train-step output inside a
  training loop forces one host sync PER STEP; over the axon tunnel
  each sync is a full round trip, and it defeats the fused K-step
  driver's one-readback-per-fusion contract (CLAUDE.md dispatch
  amortization; trainer/train_step.py).  Cadence-gated readbacks
  (under an ``if`` — e.g. logging every N steps) are fine.
- ``unverified-restore`` — raw checkpoint bytes (shm ``load_state_dict``
  / ``iter_shards``, shard-file ``np.frombuffer``) feeding a restore
  sink (``restore_pytree`` / ``jax.device_put``) in a function that
  never calls the verification API (checkpoint/integrity.py): the
  checkpoint trust boundary digests every shard at save, and a decode
  path that skips the check hands a flipped bit straight to the device.
- ``raw-rpc-call``     — a control-plane socket dial
  (``socket.create_connection``, ``*sock*.connect``) or frame-level IO
  (``_send_frame``/``_recv_frame``) outside the retry wrapper: every
  such invocation must run inside a function that routes through
  ``retry_call`` (common/util.py) or live in common/comm.py itself —
  the one place the policy is implemented.  A bare dial raises on the
  first ConnectionError, which is exactly how the control plane used
  to die with the master (ISSUE 4); the shared policy gives bounded
  exponential backoff + reconnect everywhere.

This module is import-light on purpose: NO jax, NO package siblings —
``__graft_entry__.py`` runs it as a pre-flight gate before any backend
initialization.  Suppressions: a line containing ``graftlint:
disable=<checker>`` silences that checker for that line (the in-tree
self-lint must pass with suppressions reserved for intentional,
documented cases — e.g. bench.py's measured per-step driver, whose
whole point is the per-step sync the rule exists to catch).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding

# package subtrees whose functions run under jit/trace: an env read there
# is a trace-time input (ops/flash_attention.py kernel picks are the
# canonical case).  trainer/ is split: train_step.py is traced, the
# Trainer loop around it is host-side orchestration (reads DWT_JOB_NAME
# etc. legitimately).
COMPUTE_DIRS = ("ops", "models", "parallel", "optimizers", "embedding")
COMPUTE_FILES = ("trainer/train_step.py",)

# control-plane modules whose wire format must stay typed JSON
FRAME_MODULES = ("comm.py", "messages.py", "serialize.py")

# callee name -> (donated positional indices, donated keyword names);
# positions follow the public signatures (trainer/train_step.py:84,
# embedding/sparse_optim.py:133)
DONATING_CALLS: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {
    "train_step": ((0,), ("state",)),
    "apply_sparse_update": ((1, 2), ("table", "state")),
}

_CITE_RE = re.compile(r"[\w/\.-]+\.(?:py|cc|h|proto|md):\d+|\bparity\b",
                      re.IGNORECASE)
_ENV_PREFIX = "DWT_"

# v2 suppression grammar lives in findings.py (shared with the protocol
# engine); reason-less disables are themselves findings — see
# check_suppression_reasons, run once per file below.
from .findings import is_suppressed as _suppressed  # noqa: E402


def _dotted(node: ast.AST) -> Optional[str]:
    """'res.state' for simple Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _env_var_read(node: ast.Call) -> Optional[str]:
    """The env-var name when `node` reads one via os.getenv / environ.get."""
    func = node.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else "")
    if name == "getenv" or (
            name == "get" and isinstance(func, ast.Attribute)
            and _dotted(func.value) in ("os.environ", "environ")):
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            return node.args[0].value
    return None


def _env_var_subscript(node: ast.Subscript) -> Optional[str]:
    if _dotted(node.value) in ("os.environ", "environ") and \
            isinstance(node.slice, ast.Constant) and \
            isinstance(node.slice.value, str):
        return node.slice.value
    return None


def trace_env_key_vars(package_roots: Iterable[str]) -> Optional[Set[str]]:
    """Parse TRACE_ENV_VARS out of auto/compile_cache.py (AST, no import).

    Looks under each scanned root, then next to this file's own package —
    so fixtures can ship their own key-builder and the in-repo scan always
    finds the real one.
    """
    candidates = [os.path.join(r, "auto", "compile_cache.py")
                  for r in package_roots]
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    candidates.append(os.path.join(here, "auto", "compile_cache.py"))
    for path in candidates:
        if not os.path.isfile(path):
            continue
        try:
            tree = ast.parse(open(path).read())
        except (OSError, SyntaxError):
            continue
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "TRACE_ENV_VARS"
                    for t in node.targets):
                if isinstance(node.value, (ast.Tuple, ast.List, ast.Set)):
                    return {e.value for e in node.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)}
    return None


# --------------------------------------------------------- env-at-trace


def check_env_at_trace(path: str, tree: ast.Module,
                       source_lines: Sequence[str],
                       key_vars: Set[str]) -> List[Finding]:
    """DWT_* env reads inside functions of a compute-path module must be
    in the compile-cache key set — they are trace-time HLO inputs."""
    posix = path.replace(os.sep, "/")
    parts = posix.split("/")
    in_compute = (any(d in parts[:-1] for d in COMPUTE_DIRS)
                  or any(posix.endswith(f) for f in COMPUTE_FILES))
    if not in_compute or "tests" in parts:
        return []
    findings: List[Finding] = []

    def visit(node: ast.AST, in_func: bool) -> None:
        for child in ast.iter_child_nodes(node):
            child_in_func = in_func or isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef))
            var = None
            if isinstance(child, ast.Call):
                var = _env_var_read(child)
            elif isinstance(child, ast.Subscript):
                var = _env_var_subscript(child)
            if var and var.startswith(_ENV_PREFIX) and child_in_func \
                    and var not in key_vars \
                    and not _suppressed(source_lines, child.lineno,
                                        "env-at-trace"):
                findings.append(Finding(
                    "env-at-trace",
                    f"{var} read inside a compute-path function but absent "
                    f"from TRACE_ENV_VARS (auto/compile_cache.py) — two "
                    f"processes with different values would share one "
                    f"framework cache key over different HLO",
                    path, child.lineno,
                    rule="trace-time env toggles must be in the compile "
                         "cache key"))
            visit(child, child_in_func)

    visit(tree, in_func=False)
    return findings


# ---------------------------------------------- env-flip-outside-tuner

#: the ONLY files allowed to write TRACE_ENV_VARS names into os.environ —
#: the variant autotuner's sanctioned writer (_set_trace_env /
#: variant_env / apply_variant own save-restore and the compile-cache
#: re-key discipline).
TUNER_FILES = ("auto/tuner.py",)


def check_env_flip_outside_tuner(path: str, tree: ast.Module,
                                 source_lines: Sequence[str],
                                 key_vars: Set[str]) -> List[Finding]:
    """Raw os.environ WRITES of TRACE_ENV_VARS names outside the tuner.

    A DWT_FA_* value is part of the executable identity (it rides the
    compile-cache key and the perf-observatory executable key): a raw
    ``os.environ[...] = ...`` / ``.pop`` / ``.setdefault`` / ``del``
    outside auto/tuner.py flips the trace env without the save-restore,
    validation and re-key bookkeeping the sanctioned writer provides —
    the fused cache and warm pool then disagree with the process env.
    Route every flip through ``variant_env`` (scoped) or
    ``apply_variant`` (cutover).  Tests are exempt (they pin behavior
    under both values).
    """
    posix = path.replace(os.sep, "/")
    parts = posix.split("/")
    if "tests" in parts or parts[-1].startswith("test_"):
        return []
    if any(posix.endswith(f) for f in TUNER_FILES):
        return []
    if not key_vars:
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        var, how = None, ""
        if isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            v = _env_var_subscript(node)
            if v in key_vars:
                var = v
                how = ("del os.environ[...]"
                       if isinstance(node.ctx, ast.Del)
                       else "os.environ[...] = ...")
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and \
                    _dotted(func.value) in ("os.environ", "environ") and \
                    func.attr in ("pop", "setdefault"):
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and node.args[0].value in key_vars:
                    var = node.args[0].value
                    how = f"os.environ.{func.attr}(...)"
        if var and not _suppressed(source_lines, node.lineno,
                                   "env-flip-outside-tuner"):
            findings.append(Finding(
                "env-flip-outside-tuner",
                f"{how} writes trace-time toggle {var} outside the "
                f"variant autotuner — raw flips skip save-restore and "
                f"the compile-cache re-key; use auto/tuner.py "
                f"variant_env (scoped) or apply_variant (cutover)",
                path, node.lineno,
                rule="the tuner owns TRACE_ENV_VARS writes"))
    return findings


# -------------------------------------------------------- donated-reuse


class _Scope:
    """Per-function bookkeeping for the donated-reuse dataflow."""

    def __init__(self) -> None:
        self.stores: Dict[str, List[int]] = {}   # root name -> linenos
        self.loads: Dict[str, List[int]] = {}    # dotted path -> linenos


def _collect_scope(fn: ast.AST) -> Tuple[_Scope, List[Tuple[ast.Call, str,
                                                            List[ast.AST]]]]:
    scope = _Scope()
    donating: List[Tuple[ast.Call, str, List[ast.AST]]] = []

    def record_store(name: str, line: int) -> None:
        scope.stores.setdefault(name, []).append(line)

    def visit(node: ast.AST, loops: List[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and child is not fn:
                continue  # nested scopes tracked separately
            if isinstance(child, ast.Name):
                if isinstance(child.ctx, (ast.Store, ast.Del)):
                    record_store(child.id, child.lineno)
                else:
                    scope.loads.setdefault(child.id, []).append(child.lineno)
            elif isinstance(child, ast.Attribute):
                dotted = _dotted(child)
                if dotted and "." in dotted:
                    if isinstance(child.ctx, (ast.Store, ast.Del)):
                        # `self.state, m = ...` rebinds the attribute: a
                        # kill for the dotted path (but not its root)
                        record_store(dotted, child.lineno)
                    else:
                        scope.loads.setdefault(dotted,
                                               []).append(child.lineno)
            elif isinstance(child, ast.Call):
                func = child.func
                callee = func.attr if isinstance(func, ast.Attribute) else (
                    func.id if isinstance(func, ast.Name) else "")
                if callee in DONATING_CALLS:
                    donating.append((child, callee, list(loops)))
            child_loops = loops + [child] if isinstance(
                child, (ast.For, ast.While, ast.AsyncFor)) else loops
            visit(child, child_loops)

    visit(fn, [])
    return scope, donating


def _donated_args(call: ast.Call, callee: str) -> List[ast.AST]:
    pos, kw = DONATING_CALLS[callee]
    out = [call.args[i] for i in pos if i < len(call.args)]
    out += [k.value for k in call.keywords if k.arg in kw]
    return out


def check_donated_reuse(path: str, tree: ast.Module,
                        source_lines: Sequence[str]) -> List[Finding]:
    """A variable passed to a donating jit must not be read afterwards."""
    findings: List[Finding] = []
    # the module body is a scope too — example scripts donate at top level
    fns: List[ast.AST] = [tree]
    fns += [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in fns:
        scope, donating = _collect_scope(fn)
        for call, callee, loops in donating:
            if _suppressed(source_lines, call.lineno, "donated-reuse"):
                continue
            for arg in _donated_args(call, callee):
                dotted = _dotted(arg)
                if dotted is None:
                    continue  # fresh expression (jnp.copy(x), literal, ...)
                root = dotted.split(".")[0]
                kill_lines = scope.stores.get(root, []) + \
                    scope.stores.get(dotted, [])
                call_end = getattr(call, "end_lineno", call.lineno) \
                    or call.lineno
                # (a) read after the donating call with no reassignment
                for load_line in scope.loads.get(dotted, []):
                    if load_line <= call_end:
                        continue
                    if any(call.lineno <= k <= load_line
                           for k in kill_lines):
                        continue
                    if _suppressed(source_lines, load_line,
                                   "donated-reuse"):
                        continue
                    findings.append(Finding(
                        "donated-reuse",
                        f"`{dotted}` is read at line {load_line} after "
                        f"being DONATED to {callee}() — the buffer is dead"
                        f"; copy first (jnp.copy) or rebind the name",
                        path, load_line,
                        rule="train_step/apply_sparse_update donate their "
                             "inputs"))
                    break  # one finding per donated arg is enough
                # (b) re-donated on the next loop iteration unchanged
                if loops:
                    loop = loops[-1]
                    end = max((getattr(n, "lineno", loop.lineno)
                               for n in ast.walk(loop)),
                              default=loop.lineno)
                    if not any(loop.lineno <= k <= end for k in kill_lines):
                        findings.append(Finding(
                            "donated-reuse",
                            f"`{dotted}` is donated to {callee}() inside a "
                            f"loop but never reassigned in the loop body — "
                            f"the next iteration passes a dead buffer",
                            path, call.lineno,
                            rule="train_step/apply_sparse_update donate "
                                 "their inputs"))
    return findings


# ----------------------------------------------- blocking-readback

# callee names that advance the training hot loop; assignments fed by a
# call to one of these mark their targets as step outputs (device values)
STEP_ADVANCING_CALLS = ("train_step", "fused_train_step")
# callee names that force a blocking host readback of their argument
READBACK_CALLS = ("float", "asarray", "device_get")


def _terminal_callee(func: ast.AST) -> str:
    """Terminal name of a call target, through immediately-invoked
    factories: `res.train_step(...)`, `res.fused_train_step(k)(...)`."""
    if isinstance(func, ast.Call):  # factory(...)(args) — look inside
        return _terminal_callee(func.func)
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _assign_targets(node: ast.AST) -> List[str]:
    """Dotted/plain names stored by an assignment target tree."""
    out: List[str] = []
    for t in ast.walk(node):
        if isinstance(t, ast.Name) and isinstance(t.ctx, ast.Store):
            out.append(t.id)
        elif isinstance(t, ast.Attribute) and isinstance(t.ctx, ast.Store):
            dotted = _dotted(t)
            if dotted:
                out.append(dotted)
    return out


def _reads_step_output(expr: ast.AST, outputs: Set[str]) -> bool:
    plain = {o for o in outputs if "." not in o}
    dotted = {o for o in outputs if "." in o}
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in plain:
            return True
        if isinstance(n, ast.Attribute):
            d = _dotted(n)
            if d and any(d == o or d.startswith(o + ".") for o in dotted):
                return True
    return False


def check_blocking_readback(path: str, tree: ast.Module,
                            source_lines: Sequence[str]) -> List[Finding]:
    """Unconditional host readbacks of step outputs inside a train loop.

    A loop qualifies when its body calls a step-advancing function
    (STEP_ADVANCING_CALLS).  A readback qualifies when it executes on
    EVERY iteration — i.e. not nested under an ``if`` within the loop
    (cadence-gated logging is the sanctioned pattern) — and its argument
    derives from a variable assigned from the step call.  Tests are
    exempt: convergence tests read the loss back per step on purpose.
    """
    parts = path.replace(os.sep, "/").split("/")
    if "tests" in parts or parts[-1].startswith("test_"):
        return []
    findings: List[Finding] = []

    loops = [n for n in ast.walk(tree)
             if isinstance(n, (ast.For, ast.While, ast.AsyncFor))]
    for loop in loops:
        # collect step-output names assigned anywhere in this loop body
        outputs: Set[str] = set()
        step_callee = ""
        for n in ast.walk(loop):
            if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)) \
                    and n.value is not None:
                calls = [c for c in ast.walk(n.value)
                         if isinstance(c, ast.Call)
                         and _terminal_callee(c.func)
                         in STEP_ADVANCING_CALLS]
                if calls:
                    step_callee = _terminal_callee(calls[0].func)
                    targets = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    for t in targets:
                        outputs.update(_assign_targets(t))
        if not outputs:
            continue

        # walk the loop body tracking conditional nesting; stop at nested
        # loops' own step calls (they get their own pass) is unnecessary —
        # an inner loop's unconditional readback is still per-step
        def visit(node: ast.AST, conditional: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # deferred execution: not per-iteration
                child_cond = conditional or isinstance(
                    child, (ast.If, ast.IfExp, ast.Try, ast.ExceptHandler))
                if isinstance(child, ast.Call) and not child_cond:
                    callee = _terminal_callee(child.func)
                    if callee in READBACK_CALLS and child.args and \
                            _reads_step_output(child.args[0], outputs) and \
                            not _suppressed(source_lines, child.lineno,
                                            "blocking-readback"):
                        findings.append(Finding(
                            "blocking-readback",
                            f"`{callee}(...)` on a {step_callee}() output "
                            f"runs UNCONDITIONALLY inside the training "
                            f"loop — one blocking host sync per step "
                            f"(a full round trip over the axon tunnel); "
                            f"gate it on a cadence or read back once per "
                            f"fused block",
                            path, child.lineno,
                            rule="no per-step host readbacks on the "
                                 "training hot path"))
                visit(child, child_cond)

        visit(loop, conditional=False)
    return findings


# --------------------------------------------------------- raw-rpc-call

# the module that IS the retry wrapper — raw socket IO is its job
RPC_WRAPPER_FILES = ("common/comm.py",)
# frame-level helpers that imply hand-rolled RPC when called elsewhere
FRAME_IO_CALLS = ("_send_frame", "_recv_frame")


def _function_spans(tree: ast.Module):
    """[(start, end, contains_retry_call)] for every function in the file."""
    spans = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        end = max((getattr(n, "end_lineno", None) or
                   getattr(n, "lineno", fn.lineno)
                   for n in ast.walk(fn)), default=fn.lineno)
        has_retry = any(
            isinstance(n, ast.Call)
            and _terminal_callee(n.func) == "retry_call"
            for n in ast.walk(fn))
        spans.append((fn.lineno, end, has_retry))
    return spans


def check_raw_rpc_call(path: str, tree: ast.Module,
                       source_lines: Sequence[str]) -> List[Finding]:
    """Socket dials / frame IO outside the shared retry wrapper.

    A call site is sanctioned when ANY enclosing function also routes
    through ``retry_call`` (the dial being the retried attempt — the
    multi_process IPC client and the checkpoint-replica fetch are the
    in-tree shapes), or when the file is common/comm.py.  Tests are
    exempt: fault-injection tests open raw sockets on purpose.
    """
    posix = path.replace(os.sep, "/")
    parts = posix.split("/")
    if "tests" in parts or parts[-1].startswith("test_"):
        return []
    if any(posix.endswith(f) for f in RPC_WRAPPER_FILES):
        return []
    findings: List[Finding] = []
    spans = _function_spans(tree)

    def sanctioned(line: int) -> bool:
        return any(s <= line <= e and has_retry
                   for s, e, has_retry in spans)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        dotted = _dotted(func) or ""
        callee = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        is_dial = (dotted in ("socket.create_connection",
                              "create_connection")
                   or (callee == "connect" and isinstance(
                       func, ast.Attribute)
                       and "sock" in (_dotted(func.value) or "").lower()))
        is_frame_io = callee in FRAME_IO_CALLS
        if not (is_dial or is_frame_io):
            continue
        line = node.lineno
        if sanctioned(line) or _suppressed(source_lines, line,
                                           "raw-rpc-call"):
            continue
        what = ("frame-level RPC IO" if is_frame_io
                else "control-plane socket dial")
        findings.append(Finding(
            "raw-rpc-call",
            f"{what} `{dotted or callee}(...)` outside the shared retry "
            f"wrapper — route the attempt through retry_call "
            f"(common/util.py) so it gets bounded backoff + reconnect "
            f"instead of dying on the first ConnectionError",
            path, line,
            rule="control-plane sockets go through retry_call"))
    return findings


# --------------------------------------------------- unverified-restore

# device-bound restore sinks: these hand bytes to the accelerator (or to
# the pytree rebuild that feeds device_put)
RESTORE_SINKS = ("restore_pytree", "device_put")
# raw checkpoint byte sources: shm segment reads and shard-file decodes —
# bytes from storage/shm/replica that carry digests which MUST be checked
RAW_RESTORE_SOURCES = ("load_state_dict", "iter_shards", "frombuffer")
# the verification API (checkpoint/integrity.py + the engine's verified
# readers): any of these in the same function sanctions the flow
RESTORE_VERIFY_CALLS = (
    "verify", "verify_segment_entries", "verify_segment_blob",
    "verify_rank_bytes", "verify_meta_bytes", "verify_storage_step",
    "_load_verified_shm", "_read_verified_step",
)


def check_unverified_restore(path: str, tree: ast.Module,
                             source_lines: Sequence[str]) -> List[Finding]:
    """Raw checkpoint bytes reaching a restore sink without verification.

    The checkpoint trust boundary (checkpoint/integrity.py) digests every
    shard at save; a code path that reads raw bytes (shm
    ``load_state_dict``/``iter_shards``, shard-file ``np.frombuffer``)
    AND feeds a restore sink (``restore_pytree``/``jax.device_put``) in
    the same function, without calling the verification API, would hand
    a flipped bit or torn persist straight to the device — exactly the
    silent-restore class the boundary exists to kill.  The sanctioned
    shape is the engine's: verify in the same function that decodes
    (``_read_verified_step``), or go through ``engine.load`` which does.
    Tests are exempt (fault-injection tests read raw bytes on purpose).
    """
    parts = path.replace(os.sep, "/").split("/")
    if "tests" in parts or parts[-1].startswith("test_"):
        return []
    findings: List[Finding] = []

    def scope_calls(fn: ast.AST) -> List[ast.Call]:
        """Calls lexically in `fn`'s own scope (nested defs excluded —
        they are separate scopes walked on their own)."""
        out: List[ast.Call] = []

        def visit(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                if isinstance(child, ast.Call):
                    out.append(child)
                visit(child)

        visit(fn)
        return out

    fns: List[ast.AST] = [tree]
    fns += [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in fns:
        sinks: List[ast.Call] = []
        has_source = has_verify = False
        for node in scope_calls(fn):
            callee = _terminal_callee(node.func)
            if callee in RESTORE_SINKS:
                sinks.append(node)
            elif callee in RAW_RESTORE_SOURCES:
                has_source = True
            elif callee in RESTORE_VERIFY_CALLS:
                has_verify = True
        if not (sinks and has_source) or has_verify:
            continue
        for call in sinks:
            if _suppressed(source_lines, call.lineno,
                           "unverified-restore"):
                continue
            callee = _terminal_callee(call.func)
            findings.append(Finding(
                "unverified-restore",
                f"`{callee}(...)` in a function that also decodes raw "
                f"checkpoint bytes "
                f"({'/'.join(RAW_RESTORE_SOURCES)}) with no call into "
                f"the verification API (checkpoint/integrity.py) — a "
                f"flipped bit or torn persist would reach the device "
                f"silently; verify digests first or route through "
                f"engine.load",
                path, call.lineno,
                rule="checkpoint bytes are verified before device_put"))
    return findings


# ----------------------------------------------- control-plane-hygiene


def check_control_plane_hygiene(path: str, tree: ast.Module,
                                source_lines: Sequence[str]
                                ) -> List[Finding]:
    """No pickle on the typed-JSON frame path; spawn, never fork."""
    findings: List[Finding] = []
    parts = path.replace(os.sep, "/").split("/")
    frame_path = parts[-1] in FRAME_MODULES and "common" in parts
    imports_jax = any(
        (isinstance(n, ast.Import)
         and any(a.name.split(".")[0] == "jax" for a in n.names))
        or (isinstance(n, ast.ImportFrom) and n.module
            and n.module.split(".")[0] == "jax")
        for n in ast.walk(tree))

    for node in ast.walk(tree):
        line = getattr(node, "lineno", 0)
        if _suppressed(source_lines, line, "control-plane-hygiene"):
            continue
        if frame_path and isinstance(node, (ast.Import, ast.ImportFrom)):
            mods = [a.name for a in node.names] if isinstance(
                node, ast.Import) else [node.module or ""]
            for mod in mods:
                if mod.split(".")[0] in ("pickle", "cloudpickle", "dill"):
                    findings.append(Finding(
                        "control-plane-hygiene",
                        f"`{mod}` imported on the control-plane frame path "
                        f"({parts[-1]}) — the wire format is typed JSON "
                        f"frames, never pickle",
                        path, line,
                        rule="control plane is typed JSON frames"))
        if isinstance(node, ast.Call):
            func = node.func
            callee = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else "")
            first = node.args[0].value if node.args and isinstance(
                node.args[0], ast.Constant) else None
            if callee in ("get_context", "set_start_method") and \
                    first == "fork":
                findings.append(Finding(
                    "control-plane-hygiene",
                    f"{callee}('fork') — fork from a JAX-initialized "
                    f"(multithreaded) process deadlocks; use 'spawn' "
                    f"(data/shm_loader.py)",
                    path, line, rule="spawn, never fork"))
            elif callee == "fork" and isinstance(func, ast.Attribute) and \
                    _dotted(func.value) == "os":
                findings.append(Finding(
                    "control-plane-hygiene",
                    "os.fork() — fork from a JAX-initialized process "
                    "deadlocks; use a spawn context",
                    path, line, rule="spawn, never fork"))
            elif callee in ("Process", "Pool") and imports_jax and \
                    isinstance(func, ast.Attribute) and \
                    _dotted(func.value) in ("multiprocessing", "mp"):
                findings.append(Finding(
                    "control-plane-hygiene",
                    f"bare multiprocessing.{callee}() in a jax-importing "
                    f"module defaults to fork on Linux — use "
                    f"get_context('spawn').{callee}",
                    path, line, rule="spawn, never fork"))
    return findings


# ------------------------------------------------- docstring-citation


def check_docstring_citation(path: str, tree: ast.Module,
                             source_lines: Sequence[str],
                             in_package: Optional[bool] = None
                             ) -> List[Finding]:
    """Package modules with code must cite their reference (`file:line`).

    Scoped to files living inside a python package (a dir with
    __init__.py) — bench.py / tools/ scripts document themselves freely.
    """
    parts = path.replace(os.sep, "/").split("/")
    if parts[-1] == "__init__.py" or "tests" in parts:
        return []
    if in_package is None:
        in_package = os.path.isfile(os.path.join(
            os.path.dirname(os.path.abspath(path)), "__init__.py"))
    if not in_package:
        return []
    has_code = any(isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)) for n in tree.body)
    if not has_code:
        return []
    if _suppressed(source_lines, 1, "docstring-citation"):
        return []
    doc = ast.get_docstring(tree) or ""
    if _CITE_RE.search(doc):
        return []
    what = "has no module docstring" if not doc else \
        "docstring cites no reference file:line (and carries no Parity note)"
    return [Finding(
        "docstring-citation",
        f"module {what} — the repo convention is to cite the matched "
        f"reference files and explain the TPU redesign",
        path, 1, rule="every module docstring cites its reference")]


# ------------------------------------------------ wall-clock-duration

#: arithmetic against a file timestamp is wall-to-wall by necessity
#: (mtimes are wall clock) — exempt, the comparison is correct as is
_WALL_EXEMPT_CALLEES = ("getmtime", "getctime", "getatime",
                        "st_mtime", "st_ctime", "st_atime")


def _is_wall_clock_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _dotted(node.func) in ("time.time", "_time.time"))


def _touches_file_timestamp(node: ast.AST) -> bool:
    for n in ast.walk(node):
        name = ""
        if isinstance(n, ast.Attribute):
            name = n.attr
        elif isinstance(n, ast.Name):
            name = n.id
        if name in _WALL_EXEMPT_CALLEES:
            return True
    return False


def check_wall_clock_duration(path: str, tree: ast.Module,
                              source_lines: Sequence[str]
                              ) -> List[Finding]:
    """``time.time()`` inside elapsed-time / deadline arithmetic.

    Wall clock steps under NTP slew and host suspend; a deadline computed
    as ``time.time() + timeout`` or an interval as ``time.time() - t0``
    can fire early, late, or negative.  Duration math belongs on
    ``time.monotonic()``.  ``time.time()`` stays correct for PERSISTED /
    cross-process timestamps (journal entries, manifest ``ts`` fields,
    file-mtime comparisons) — those sites carry a suppression with the
    reason, or compare against a file timestamp (auto-exempt).
    """
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.BinOp) or \
                not isinstance(node.op, (ast.Add, ast.Sub)):
            continue
        line = getattr(node, "lineno", 0)
        if _suppressed(source_lines, line, "wall-clock-duration"):
            continue
        sides = (node.left, node.right)
        if not any(_is_wall_clock_call(s) for s in sides):
            continue
        if any(_touches_file_timestamp(s) for s in sides):
            continue
        op = "+" if isinstance(node.op, ast.Add) else "-"
        findings.append(Finding(
            "wall-clock-duration",
            f"time.time() used in `{op}` arithmetic — elapsed/deadline "
            f"math on the wall clock drifts under NTP slew; use "
            f"time.monotonic() (keep time.time() only for persisted or "
            f"cross-process timestamps, with a suppression reason)",
            path, line,
            rule="duration math runs on the monotonic clock"))
    return findings


# ------------------------------------------------------------- driver


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(root, f)
                           for f in sorted(files) if f.endswith(".py"))
    return sorted(set(out))


def run_paths(paths: Sequence[str],
              checkers: Optional[Sequence[str]] = None,
              key_vars: Optional[Set[str]] = None
              ) -> Tuple[List[Finding], int]:
    """Run the AST engine over files/dirs; returns (findings, files_scanned).

    `checkers` filters by name; `key_vars` overrides the TRACE_ENV_VARS
    set (parsed from auto/compile_cache.py when None).
    """
    if key_vars is None:
        key_vars = trace_env_key_vars(paths) or set()
    files = iter_python_files(paths)
    findings: List[Finding] = []
    for path in files:
        try:
            source = open(path).read()
            tree = ast.parse(source)
        except (OSError, SyntaxError) as e:
            findings.append(Finding("parse-error", str(e), path, 0))
            continue
        lines = source.splitlines()
        rel = os.path.relpath(path)
        if not checkers or "env-at-trace" in checkers:
            findings.extend(check_env_at_trace(rel, tree, lines, key_vars))
        if not checkers or "env-flip-outside-tuner" in checkers:
            findings.extend(check_env_flip_outside_tuner(
                rel, tree, lines, key_vars))
        if not checkers or "donated-reuse" in checkers:
            findings.extend(check_donated_reuse(rel, tree, lines))
        if not checkers or "blocking-readback" in checkers:
            findings.extend(check_blocking_readback(rel, tree, lines))
        if not checkers or "raw-rpc-call" in checkers:
            findings.extend(check_raw_rpc_call(rel, tree, lines))
        if not checkers or "unverified-restore" in checkers:
            findings.extend(check_unverified_restore(rel, tree, lines))
        if not checkers or "control-plane-hygiene" in checkers:
            findings.extend(
                check_control_plane_hygiene(rel, tree, lines))
        if not checkers or "docstring-citation" in checkers:
            findings.extend(check_docstring_citation(rel, tree, lines))
        if not checkers or "wall-clock-duration" in checkers:
            findings.extend(check_wall_clock_duration(rel, tree, lines))
        if not checkers or "suppression-no-reason" in checkers:
            from .findings import check_suppression_reasons

            findings.extend(check_suppression_reasons(rel, lines))
    return findings, len(files)

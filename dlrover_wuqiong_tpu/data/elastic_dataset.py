"""Elastic data pipeline: dynamic-sharded dataset, resume-aware sampler,
host→device prefetch.

Parity: reference `dlrover/trainer/torch/elastic/sampler.py`
(ElasticDistributedSampler :25, state_dict :118, load_state_dict :130),
`elastic/dataloader.py` (ElasticDataLoader :26), atorch
`data/elastic_dataset.py` (ElasticDataset :19) and `data/preloader.py`
(GpuPreLoader :8).

TPU redesign: a JAX input pipeline is host-side numpy; the "loader" is an
iterator of pytrees the training loop `device_put`s with the mesh's batch
sharding.  Elasticity comes from (a) the master-backed `ShardingClient`
(workers pull shards, failed workers' shards are re-dispatched — the dynamic
path) or (b) the deterministic `ElasticDistributedSampler` (rank-sliced with
a resumable epoch/step cursor — the static path).  `DevicePrefetcher`
overlaps host batch prep with device compute; `FusedBatchStager` builds
on it for fused K-step dispatch (trainer/train_step.py), staging the
next K batches as ONE stacked device_put while the current fusion
executes.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from ..common.log import get_logger

logger = get_logger("data")


class ElasticDistributedSampler:
    """Deterministic rank-sliced sampler with a resumable position.

    Parity: reference sampler.py:25 — `state_dict`/`load_state_dict` let a
    restarted (possibly re-scaled) job continue mid-epoch: `completed_num`
    counts globally-consumed samples; on resume each new rank continues from
    that global offset regardless of the new world size.
    """

    def __init__(self, dataset_size: int, num_replicas: int = 1,
                 rank: int = 0, shuffle: bool = True, seed: int = 0,
                 drop_last: bool = False):
        if rank >= num_replicas:
            raise ValueError(f"rank {rank} >= num_replicas {num_replicas}")
        self.dataset_size = dataset_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.completed_num = 0  # global samples consumed in this epoch

    def _epoch_indices(self) -> np.ndarray:
        idx = np.arange(self.dataset_size)
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            rng.shuffle(idx)
        if self.drop_last:
            total = (len(idx) // self.num_replicas) * self.num_replicas
            idx = idx[:total]
        elif len(idx) % self.num_replicas:
            # pad (wrap around) so every rank yields the same count — in SPMD
            # every process must drive the same number of collective steps or
            # the job hangs at epoch end (torch DistributedSampler contract)
            pad = self.num_replicas - len(idx) % self.num_replicas
            idx = np.concatenate([idx, idx[:pad]])
        return idx

    def __iter__(self) -> Iterator[int]:
        idx = self._epoch_indices()
        # skip what the job already consumed before the restart
        start = self.completed_num
        for i in range(start + self.rank, len(idx), self.num_replicas):
            self.completed_num = min(i + self.num_replicas, len(idx))
            yield int(idx[i])
        self.epoch += 1
        self.completed_num = 0

    def __len__(self) -> int:
        remaining = self.dataset_size - self.completed_num
        return max(0, remaining) // self.num_replicas

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        self.completed_num = 0

    def state_dict(self) -> Dict:
        """Parity sampler.py:118."""
        return {"epoch": self.epoch, "completed_num": self.completed_num}

    def load_state_dict(self, state: Dict):
        """Parity sampler.py:130 — tolerant of a changed world size."""
        self.epoch = int(state.get("epoch", 0))
        self.completed_num = int(state.get("completed_num", 0))
        # align to the new replica grid so ranks don't overlap
        self.completed_num -= self.completed_num % self.num_replicas


class ElasticDataset:
    """Master-sharded dataset: indices stream from the dynamic-sharding
    service, so a failed worker's in-flight shards are re-dispatched.

    Parity: atorch `data/elastic_dataset.py:19` (built on the reference's
    IndexShardingClient).
    """

    def __init__(self, sharding_client, read_sample: Callable[[int], Any]):
        self._client = sharding_client
        self._read = read_sample

    def __iter__(self) -> Iterator[Any]:
        while True:
            index = self._client.fetch_sample_index()
            if index is None:
                return
            yield self._read(index)

    def report_batch_done(self, n: int):
        self._client.report_batch_done(n)


def batch_iterator(sample_iter: Iterator[Any], batch_size: int,
                   collate: Optional[Callable[[List[Any]], Any]] = None,
                   drop_last: bool = True) -> Iterator[Any]:
    """Group samples into batches; default collate stacks numpy leaves."""
    collate = collate or _default_collate
    buf: List[Any] = []
    for s in sample_iter:
        buf.append(s)
        if len(buf) == batch_size:
            yield collate(buf)
            buf = []
    if buf and not drop_last:
        yield collate(buf)


def _default_collate(samples: List[Any]):
    import jax

    return jax.tree.map(lambda *xs: np.stack(xs), *samples)


class DevicePrefetcher:
    """Overlap host batch prep (+ device transfer) with compute.

    Parity: atorch `data/preloader.py:8` (GpuPreLoader — CUDA-stream
    prefetch).  TPU version: a background thread runs `place` (typically
    `AccelerateResult.place_batch`) so the next batch's host→HBM copy
    overlaps the current step.
    """

    def __init__(self, it: Iterator[Any], place: Callable[[Any], Any],
                 depth: int = 2):
        import queue as _q

        self._q: "_q.Queue" = _q.Queue(maxsize=depth)
        self._src = it
        self._place = place
        self._done = object()
        self._err: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        try:
            for batch in self._src:
                self._q.put(self._place(batch))
        except BaseException as e:  # noqa: BLE001 — surfaced on next()
            self._err = e
        finally:
            self._q.put(self._done)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._done:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item


def stack_batches(batches: Sequence[Any]):
    """Stack K host batches on a NEW leading fused-step axis.

    The host-side half of fused multi-step dispatch
    (trainer/train_step.py): the fused driver scans this axis on device,
    so K per-step batches ride ONE `device_put` and one dispatch instead
    of K of each."""
    import jax

    return jax.tree.map(lambda *xs: np.stack(xs), *batches)


class FusedBatchStager:
    """Stage fused K-step blocks onto device while the current fusion runs.

    Builds on `DevicePrefetcher`: a background thread pulls K host batches
    per block from `batch_at(step)`, stacks them (`stack_batches`), and
    runs `place_block` (typically `AccelerateResult.place_fused_batch`) so
    block N+1's host→HBM copy overlaps block N's on-device K-step scan.
    Yields `(start_step, k_eff, device_block)`.

    `k_eff` honors boundary alignment: the first block is truncated to the
    next multiple of `fused_steps` (a rollback resume can land anywhere)
    and the last to `max_steps`, so every trainer hook cadence that K
    divides fires exactly at a block boundary.
    """

    def __init__(self, batch_at: Callable[[int], Any],
                 place_block: Callable[[Any], Any], fused_steps: int,
                 start_step: int, max_steps: int,
                 place_single: Optional[Callable[[Any], Any]] = None,
                 depth: int = 2):
        """`place_single` places the un-stacked batch of a truncated
        k_eff=1 alignment/tail block (the K=1 step takes no fused axis);
        defaults to `place_block`."""
        if fused_steps < 1:
            raise ValueError(f"fused_steps must be >= 1, got {fused_steps}")
        self.fused_steps = fused_steps
        place_single = place_single or place_block

        def blocks() -> Iterator[Any]:
            step = start_step
            while step < max_steps:
                k_eff = min(fused_steps - step % fused_steps,
                            max_steps - step)
                if k_eff == 1:
                    yield step, 1, batch_at(step)
                else:
                    yield step, k_eff, stack_batches(
                        [batch_at(step + i) for i in range(k_eff)])
                step += k_eff

        def _place(item):
            step, k_eff, host = item
            placed = place_block(host) if k_eff > 1 else place_single(host)
            return step, k_eff, placed

        self._pf = DevicePrefetcher(blocks(), _place, depth=depth)

    def __iter__(self):
        return self._pf


class ElasticDataLoader:
    """Batched loader over either sampler- or master-sharded indices, with
    a master-tunable batch size.

    Parity: reference `elastic/dataloader.py:26` (`update_batch_size :133` —
    the master's paral-config tuner can adjust the local batch size).
    """

    def __init__(self, read_sample: Callable[[int], Any],
                 batch_size: int,
                 sampler: Optional[ElasticDistributedSampler] = None,
                 sharding_client=None,
                 collate: Optional[Callable] = None,
                 drop_last: bool = True,
                 with_state: bool = False):
        """`with_state=True` yields `(batch, sampler_state)` pairs where the
        state snapshot is taken when the batch is BUILT — checkpoint that
        state, not `sampler.state_dict()` directly: a `DevicePrefetcher`
        advances the sampler ahead of consumption, so the live sampler
        position skips prefetched-but-unconsumed samples after a restore."""
        if (sampler is None) == (sharding_client is None):
            raise ValueError("exactly one of sampler/sharding_client")
        self._read = read_sample
        self.batch_size = batch_size
        self._sampler = sampler
        self._client = sharding_client
        self._collate = collate
        self._drop_last = drop_last
        self._with_state = with_state

    def update_batch_size(self, batch_size: int):
        """Takes effect on the NEXT batch, including mid-epoch (the master's
        paral-config tuner adjusts this during training)."""
        logger.info("dataloader batch size %d -> %d", self.batch_size,
                    batch_size)
        self.batch_size = batch_size

    def _samples(self) -> Iterator[Any]:
        if self._sampler is not None:
            return (self._read(i) for i in self._sampler)
        return iter(ElasticDataset(self._client, self._read))

    def __iter__(self) -> Iterator[Any]:
        samples = self._samples()
        collate = self._collate or _default_collate
        buf: List[Any] = []
        for s in samples:
            buf.append(s)
            if len(buf) < self.batch_size:  # re-read: tunable mid-epoch
                continue
            n = len(buf)
            yield self._emit(collate(buf))
            buf = []
            # generator resumed → the consumer moved past the batch: report
            # its SAMPLE count consumed (the sharding client counts samples
            # toward shard completion; at-least-once — a crash mid-batch
            # leaves the shard unfinished and it gets re-dispatched)
            if self._client is not None:
                self._client.report_batch_done(n)
        if buf and not self._drop_last:
            yield self._emit(collate(buf))
            if self._client is not None:
                self._client.report_batch_done(len(buf))

    def _emit(self, batch):
        if self._with_state and self._sampler is not None:
            return batch, self._sampler.state_dict()
        return batch

"""Shared-memory coworker data loader: preprocessing in sidecar processes.

Parity: reference `atorch/atorch/data/shm_context.py:139` (`ShmDataContext`)
and `shm_dataloader.py:138` (`ShmDataloader`) — CPU-heavy preprocessing runs
in coworker processes that hand finished batches to the trainer through
shared memory, so the training process never blocks on tokenization/
augmentation and no per-batch pickling crosses process boundaries.

Design on this repo's IPC primitives (`common/multi_process.py`): a ring of
POSIX-shm slots, each holding one fixed-shape batch (header + raw arrays,
the `shm_handler` layout); producers claim free slot ids from one shared
queue, write, and announce on a ready queue; the consumer yields zero-copy
numpy views and recycles the slot when the next batch is requested.
"""

from __future__ import annotations

import json
import multiprocessing
import os
import time
from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np

from ..common.log import get_logger
from ..common.multi_process import SharedMemoryBuffer, SharedQueue

logger = get_logger("shm_loader")

_HEADER = 1 << 16  # per-slot JSON header region


def _flatten_example(batch: Dict[str, np.ndarray]):
    metas, offset = [], _HEADER
    for name in sorted(batch):
        arr = np.ascontiguousarray(batch[name])
        metas.append({"name": name, "dtype": arr.dtype.name,
                      "shape": list(arr.shape), "offset": offset,
                      "nbytes": arr.nbytes})
        offset += arr.nbytes
    return metas, offset


def _write_slot(buf: SharedMemoryBuffer, batch: Dict[str, np.ndarray],
                seq: int):
    metas, _ = _flatten_example(batch)
    header = json.dumps({"seq": seq, "metas": metas}).encode()
    mv = buf.buf
    mv[0:8] = len(header).to_bytes(8, "big")
    mv[8:8 + len(header)] = header
    for m in metas:
        arr = np.ascontiguousarray(batch[m["name"]])
        mv[m["offset"]:m["offset"] + m["nbytes"]] = \
            arr.view(np.uint8).reshape(-1)


def _read_slot(buf: SharedMemoryBuffer) -> Dict[str, np.ndarray]:
    mv = buf.buf
    n = int.from_bytes(bytes(mv[0:8]), "big")
    header = json.loads(bytes(mv[8:8 + n]).decode())
    out = {}
    for m in header["metas"]:
        raw = np.frombuffer(bytes(mv[m["offset"]:m["offset"] + m["nbytes"]]),
                            dtype=np.dtype(m["dtype"]))
        out[m["name"]] = raw.reshape(m["shape"])
    return out


def _producer_main(job_name: str, worker_id: int, num_workers: int,
                   produce_fn: Callable[[int, int], Dict[str, np.ndarray]],
                   max_steps: int):
    """Coworker loop: claim slot → produce → write → announce."""
    free_q = SharedQueue(f"{job_name}-shm-free", master=False)
    ready_q = SharedQueue(f"{job_name}-shm-ready", master=False)
    step = worker_id
    try:
        while max_steps < 0 or step < max_steps:
            slot = free_q.get()
            if slot is None or (isinstance(slot, int) and slot < 0):
                break  # shutdown token
            try:
                batch = produce_fn(worker_id, step)
                buf = SharedMemoryBuffer(f"{job_name}_shm_slot_{slot}")
                _write_slot(buf, batch, step)
                buf.close()
            except Exception as e:  # noqa: BLE001 — surface to consumer
                # a dead-silent producer would make training "complete"
                # early as if the data ran out
                ready_q.put({"error": f"worker {worker_id} step {step}: "
                                      f"{e!r}"})
                raise
            ready_q.put(slot)
            step += num_workers
    except (EOFError, OSError, ConnectionError):
        pass  # consumer went away


class ShmCoworkerLoader:
    """Iterate batches produced by coworker processes through shm.

    produce_fn(worker_id, step) -> {name: np.ndarray} with shapes/dtypes
    matching `example_batch` (slots are sized once from it); it must be
    PICKLABLE (module-level function or functools.partial of one) because
    coworkers are spawned, not forked.  Batches are yielded in READY order,
    not step order (parity: the reference's unordered dataloader) — pass
    num_workers=1 for strict ordering.
    """

    def __init__(self, produce_fn: Callable,
                 example_batch: Dict[str, np.ndarray],
                 num_workers: int = 2, depth: int = 4,
                 job_name: Optional[str] = None, max_steps: int = -1):
        self.job_name = job_name or f"dwt-shmdl-{os.getpid()}"
        _, slot_size = _flatten_example(example_batch)
        self._slots = [
            SharedMemoryBuffer(f"{self.job_name}_shm_slot_{i}", create=True,
                               size=slot_size)
            for i in range(depth)
        ]
        self._free_q = SharedQueue(f"{self.job_name}-shm-free", master=True)
        self._ready_q = SharedQueue(f"{self.job_name}-shm-ready",
                                    master=True)
        for i in range(depth):
            self._free_q.put(i)
        self._inflight_slot: Optional[int] = None
        # SPAWN, not fork: the consumer is typically a JAX-initialized
        # (multithreaded) process — fork from it is a documented deadlock
        # (os.fork RuntimeWarning in the r3 bench tail).  Spawn requires
        # produce_fn to be picklable: a module-level function or a
        # functools.partial of one, never a closure.
        ctx = multiprocessing.get_context("spawn")
        self._procs = [
            ctx.Process(
                target=_producer_main,
                args=(self.job_name, w, num_workers, produce_fn, max_steps),
                daemon=True)
            for w in range(num_workers)
        ]
        for p in self._procs:
            p.start()
        self._num_workers = num_workers
        self._max_steps = max_steps
        self._yielded = 0

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        self._recycle()
        if self._max_steps >= 0 and self._yielded >= self._max_steps:
            raise StopIteration
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if self._max_steps >= 0 and self._yielded >= self._max_steps:
                raise StopIteration
            try:
                slot = self._ready_q.get(timeout=1.0)
            except Exception:  # noqa: BLE001 — queue.Empty
                if not any(p.is_alive() for p in self._procs):
                    bad = [p.exitcode for p in self._procs
                           if p.exitcode not in (0, None)]
                    if bad:
                        raise RuntimeError(
                            f"coworker producers crashed (exit codes "
                            f"{bad})") from None
                    raise StopIteration from None
                continue
            if isinstance(slot, dict) and "error" in slot:
                raise RuntimeError(f"coworker produce failed: "
                                   f"{slot['error']}")
            self._inflight_slot = slot
            self._yielded += 1
            return _read_slot(self._slots[slot])
        raise TimeoutError("no batch produced within 300s")

    def _recycle(self):
        if self._inflight_slot is not None:
            try:
                self._free_q.put(self._inflight_slot)
            except Exception:  # noqa: BLE001
                pass
            self._inflight_slot = None

    def close(self):
        self._recycle()
        for _ in self._procs:
            try:
                self._free_q.put(-1)  # shutdown tokens
            except Exception:  # noqa: BLE001
                break
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        for s in self._slots:
            s.unlink()
            s.close()
        self._free_q.close()
        self._ready_q.close()

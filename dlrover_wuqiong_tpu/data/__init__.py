"""Elastic data pipeline (parity: reference trainer/elastic + atorch/data)."""

from .elastic_dataset import (
    DevicePrefetcher,
    ElasticDataLoader,
    ElasticDataset,
    ElasticDistributedSampler,
    batch_iterator,
)

__all__ = [
    "DevicePrefetcher", "ElasticDataLoader", "ElasticDataset",
    "ElasticDistributedSampler", "batch_iterator",
]

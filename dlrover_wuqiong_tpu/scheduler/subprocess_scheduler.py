"""Subprocess scheduler backend: a "pod" is a local process.

Parity: the reference's local-process platform backing `--standalone`
(LocalJobMaster) — here generalized so the SAME PodScaler/PodWatcher code
path that drives k8s also drives single-host TPU-VM jobs: the master
relaunch decision exercises real process creation instead of a noop.
"""

from __future__ import annotations

import os
import queue
import signal
import subprocess
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from ..common.constants import NodeEventType, NodeStatus
from ..common.log import get_logger
from ..common.node import Node, NodeEvent
from .base import NodeSpec, SchedulerClient

logger = get_logger("subprocess_scheduler")


class SubprocessSchedulerClient(SchedulerClient):
    def __init__(self, log_dir: Optional[str] = None):
        self._procs: Dict[Tuple[str, int], subprocess.Popen] = {}
        self._nodes: Dict[Tuple[str, int], Node] = {}
        self._specs: Dict[Tuple[str, int], NodeSpec] = {}
        self._lock = threading.Lock()
        self._log_dir = log_dir
        self._events: "queue.Queue[NodeEvent]" = queue.Queue()

    def create_node(self, spec: NodeSpec) -> bool:
        if not spec.command:
            raise ValueError("subprocess backend needs spec.command")
        env = dict(os.environ)
        env.update(spec.env)
        stdout = None
        if self._log_dir:
            os.makedirs(self._log_dir, exist_ok=True)
            stdout = open(os.path.join(
                self._log_dir,
                f"{spec.node_type}-{spec.node_id}.log"), "ab")
        try:
            proc = subprocess.Popen(spec.command, env=env, stdout=stdout,
                                    stderr=subprocess.STDOUT
                                    if stdout else None,
                                    start_new_session=True)
        except OSError as e:
            logger.error("failed to launch %s: %s", spec.command, e)
            if stdout is not None:
                stdout.close()
            return False
        if stdout is not None:
            # the child inherited its own descriptor at fork — close the
            # parent's copy now (leaking one per relaunch would exhaust the
            # master's fd limit over a long crash-looping job)
            stdout.close()
        node = Node(spec.node_type, spec.node_id,
                    rank_index=spec.rank_index,
                    config_resource=spec.resource)
        node.status = NodeStatus.RUNNING
        node.create_time = time.time()
        with self._lock:
            self._procs[(spec.node_type, spec.node_id)] = proc
            self._nodes[(spec.node_type, spec.node_id)] = node
            self._specs[(spec.node_type, spec.node_id)] = spec
        # surface the launch as an event (a process is RUNNING the moment it
        # exists — the state machine needs the INITIAL→RUNNING hop before a
        # terminal status can land)
        self._events.put(NodeEvent(NodeEventType.ADDED, node))
        logger.info("launched %s-%d pid=%d", spec.node_type, spec.node_id,
                    proc.pid)
        return True

    def delete_node(self, node_type: str, node_id: int) -> bool:
        with self._lock:
            proc = self._procs.get((node_type, node_id))
        if proc is None:
            return False
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
        with self._lock:
            self._procs.pop((node_type, node_id), None)
            node = self._nodes.pop((node_type, node_id), None)
            self._specs.pop((node_type, node_id), None)
        if node is not None:
            node.status = NodeStatus.DELETED
        return True

    def list_nodes(self) -> List[Node]:
        self._poll()
        with self._lock:
            return list(self._nodes.values())

    def watch(self, timeout: float = 1.0) -> Iterator[NodeEvent]:
        """Launch events + process-exit polling."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            got = False
            try:
                while True:
                    yield self._events.get_nowait()
                    got = True
            except queue.Empty:
                pass
            events = self._poll()
            for e in events:
                yield e
            if events or got:
                deadline = time.monotonic() + timeout
            else:
                time.sleep(0.05)

    def _poll(self) -> List[NodeEvent]:
        events = []
        with self._lock:
            for key, proc in list(self._procs.items()):
                code = proc.poll()
                if code is None:
                    continue
                node = self._nodes[key]
                if node.status in (NodeStatus.SUCCEEDED, NodeStatus.FAILED):
                    continue
                node.status = (NodeStatus.SUCCEEDED if code == 0
                               else NodeStatus.FAILED)
                if code != 0:
                    node.exit_reason = f"exit_code={code}"
                events.append(NodeEvent(NodeEventType.MODIFIED, node))
        return events

    def close(self):
        with self._lock:
            keys = list(self._procs)
        for node_type, node_id in keys:
            self.delete_node(node_type, node_id)

"""Scheduler interface + node spec.

Parity: reference `scheduler/kubernetes.py:121` (k8sClient CRUD surface) and
`master/watcher/k8s_watcher.py` (list/watch → NodeEvent stream), collapsed
into one backend-agnostic client interface the master's scaler/watcher pair
programs against.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional

from ..common.node import Node, NodeEvent, NodeResource


@dataclasses.dataclass
class NodeSpec:
    """What to launch: the platform-agnostic pod/process description."""

    node_type: str  # NodeType.*
    node_id: int
    rank_index: int = 0
    resource: NodeResource = dataclasses.field(default_factory=NodeResource)
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    command: Optional[List[str]] = None  # subprocess backend
    image: str = ""  # k8s backend
    relaunch_count: int = 0

    def name(self, job_name: str) -> str:
        return f"{job_name}-{self.node_type}-{self.node_id}"


class SchedulerClient:
    """Backend interface. All methods are synchronous and idempotent."""

    def create_node(self, spec: NodeSpec) -> bool:
        raise NotImplementedError

    def delete_node(self, node_type: str, node_id: int) -> bool:
        raise NotImplementedError

    def list_nodes(self) -> List[Node]:
        raise NotImplementedError

    def watch(self, timeout: float = 1.0) -> Iterator[NodeEvent]:
        """Yield node events; returns when no event arrives within
        `timeout` (the watcher loop re-calls)."""
        raise NotImplementedError

    def close(self):
        pass


def new_scheduler_client(platform: str, **kwargs) -> SchedulerClient:
    """Factory (parity: reference `new_job_args` scheduler/factory.py)."""
    if platform in ("fake", "test"):
        from .fake import FakeSchedulerClient

        return FakeSchedulerClient(**kwargs)
    if platform in ("local", "subprocess"):
        from .subprocess_scheduler import SubprocessSchedulerClient

        return SubprocessSchedulerClient(**kwargs)
    if platform in ("k8s", "kubernetes"):
        from .k8s import K8sSchedulerClient

        return K8sSchedulerClient(**kwargs)
    if platform == "ray":
        from .ray_scheduler import RaySchedulerClient

        return RaySchedulerClient(**kwargs)
    raise ValueError(f"unknown platform {platform!r} "
                     "(expected fake|local|k8s|ray)")

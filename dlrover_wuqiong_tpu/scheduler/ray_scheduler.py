"""Ray scheduler backend: a "pod" is a Ray actor.

Parity: reference `dlrover/python/scheduler/ray.py` (RayClient actor
management), `master/scaler/ray_scaler.py` (`ActorScaler`) and
`master/watcher/ray_watcher.py` (`ActorWatcher`) — collapsed into the same
SchedulerClient interface the other backends implement, so the master's
PodScaler/PodWatcher drive Ray unchanged.

The `ray` package is imported lazily (mirrors the k8s backend); hosts
without it get a clear error at construction.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from ..common.constants import NodeEventType, NodeStatus
from ..common.log import get_logger
from ..common.node import Node, NodeEvent
from .base import NodeSpec, SchedulerClient

logger = get_logger("ray_scheduler")


class RaySchedulerClient(SchedulerClient):
    def __init__(self, job_name: str = "dwt", namespace: str = "dwt",
                 init_kwargs: Optional[Dict] = None):
        try:
            import ray  # type: ignore
        except ImportError as e:  # pragma: no cover - env without ray
            raise RuntimeError(
                "RaySchedulerClient needs the `ray` package; use "
                "platform='local' on hosts without it") from e
        self._ray = ray
        if not ray.is_initialized():
            ray.init(namespace=namespace, **(init_kwargs or {}))
        self.job_name = job_name
        self._actors: Dict[Tuple[str, int], object] = {}
        self._tasks: Dict[Tuple[str, int], object] = {}  # run() futures
        self._nodes: Dict[Tuple[str, int], Node] = {}
        self._lock = threading.Lock()
        self._events: "queue.Queue[NodeEvent]" = queue.Queue()

    def _actor_name(self, node_type: str, node_id: int) -> str:
        return f"{self.job_name}-{node_type}-{node_id}"

    def create_node(self, spec: NodeSpec) -> bool:
        if not spec.command:
            raise ValueError("ray backend needs spec.command")
        ray = self._ray

        @ray.remote
        class _NodeActor:  # runs the command as a subprocess inside the actor
            def run(self, command, env):
                import os
                import subprocess

                e = dict(os.environ)
                e.update(env)
                return subprocess.run(command, env=e).returncode

        opts = {"name": self._actor_name(spec.node_type, spec.node_id),
                "lifetime": "detached"}
        if spec.resource.cpu:
            opts["num_cpus"] = spec.resource.cpu
        if spec.resource.memory_mb:
            opts["memory"] = int(spec.resource.memory_mb * 1024 * 1024)
        try:
            actor = _NodeActor.options(**opts).remote()
            task = actor.run.remote(spec.command, spec.env)
        except Exception:  # noqa: BLE001
            logger.exception("ray actor create failed: %s",
                             self._actor_name(spec.node_type, spec.node_id))
            return False
        node = Node(spec.node_type, spec.node_id,
                    rank_index=spec.rank_index,
                    config_resource=spec.resource)
        node.status = NodeStatus.RUNNING
        node.create_time = time.time()
        with self._lock:
            self._actors[(spec.node_type, spec.node_id)] = actor
            self._tasks[(spec.node_type, spec.node_id)] = task
            self._nodes[(spec.node_type, spec.node_id)] = node
        self._events.put(NodeEvent(NodeEventType.ADDED, node))
        return True

    def delete_node(self, node_type: str, node_id: int) -> bool:
        with self._lock:
            actor = self._actors.pop((node_type, node_id), None)
            self._tasks.pop((node_type, node_id), None)
            node = self._nodes.pop((node_type, node_id), None)
        if actor is None:
            return False
        try:
            self._ray.kill(actor)
        except Exception:  # noqa: BLE001
            pass
        if node is not None:
            node.status = NodeStatus.DELETED
            self._events.put(NodeEvent(NodeEventType.DELETED, node))
        return True

    def list_nodes(self) -> List[Node]:
        self._poll()  # events land on the queue for watch() consumers
        with self._lock:
            return list(self._nodes.values())

    def watch(self, timeout: float = 1.0) -> Iterator[NodeEvent]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            got = False
            try:
                while True:
                    yield self._events.get_nowait()
                    got = True
            except queue.Empty:
                pass
            if self._poll() or got:
                try:
                    while True:
                        yield self._events.get_nowait()
                except queue.Empty:
                    pass
                deadline = time.monotonic() + timeout
            else:
                time.sleep(0.05)

    def _poll(self) -> int:
        """Check actor run() futures; terminal transitions go to the event
        QUEUE (never returned-and-dropped — a list_nodes() caller must not
        swallow events a watch() consumer needs).  Returns #events."""
        ray = self._ray
        events = []
        with self._lock:
            items = list(self._tasks.items())
        for key, task in items:
            done, _ = ray.wait([task], timeout=0)
            if not done:
                continue
            with self._lock:
                node = self._nodes.get(key)
                self._tasks.pop(key, None)
            if node is None or node.status in (NodeStatus.SUCCEEDED,
                                               NodeStatus.FAILED):
                continue
            try:
                code = ray.get(done[0])
            except Exception:  # noqa: BLE001 — actor died
                code = 1
                node.exit_reason = "actor_died"
            node.status = (NodeStatus.SUCCEEDED if code == 0
                           else NodeStatus.FAILED)
            if code != 0 and not node.exit_reason:
                node.exit_reason = f"exit_code={code}"
            events.append(NodeEvent(NodeEventType.MODIFIED, node))
        for e in events:
            self._events.put(e)
        return len(events)

    def close(self):
        with self._lock:
            keys = list(self._actors)
        for node_type, node_id in keys:
            self.delete_node(node_type, node_id)

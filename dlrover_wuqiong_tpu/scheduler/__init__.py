"""Platform scheduler abstraction (L1 of the layer map, SURVEY.md §1).

Parity: reference `dlrover/python/scheduler/` — `k8sClient`
(`scheduler/kubernetes.py:121`, pod/service CRUD + watch), the local-process
scheduler, and `JobArgs` (`scheduler/job.py:117`).

One interface, three backends:
  FakeSchedulerClient        — in-memory; unit tests drive events by hand
  SubprocessSchedulerClient  — a "pod" is a local process (TPU-VM
                               single-host jobs, CI, `--standalone`)
  K8sSchedulerClient         — real kubernetes pods (GKE TPU slices); the
                               `kubernetes` package is imported lazily so
                               the rest of the stack never depends on it
"""

from .base import NodeSpec, SchedulerClient, new_scheduler_client
from .fake import FakeSchedulerClient
from .subprocess_scheduler import SubprocessSchedulerClient

__all__ = [
    "NodeSpec",
    "SchedulerClient",
    "new_scheduler_client",
    "FakeSchedulerClient",
    "SubprocessSchedulerClient",
]

"""In-memory scheduler backend for tests.

Parity: the reference tests' `mock_k8s_client` pattern
(`dlrover/python/tests/test_utils.py:268-284` — monkey-patched CRUD with
canned pod lists); here it is a first-class backend instead of a patch, so
the same scaler/watcher code runs in unit tests unchanged.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Iterator, List, Tuple

from ..common.constants import NodeEventType, NodeStatus
from ..common.node import Node, NodeEvent
from .base import NodeSpec, SchedulerClient


class FakeSchedulerClient(SchedulerClient):
    def __init__(self, fail_creates: int = 0):
        self._nodes: Dict[Tuple[str, int], Node] = {}
        self._events: "queue.Queue[NodeEvent]" = queue.Queue()
        self._lock = threading.Lock()
        self.create_calls: List[NodeSpec] = []
        self.delete_calls: List[Tuple[str, int]] = []
        self._fail_creates = fail_creates  # simulate platform flake

    # ------------------------------------------------------------- interface

    def create_node(self, spec: NodeSpec) -> bool:
        with self._lock:
            self.create_calls.append(spec)
            if self._fail_creates > 0:
                self._fail_creates -= 1
                return False
            node = Node(spec.node_type, spec.node_id,
                        rank_index=spec.rank_index,
                        config_resource=spec.resource)
            node.status = NodeStatus.PENDING
            node.create_time = time.time()
            self._nodes[(spec.node_type, spec.node_id)] = node
        self._events.put(NodeEvent(NodeEventType.ADDED, node))
        return True

    def delete_node(self, node_type: str, node_id: int) -> bool:
        with self._lock:
            self.delete_calls.append((node_type, node_id))
            node = self._nodes.pop((node_type, node_id), None)
        if node is not None:
            node.status = NodeStatus.DELETED
            self._events.put(NodeEvent(NodeEventType.DELETED, node))
        return node is not None

    def list_nodes(self) -> List[Node]:
        with self._lock:
            return list(self._nodes.values())

    def watch(self, timeout: float = 1.0) -> Iterator[NodeEvent]:
        while True:
            try:
                yield self._events.get(timeout=timeout)
            except queue.Empty:
                return

    # ----------------------------------------------------------- test drives

    def set_node_status(self, node_type: str, node_id: int, status: str,
                        exit_reason: str = ""):
        """Simulate the platform reporting a phase change."""
        with self._lock:
            node = self._nodes.get((node_type, node_id))
            if node is None:
                return
            node.status = status
            node.exit_reason = exit_reason
        self._events.put(NodeEvent(NodeEventType.MODIFIED, node))

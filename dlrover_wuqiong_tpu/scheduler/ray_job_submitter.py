"""Ray job submitter — conf-file → Ray Jobs API submission.

Parity: reference `dlrover/client/platform/ray/ray_job_submitter.py`
(RayJobSubimitter [sic]: YAML conf with dashboardUrl/command/workingDir/
requirements → JobSubmissionClient.submit_job, then poll status + stream
logs).

Ray is an optional dependency (not in this image); the submission client
is injectable, so everything but the actual HTTP call is testable — and a
missing ray fails with a clear message at submit time, not import time.

CLI:  python -m dlrover_wuqiong_tpu.scheduler.ray_job_submitter conf.yaml
Conf: dashboardUrl: "127.0.0.1:8265"
      command: "dwt-run --standalone ... train.py"
      workingDir: "./"            # shipped as the job's runtime env
      requirements: ["jax"]       # optional pip list
      pollInterval: 5.0           # optional
"""

from __future__ import annotations

import json
import sys
import time
from typing import Dict, List, Optional

from ..common.log import get_logger

logger = get_logger("ray_submitter")

TERMINAL_STATUSES = {"SUCCEEDED", "FAILED", "STOPPED"}


def load_conf(path: str) -> Dict:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    if path.endswith(".json"):
        return (json.loads(text) if text.strip() else {}) or {}
    import yaml

    return yaml.safe_load(text) or {}  # empty file → {}, not None


class RayJobSubmitter:
    """Submit + babysit one elastic job on a Ray cluster."""

    def __init__(self, conf_path: str, client=None):
        self.conf = load_conf(conf_path)
        if not self.conf.get("command"):
            raise ValueError(f"{conf_path}: conf needs a 'command'")
        self._client = client
        self.job_id: Optional[str] = None

    def _make_client(self):
        if self._client is not None:
            return self._client
        try:
            from ray.job_submission import JobSubmissionClient
        except ImportError as e:  # pragma: no cover — ray not in image
            raise RuntimeError(
                "ray is not installed — `pip install 'ray[default]'` on "
                "the submitting machine (the cluster itself is remote)"
            ) from e
        addr = self.conf.get("dashboardUrl", "127.0.0.1:8265")
        self._client = JobSubmissionClient(f"http://{addr}")
        return self._client

    def submit(self) -> str:
        client = self._make_client()
        runtime_env: Dict = {
            "working_dir": self.conf.get("workingDir", "./")}
        reqs: List[str] = self.conf.get("requirements") or []
        if reqs:
            runtime_env["pip"] = reqs
        self.job_id = client.submit_job(
            entrypoint=self.conf["command"], runtime_env=runtime_env)
        logger.info("submitted ray job %s: %s", self.job_id,
                    self.conf["command"])
        return self.job_id

    def status(self) -> str:
        if self.job_id is None:
            raise RuntimeError("no job submitted")
        return str(self._make_client().get_job_status(self.job_id))

    def logs(self) -> str:
        if self.job_id is None:
            raise RuntimeError("no job submitted")
        return self._make_client().get_job_logs(self.job_id)

    def wait(self, timeout: float = 0.0, stream_logs: bool = True) -> str:
        """Poll until a terminal status; returns it.  timeout 0 = forever."""
        poll = float(self.conf.get("pollInterval", 5.0))
        deadline = time.monotonic() + timeout if timeout else None
        printed = 0
        while True:
            status = self.status()
            if stream_logs:
                try:
                    text = self.logs()
                    # the Jobs API log is nominally append-only, but
                    # rotation/truncation can shrink it — clamp so the
                    # slice below never re-prints from a negative index
                    printed = min(printed, len(text))
                    if len(text) > printed:
                        sys.stdout.write(text[printed:])
                        sys.stdout.flush()
                        printed = len(text)
                except Exception:  # noqa: BLE001 — logs are best-effort
                    pass
            if status in TERMINAL_STATUSES:
                logger.info("ray job %s finished: %s", self.job_id, status)
                return status
            if deadline and time.monotonic() > deadline:
                raise TimeoutError(
                    f"ray job {self.job_id} still {status} after "
                    f"{timeout}s")
            time.sleep(poll)

    def stop(self) -> bool:
        if self.job_id is None:
            return False
        return bool(self._make_client().stop_job(self.job_id))


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m dlrover_wuqiong_tpu.scheduler."
              "ray_job_submitter <conf.yaml|conf.json>", file=sys.stderr)
        return 2
    submitter = RayJobSubmitter(argv[0])
    submitter.submit()
    status = submitter.wait()
    return 0 if status == "SUCCEEDED" else 1


if __name__ == "__main__":
    sys.exit(main())

"""Kubernetes scheduler backend.

Parity: reference `scheduler/kubernetes.py:121` (`k8sClient` — pod CRUD,
watch streams, singleton client) and the pod template handling in
`master/scaler/pod_scaler.py:399` (`_create_pod`).

The `kubernetes` package is imported lazily: environments without it (unit
tests, single-host TPU-VMs) never touch this module.  Pod phase → NodeStatus
mapping follows the reference's `master/watcher/k8s_watcher.py`.
"""

from __future__ import annotations

import threading
from typing import Iterator, List, Optional

from ..common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
)
from ..common.log import get_logger
from ..common.node import Node, NodeEvent, NodeResource
from .base import NodeSpec, SchedulerClient

logger = get_logger("k8s_scheduler")

_POD_PHASE_TO_STATUS = {
    "Pending": NodeStatus.PENDING,
    "Running": NodeStatus.RUNNING,
    "Succeeded": NodeStatus.SUCCEEDED,
    "Failed": NodeStatus.FAILED,
    "Unknown": NodeStatus.BREAKDOWN,
}

_LABEL_TYPE = "dwt.ai/node-type"
_LABEL_ID = "dwt.ai/node-id"
_LABEL_RANK = "dwt.ai/rank-index"
_LABEL_JOB = "dwt.ai/job-name"


class K8sSchedulerClient(SchedulerClient):
    def __init__(self, namespace: str = "default", job_name: str = "dwt",
                 image: str = "", master_addr: str = ""):
        try:
            from kubernetes import client, config, watch  # type: ignore
        except ImportError as e:  # pragma: no cover - env without k8s
            raise RuntimeError(
                "K8sSchedulerClient needs the `kubernetes` package; use "
                "platform='local' on hosts without it") from e
        try:
            config.load_incluster_config()
        except Exception:  # noqa: BLE001 - outside a cluster
            config.load_kube_config()
        self._core = client.CoreV1Api()
        self._client = client
        self._watch_mod = watch
        self.namespace = namespace
        self.job_name = job_name
        self.image = image
        self.master_addr = master_addr
        self._lock = threading.Lock()

    # -------------------------------------------------------------- pod CRUD

    def _pod_manifest(self, spec: NodeSpec):
        c = self._client
        env = [c.V1EnvVar(name=k, value=v) for k, v in spec.env.items()]
        if self.master_addr:
            env.append(c.V1EnvVar(name="DWT_MASTER_ADDR",
                                  value=self.master_addr))
        resources = {}
        if spec.resource.cpu:
            resources["cpu"] = str(spec.resource.cpu)
        if spec.resource.memory_mb:
            resources["memory"] = f"{int(spec.resource.memory_mb)}Mi"
        container = c.V1Container(
            name="main", image=spec.image or self.image,
            command=spec.command, env=env,
            resources=c.V1ResourceRequirements(
                requests=resources or None, limits=resources or None))
        return c.V1Pod(
            metadata=c.V1ObjectMeta(
                name=spec.name(self.job_name),
                labels={
                    _LABEL_JOB: self.job_name,
                    _LABEL_TYPE: spec.node_type,
                    _LABEL_ID: str(spec.node_id),
                    _LABEL_RANK: str(spec.rank_index),
                }),
            spec=c.V1PodSpec(containers=[container],
                             restart_policy="Never"))

    def create_node(self, spec: NodeSpec) -> bool:
        try:
            self._core.create_namespaced_pod(self.namespace,
                                             self._pod_manifest(spec))
            return True
        except Exception:  # noqa: BLE001
            logger.exception("pod create failed: %s",
                             spec.name(self.job_name))
            return False

    def delete_node(self, node_type: str, node_id: int) -> bool:
        name = f"{self.job_name}-{node_type}-{node_id}"
        try:
            self._core.delete_namespaced_pod(name, self.namespace)
            return True
        except Exception:  # noqa: BLE001
            logger.exception("pod delete failed: %s", name)
            return False

    # ------------------------------------------------------------ list/watch

    def _pod_to_node(self, pod) -> Optional[Node]:
        labels = pod.metadata.labels or {}
        if labels.get(_LABEL_JOB) != self.job_name:
            return None
        try:
            node = Node(labels[_LABEL_TYPE], int(labels[_LABEL_ID]),
                        rank_index=int(labels.get(_LABEL_RANK, 0)),
                        config_resource=NodeResource())
        except (KeyError, ValueError):
            return None
        node.status = _POD_PHASE_TO_STATUS.get(
            getattr(pod.status, "phase", "Unknown"), NodeStatus.BREAKDOWN)
        statuses = getattr(pod.status, "container_statuses", None) or []
        for cs in statuses:
            term = getattr(cs.state, "terminated", None)
            if term is not None and term.exit_code not in (0, None):
                node.exit_reason = (
                    NodeExitReason.OOM if term.reason == "OOMKilled"
                    else f"exit_code={term.exit_code}")
        return node

    def list_nodes(self) -> List[Node]:
        pods = self._core.list_namespaced_pod(
            self.namespace, label_selector=f"{_LABEL_JOB}={self.job_name}")
        nodes = [self._pod_to_node(p) for p in pods.items]
        return [n for n in nodes if n is not None]

    def watch(self, timeout: float = 1.0) -> Iterator[NodeEvent]:
        w = self._watch_mod.Watch()
        stream = w.stream(
            self._core.list_namespaced_pod, self.namespace,
            label_selector=f"{_LABEL_JOB}={self.job_name}",
            timeout_seconds=max(1, int(timeout)))
        for event in stream:
            node = self._pod_to_node(event["object"])
            if node is None:
                continue
            etype = {"ADDED": NodeEventType.ADDED,
                     "MODIFIED": NodeEventType.MODIFIED,
                     "DELETED": NodeEventType.DELETED}.get(
                         event["type"], NodeEventType.MODIFIED)
            yield NodeEvent(etype, node)

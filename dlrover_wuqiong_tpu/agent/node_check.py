"""TPU node health-check workload: matmul + collective benchmark.

Parity: reference `dlrover/trainer/torch/node_check/nvidia_gpu.py` (matmul
`utils.py:269`, `bm_allgather` :178) + `NodeCheckElasticAgent`
(training.py:864-1092).  GPU XID checks become TPU chip probes: a large bf16
matmul exercises the MXU; an all-gather over the local mesh (and, cross-host,
over ICI/DCN via jax.distributed) exercises the interconnect.  Results are
reported to the master's NetworkCheckRendezvousManager, which runs the 2-round
pairwise sweep to isolate the faulty node and flag stragglers.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Tuple

from ..common.constants import RendezvousName
from ..common.log import get_logger

logger = get_logger("node_check")


def matmul_benchmark(size: int = 2048, rounds: int = 8) -> float:
    """Time a chain of bf16 matmuls on the local accelerator (MXU probe)."""
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (size, size), dtype=jnp.bfloat16)

    @jax.jit
    def chain(x):
        def body(carry, _):
            y = carry @ carry
            # renormalize so values stay finite
            y = y / (jnp.sqrt(jnp.float32(size)).astype(jnp.bfloat16))
            return y, ()
        out, _ = jax.lax.scan(body, x, None, length=rounds)
        return out

    chain(x).block_until_ready()  # warmup/compile
    t0 = time.monotonic()
    chain(x).block_until_ready()
    return time.monotonic() - t0


def allgather_benchmark(nbytes: int = 1 << 24) -> float:
    """Time an all-gather across all visible devices (ICI probe)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    n = len(devices)
    if n == 1:
        # single chip: time a HBM round-trip instead
        x = jnp.ones((nbytes // 4,), jnp.float32)
        y = jax.device_put(x)
        t0 = time.monotonic()
        jax.device_get(y)
        return time.monotonic() - t0
    mesh = Mesh(np.array(devices), ("x",))
    per = nbytes // 4 // n * n
    x = jax.device_put(
        jnp.ones((per,), jnp.float32),
        NamedSharding(mesh, P("x")))

    @jax.jit
    def gather(x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(None)))

    gather(x).block_until_ready()
    t0 = time.monotonic()
    gather(x).block_until_ready()
    return time.monotonic() - t0


def run_check_workload(matmul_size: int = 2048) -> Tuple[bool, float]:
    """Returns (healthy, elapsed_seconds)."""
    if os.getenv("DWT_MOCK_NODE_CHECK_FAIL") == "1":
        # fault-injection hook (parity: node_check/utils.py:169 mock_error)
        return False, 0.0
    try:
        t_matmul = matmul_benchmark(matmul_size)
        t_comm = allgather_benchmark()
        elapsed = t_matmul + t_comm
        logger.info("node check ok: matmul=%.3fs comm=%.3fs", t_matmul,
                    t_comm)
        return True, elapsed
    except Exception:  # noqa: BLE001 — any chip/runtime error = unhealthy
        logger.exception("node check workload failed")
        return False, 0.0


def run_network_check(agent, rounds: int = 2,
                      timeout: float = 300.0) -> bool:
    """Drive `rounds` sweeps of the pairwise check through the master.

    Parity: reference NodeCheckElasticAgent.run (:905) + node_health_check
    (:1073).
    """
    for r in range(rounds):
        outcome = agent.rendezvous(name=RendezvousName.NETWORK_CHECK)
        healthy, elapsed = run_check_workload()
        agent.mc.report_network_check_result(healthy, elapsed)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            success, reason = agent.mc.network_check_success()
            if success:
                break
            if reason == "Node failure":
                break
            time.sleep(0.5)
    success, _ = agent.mc.network_check_success()
    if not success:
        stragglers = agent.mc.get_stragglers()
        if stragglers:
            logger.warning("stragglers detected: %s", stragglers)
    return success

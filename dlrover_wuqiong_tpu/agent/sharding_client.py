"""Worker-side dynamic-sharding client with prefetch.

Parity: reference `dlrover/python/elastic_agent/sharding/client.py`
(ShardingClient :29, IndexShardingClient :231).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional

from ..common.log import get_logger
from .master_client import MasterClient

logger = get_logger("sharding_client")


class ShardingClient:
    """Fetch/report shard tasks for one dataset."""

    def __init__(self, master_client: MasterClient, dataset_name: str,
                 batch_size: int, dataset_size: int, num_epochs: int = 1,
                 shuffle: bool = False, num_minibatches_per_shard: int = 2,
                 storage_type: str = "", task_type: str = "training"):
        self._mc = master_client
        self.dataset_name = dataset_name
        self._mc.report_dataset_shard_params(
            batch_size=batch_size, num_epochs=num_epochs,
            dataset_size=dataset_size, shuffle=shuffle,
            num_minibatches_per_shard=num_minibatches_per_shard,
            dataset_name=dataset_name, task_type=task_type,
            storage_type=storage_type)
        self._current_task = None

    def fetch_shard(self, wait: bool = True, timeout: float = 600.0):
        """Returns a Task with a shard, or None when the dataset is finished."""
        deadline = time.monotonic() + timeout
        while True:
            task = self._mc.get_task(self.dataset_name)
            if task.task_type == "wait":
                if not wait or time.monotonic() > deadline:
                    return None
                time.sleep(0.5)
                continue
            if task.task_id < 0:
                return None
            self._current_task = task
            return task

    def report_shard_done(self, task_id: Optional[int] = None):
        tid = task_id if task_id is not None else (
            self._current_task.task_id if self._current_task else -1)
        if tid >= 0:
            self._mc.report_task_result(self.dataset_name, tid)

    def report_shard_error(self, err: str, task_id: Optional[int] = None):
        tid = task_id if task_id is not None else (
            self._current_task.task_id if self._current_task else -1)
        if tid >= 0:
            self._mc.report_task_result(self.dataset_name, tid,
                                        err_message=err)

    def get_checkpoint(self) -> str:
        return self._mc.get_shard_checkpoint(self.dataset_name)

    def restore_checkpoint(self, content: str):
        self._mc.report_shard_checkpoint(content)


class IndexShardingClient(ShardingClient):
    """Streams per-sample indices with a background prefetch thread.

    Parity: reference IndexShardingClient (:231) — `fetch_sample_index` feeds
    dataset __getitem__ with globally-sharded indices.
    """

    def __init__(self, *args, prefetch_shards: int = 2, **kwargs):
        super().__init__(*args, **kwargs)
        self._index_queue: "queue.Queue" = queue.Queue(maxsize=100000)
        self._pending: List[int] = []
        self._task_ids: "queue.Queue" = queue.Queue()
        self._fetch_lock = threading.Lock()
        self._finished = False

    def fetch_sample_index(self) -> Optional[int]:
        while True:
            try:
                return self._index_queue.get_nowait()
            except queue.Empty:
                with self._fetch_lock:
                    if self._finished:
                        return None
                    task = self.fetch_shard(wait=True)
                    if task is None:
                        self._finished = True
                        return None
                    indices = task.shard.indices or list(
                        range(task.shard.start, task.shard.end))
                    for idx in indices:
                        self._index_queue.put(idx)
                    self._task_ids.put((task.task_id, len(indices)))

    def report_batch_done(self, batch_size: int):
        """Report completed tasks once all their samples were consumed."""
        self._consumed = getattr(self, "_consumed", 0) + batch_size
        while not self._task_ids.empty():
            tid, n = self._task_ids.queue[0]
            if self._consumed >= n:
                self._task_ids.get()
                self._consumed -= n
                self.report_shard_done(tid)
            else:
                break

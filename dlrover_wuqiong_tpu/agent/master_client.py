"""Typed client wrapper over the master's get/report RPCs.

Parity: reference `dlrover/python/elastic_agent/master_client.py` (MasterClient
:50, get_task :133, join_rendezvous, report_heart_beat :230) and the torch-Store
client `master_kv_store.py` — here the KV store seeds jax.distributed bootstrap.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common import messages as msg
from ..common.comm import RpcClient
from ..common.constants import RendezvousName
from ..common.log import get_logger

logger = get_logger("master_client")


class MasterClient:
    _instance = None
    _lock = threading.Lock()

    def __init__(self, master_addr: str, node_id: int,
                 node_type: str = "worker"):
        self._client = RpcClient(master_addr, node_id, node_type)
        self.master_addr = master_addr
        self.node_id = node_id
        self.node_type = node_type

    @classmethod
    def singleton(cls, master_addr: Optional[str] = None,
                  node_id: int = -1, node_type: str = "worker"):
        with cls._lock:
            if cls._instance is None:
                if master_addr is None:
                    raise ValueError("master_addr required on first call")
                cls._instance = cls(master_addr, node_id, node_type)
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._lock:
            if cls._instance is not None:
                cls._instance.close()
            cls._instance = None

    def close(self):
        self._client.close()

    # ------------------------------------------------------------- dataset

    def report_dataset_shard_params(self, **kwargs):
        return self._client.report(msg.DatasetShardParams(**kwargs))

    def get_task(self, dataset_name: str) -> msg.Task:
        return self._client.get(msg.TaskRequest(dataset_name=dataset_name))

    def report_task_result(self, dataset_name: str, task_id: int,
                           err_message: str = ""):
        return self._client.report(msg.TaskResult(
            dataset_name=dataset_name, task_id=task_id,
            err_message=err_message))

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        resp = self._client.get(
            msg.ShardCheckpointRequest(dataset_name=dataset_name))
        return resp.content

    def report_shard_checkpoint(self, content: str):
        return self._client.report(msg.ShardCheckpoint(content=content))

    # ------------------------------------------------------------- rendezvous

    def join_rendezvous(self, node_rank: int, local_world_size: int,
                        rdzv_name: str = RendezvousName.ELASTIC_TRAINING,
                        node_ip: str = "127.0.0.1",
                        free_port: int = 0) -> int:
        import os

        resp = self._client.report(msg.JoinRendezvousRequest(
            node_id=self.node_id, node_rank=node_rank,
            local_world_size=local_world_size, rdzv_name=rdzv_name,
            node_ip=node_ip, free_port=free_port,
            slice_id=os.getenv("DWT_SLICE_ID", "")))
        return resp.rdzv_round

    def get_comm_world(
        self, rdzv_name: str = RendezvousName.ELASTIC_TRAINING
    ) -> msg.RendezvousState:
        return self._client.get(msg.CommWorldRequest(
            node_id=self.node_id, rdzv_name=rdzv_name))

    def num_nodes_waiting(
        self, rdzv_name: str = RendezvousName.ELASTIC_TRAINING
    ) -> int:
        resp = self._client.get(msg.WaitingNodeNumRequest(
            node_id=self.node_id, rdzv_name=rdzv_name))
        return resp.waiting_num

    def network_check_success(self) -> Tuple[bool, str]:
        resp = self._client.get(msg.NetworkReadyRequest())
        return resp.success, resp.reason

    def report_network_check_result(self, normal: bool, elapsed: float):
        return self._client.report(msg.NetworkCheckResult(
            node_id=self.node_id, normal=normal, elapsed_time=elapsed))

    def get_stragglers(self) -> List[int]:
        resp = self._client.get(msg.StragglerExistRequest())
        return resp.nodes

    # ------------------------------------------------------------- lifecycle

    def register_node(self, node_rank: int, addr: str = "",
                      accelerator_type: str = "tpu",
                      accelerator_num: int = 0):
        return self._client.report(msg.NodeMeta(
            node_type=self.node_type, node_id=self.node_id,
            node_rank=node_rank, addr=addr,
            accelerator_type=accelerator_type,
            accelerator_num=accelerator_num))

    def report_heart_beat(self, global_step: int = 0) -> str:
        return self.report_heart_beat_full(global_step).action

    def report_heart_beat_full(self, global_step: int = 0
                               ) -> msg.HeartbeatResponse:
        """Full response — carries rollback_before_step for spike rollbacks."""
        return self._client.report(msg.HeartBeat(
            node_id=self.node_id, timestamp=time.time(),
            global_step=global_step))

    def report_failure(self, error_data: str, restart_count: int = 0,
                       level: str = "process"):
        return self._client.report(msg.NodeFailure(
            node_id=self.node_id, restart_count=restart_count,
            error_data=error_data, level=level))

    def report_global_step(self, step: int,
                           elapsed_time_per_step: float = 0.0):
        return self._client.report(msg.GlobalStep(
            step=step, timestamp=time.time(),
            elapsed_time_per_step=elapsed_time_per_step))

    def report_node_event(self, event_type: str, message: str = "",
                          level: str = "info"):
        return self._client.report(msg.NodeEventReport(
            node_id=self.node_id, node_type=self.node_type,
            event_type=event_type, message=message, level=level))

    def report_custom_metric(self, data):
        """Push {metric_name: value} to the master; dwt_* names land in the
        master's exported metric registry."""
        return self._client.report(msg.CustomMetric(data=dict(data)))

    def report_diagnosis(self, payload_type: str,
                         content: str) -> msg.DiagnosisAction:
        return self._client.report(msg.DiagnosisReport(
            node_id=self.node_id, payload_type=payload_type,
            content=content, timestamp=time.time()))

    def get_paral_config(self) -> msg.ParallelConfig:
        return self._client.get(
            msg.ParallelConfigRequest(node_id=self.node_id))

    # ------------------------------------------------------------- kv store

    def kv_store_set(self, key: str, value: bytes):
        return self._client.report(msg.KVStoreSetRequest(key=key,
                                                         value=value))

    def kv_store_get(self, key: str) -> Optional[bytes]:
        resp = self._client.get(msg.KVStoreGetRequest(key=key))
        return resp.value if resp.found else None

    def kv_store_multi_get(self, keys: List[str]) -> Optional[List[bytes]]:
        resp = self._client.get(msg.KVStoreMultiGetRequest(keys=keys))
        return resp.values if resp.found else None

    def kv_store_add(self, key: str, amount: int = 1) -> int:
        resp = self._client.get(msg.KVStoreAddRequest(key=key, amount=amount))
        return resp.num

    def kv_store_wait(self, keys: List[str], timeout: float = 300.0,
                      poll: float = 0.2) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.kv_store_multi_get(keys) is not None:
                return True
            time.sleep(poll)
        return False

"""Typed client wrapper over the master's get/report RPCs.

Parity: reference `dlrover/python/elastic_agent/master_client.py` (MasterClient
:50, get_task :133, join_rendezvous, report_heart_beat :230) and the torch-Store
client `master_kv_store.py` — here the KV store seeds jax.distributed bootstrap.

Master fault tolerance (this PR's redesign beyond the reference, whose client
dies with the master after 3 gRPC retries):

- **three verb classes**: CRITICAL verbs (task fetch/results, rendezvous, kv,
  registration) retry with backoff up to the outage grace deadline
  (global_context.master_outage_grace_s) — a master restart is invisible
  below that; BUFFERED fire-and-forget verbs (heartbeats, step/metric/event
  reports) never block training: on an unreachable master they land in a
  bounded in-memory queue that drains after reconnect, so elastic hooks at
  fusion boundaries keep their latency contract through an outage; POLLING
  verbs (num_nodes_waiting) fail fast and let their caller's own cadence
  retry.
- **idempotency keys** ride on report_task_result / kv_store_add /
  join_rendezvous: a retry that crosses a master restart replays the
  journaled response instead of re-applying (master/servicer.py).
- **fencing epoch**: every response carries the master's epoch
  (common/comm.py); on a bump this client re-registers the node and
  re-syncs recently acked task results (idempotent — the journaled ones
  answer from the idem cache) before trusting the new world.
- **failover dialing** (ISSUE 20): ``master_addr`` may be a
  comma-separated ORDERED endpoint list ("primary,standby").  An
  unreachable endpoint or a ``NotLeaderError`` answer (a standby or
  fenced corpse refusing a mutating verb) rotates to the next endpoint;
  CRITICAL verbs keep rotating inside the outage grace window.  The new
  connection is pre-seeded with the last observed fencing epoch so the
  promoted master's higher epoch still fires the one epoch-bump resync,
  and the ORIGINAL idem keys make retried mutations exactly-once across
  the failover.  A NotLeaderError re-dial is the ONE sanctioned re-send
  of an answered RPC: the refusing master never applied the verb.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..common import messages as msg
from ..common.comm import MasterUnreachableError, RpcClient, RpcError
from ..common.constants import RendezvousName
from ..common.global_context import get_context
from ..common.log import get_logger
from ..common.util import retry_call

logger = get_logger("master_client")


class MasterClient:
    _instance = None
    _lock = threading.Lock()

    #: bounded degraded-mode buffer (fire-and-forget frames per client)
    BUFFER_CAP = 512
    #: acked task results kept for epoch-bump re-sync
    RESYNC_CAP = 64

    def __init__(self, master_addr: str, node_id: int,
                 node_type: str = "worker",
                 outage_grace_s: Optional[float] = None):
        # ordered endpoint list ("primary,standby"): index 0 is dialed
        # first; _advance_endpoint rotates on unreachable/NotLeader.
        # The single-endpoint path is byte-for-byte the historical one.
        self._endpoints = [a.strip() for a in master_addr.split(",")
                           if a.strip()] or [master_addr]
        self._endpoint_idx = 0
        self._failover_lock = threading.Lock()
        self._failovers = 0
        self._client = RpcClient(self._endpoints[0], node_id, node_type)
        self._client.on_epoch_change = self._on_epoch_change
        self.master_addr = master_addr
        self.node_id = node_id
        self.node_type = node_type
        self._outage_grace_s = (
            outage_grace_s if outage_grace_s is not None
            else get_context().master_outage_grace_s)
        # degraded mode: bounded buffer of (verb, message) frames
        self._buffer: deque = deque()
        self._buffer_lock = threading.Lock()
        self._idem_prefix = f"{node_id}:{os.getpid()}:{uuid.uuid4().hex[:8]}"
        self._idem_seq = 0
        # epoch-bump resync state
        self._registration: Optional[msg.NodeMeta] = None
        self._recent_results: deque = deque(maxlen=self.RESYNC_CAP)
        # stats (chaos drills assert on these)
        self._buffered_total = 0
        self._flushed_total = 0
        self._dropped_total = 0
        self._reregistrations = 0
        self.epochs_seen: List[int] = []

    @classmethod
    def singleton(cls, master_addr: Optional[str] = None,
                  node_id: int = -1, node_type: str = "worker"):
        with cls._lock:
            if cls._instance is None:
                if master_addr is None:
                    raise ValueError("master_addr required on first call")
                cls._instance = cls(master_addr, node_id, node_type)
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._lock:
            if cls._instance is not None:
                cls._instance.close()
            cls._instance = None

    def close(self):
        self._client.close()

    # ------------------------------------------------------------ retry core

    @property
    def epoch(self) -> Optional[int]:
        """Last master fencing epoch observed on this client."""
        return self._client.epoch

    def _next_idem(self) -> str:
        self._idem_seq += 1
        return f"{self._idem_prefix}:{self._idem_seq}"

    @staticmethod
    def _is_not_leader(exc: Exception) -> bool:
        """An answered refusal from a standby/fenced master — the verb
        was NEVER applied there, so re-dialing the next endpoint is the
        one RpcError that is safe (and required) to re-send."""
        return isinstance(exc, RpcError) and \
            not isinstance(exc, MasterUnreachableError) and \
            "NotLeaderError" in str(exc)

    def _advance_endpoint(self, seen_client: Optional[RpcClient] = None):
        """Rotate to the next configured endpoint (failover dialing).

        The replacement connection is pre-seeded with the last observed
        fencing epoch: `_observe_epoch` only fires the bump callback
        when it has an old value to compare against, and the re-register
        + idem re-sync on promotion hangs off exactly that callback."""
        if len(self._endpoints) <= 1:
            return
        with self._failover_lock:
            if seen_client is not None and self._client is not seen_client:
                return  # another thread already advanced past it
            old = self._client
            self._endpoint_idx = (self._endpoint_idx + 1) \
                % len(self._endpoints)
            addr = self._endpoints[self._endpoint_idx]
            new = RpcClient(addr, self.node_id, self.node_type)
            new.epoch = old.epoch
            new.on_epoch_change = self._on_epoch_change
            self._client = new
            self._failovers += 1
        old.on_epoch_change = None
        old.close()
        logger.warning("failover dialing: master endpoint -> %s", addr)

    def _call_critical(self, verb: str, payload, idem: Optional[str] = None):
        """Blocking control-plane verb: ride a master outage with backoff
        up to the grace deadline, then raise MasterUnreachableError.

        With multiple endpoints the grace window is spent ROTATING
        (fail-fast inner calls) instead of parked on one address — the
        idem key makes the eventual landing exactly-once wherever the
        leader turned out to be."""
        t0 = time.monotonic()
        if len(self._endpoints) == 1:
            try:
                resp = self._client._call(  # noqa: SLF001 — typed facade
                    verb, payload, idem=idem,
                    deadline_s=self._outage_grace_s)
            except MasterUnreachableError:
                # wall time burned blocking on a dead master is the
                # master-outage-degraded ledger split (telemetry/ledger.py)
                self._account_degraded(time.monotonic() - t0)
                raise
            self._maybe_flush()
            return resp
        deadline = t0 + self._outage_grace_s
        backoff = 0.05
        degraded = False
        while True:
            client = self._client
            try:
                resp = client._call(verb, payload, idem=idem,  # noqa: SLF001
                                    attempts=2)
            except MasterUnreachableError:
                degraded = True
            except RpcError as e:
                if not self._is_not_leader(e):
                    raise
                degraded = True
            else:
                if degraded:
                    # the rotation time WAS blocked control-plane time
                    self._account_degraded(time.monotonic() - t0)
                self._maybe_flush()
                return resp
            if time.monotonic() >= deadline:
                self._account_degraded(time.monotonic() - t0)
                raise MasterUnreachableError(
                    f"no reachable leader among {self._endpoints} within "
                    f"{self._outage_grace_s:.0f}s grace")
            self._advance_endpoint(client)
            time.sleep(min(backoff,
                           max(0.0, deadline - time.monotonic())))
            backoff = min(1.0, backoff * 1.5)

    def _call_buffered(self, payload, default):
        """Fire-and-forget verb: never blocks training on a dead master —
        a short retry, then the frame parks in the bounded buffer (oldest
        dropped) and `default` is returned; the buffer drains on the next
        successful call (reconnect or new master).  A NotLeaderError
        answer buffers the SAME way (the standby never applied it) and
        additionally rotates the endpoint so the next beat lands on the
        leader — it must never crash the training loop."""
        t0 = time.monotonic()
        client = self._client
        try:
            resp = client._call(  # noqa: SLF001
                "report", payload, attempts=2)
        except (MasterUnreachableError, RpcError) as e:
            not_leader = self._is_not_leader(e)
            if not not_leader and not isinstance(e,
                                                 MasterUnreachableError):
                raise
            self._account_degraded(time.monotonic() - t0)
            with self._buffer_lock:
                if len(self._buffer) >= self.BUFFER_CAP:
                    self._buffer.popleft()
                    self._dropped_total += 1
                self._buffer.append(payload)
                self._buffered_total += 1
            self._advance_endpoint(client)
            return default
        self._maybe_flush()
        return resp

    @staticmethod
    def _account_degraded(seconds: float):
        """Credit retry time burned against an unreachable master; only
        seconds actually spent blocked count — training that continues
        through the outage stays productive in the ledger."""
        try:
            from ..telemetry.ledger import get_ledger

            get_ledger().account("degraded", seconds)
        except Exception:  # noqa: BLE001 — telemetry must never break rpc
            pass

    def _call_polling(self, verb: str, payload):
        """Advisory verb on a caller-owned cadence: fail fast (the caller's
        next poll is the retry) — but still rotate the endpoint on
        unreachable/NotLeader so the NEXT poll dials somewhere better."""
        client = self._client
        try:
            resp = client._call(verb, payload, attempts=2)  # noqa: SLF001
        except (MasterUnreachableError, RpcError) as e:
            if isinstance(e, MasterUnreachableError) or \
                    self._is_not_leader(e):
                self._advance_endpoint(client)
            raise
        self._maybe_flush()
        return resp

    def _maybe_flush(self):
        """Drain the degraded-mode buffer after a successful call."""
        if not self._buffer:
            return
        while True:
            with self._buffer_lock:
                if not self._buffer:
                    return
                payload = self._buffer.popleft()
            client = self._client
            try:
                client._call("report", payload,  # noqa: SLF001
                             attempts=1)
                self._flushed_total += 1
            except MasterUnreachableError:
                with self._buffer_lock:
                    self._buffer.appendleft(payload)
                return
            except RpcError as e:
                if self._is_not_leader(e):
                    # NOT a reject: the non-leader never applied it.
                    # Re-park the frame and rotate — the drain resumes
                    # against the real leader on the next success.
                    with self._buffer_lock:
                        self._buffer.appendleft(payload)
                    self._advance_endpoint(client)
                    return
                # a frame the new master rejects (stale semantics) is
                # dropped, not retried forever
                logger.warning("degraded-buffer frame rejected on flush",
                               exc_info=True)
                self._flushed_total += 1
            except Exception:  # noqa: BLE001 — same reject contract
                logger.warning("degraded-buffer frame rejected on flush",
                               exc_info=True)
                self._flushed_total += 1

    def _on_epoch_change(self, old: int, new: int):
        """A DIFFERENT master answered: re-register, re-sync in-flight
        task results (idempotent via their original keys), drain buffers.

        Fired by the RpcClient exactly once per bump, outside its socket
        lock (common/comm.py)."""
        self.epochs_seen.append(new)
        logger.warning("master epoch changed %d -> %d — re-registering "
                       "and re-syncing in-flight state", old, new)
        try:
            if self._registration is not None:
                self._client._call("report", self._registration,  # noqa: SLF001
                                   attempts=2)
            for dataset_name, task_id, err, idem in list(
                    self._recent_results):
                self._client._call(  # noqa: SLF001
                    "report",
                    msg.TaskResult(dataset_name=dataset_name,
                                   task_id=task_id, err_message=err),
                    idem=idem, attempts=2)
            self._reregistrations += 1
        except MasterUnreachableError:
            logger.warning("re-sync with epoch-%d master interrupted — "
                           "the next successful verb retries", new)
        self._maybe_flush()

    def degraded_stats(self) -> Dict:
        """Counters for drills/tests: buffer totals + epoch resync state."""
        with self._buffer_lock:
            pending = len(self._buffer)
        return {"buffered_total": self._buffered_total,
                "flushed_total": self._flushed_total,
                "dropped_total": self._dropped_total,
                "pending": pending,
                "reregistrations": self._reregistrations,
                "epochs_seen": list(self.epochs_seen),
                "epoch": self.epoch,
                # ADD-ONLY failover-dialing gauges (ISSUE 20)
                "failovers": self._failovers,
                "endpoints": list(self._endpoints)}

    # ------------------------------------------------------------- dataset

    def report_dataset_shard_params(self, **kwargs):
        return self._call_critical("report", msg.DatasetShardParams(**kwargs))

    def get_task(self, dataset_name: str) -> msg.Task:
        # idem key per REQUEST (each poll is a distinct dispatch decision);
        # a retry of this one request across a master restart replays the
        # journaled Task instead of double-dispatching
        return self._call_critical(
            "get", msg.TaskRequest(dataset_name=dataset_name),
            idem=self._next_idem())

    def report_task_result(self, dataset_name: str, task_id: int,
                           err_message: str = ""):
        idem = self._next_idem()
        self._recent_results.append((dataset_name, task_id, err_message,
                                     idem))
        return self._call_critical("report", msg.TaskResult(
            dataset_name=dataset_name, task_id=task_id,
            err_message=err_message), idem=idem)

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        resp = self._call_critical("get",
                                   msg.ShardCheckpointRequest(
                                       dataset_name=dataset_name))
        return resp.content

    def report_shard_checkpoint(self, content: str):
        return self._call_critical("report",
                                   msg.ShardCheckpoint(content=content))

    # ------------------------------------------------------------- rendezvous

    def join_rendezvous(self, node_rank: int, local_world_size: int,
                        rdzv_name: str = RendezvousName.ELASTIC_TRAINING,
                        node_ip: str = "127.0.0.1",
                        free_port: int = 0) -> int:
        resp = self._call_critical("report", msg.JoinRendezvousRequest(
            node_id=self.node_id, node_rank=node_rank,
            local_world_size=local_world_size, rdzv_name=rdzv_name,
            node_ip=node_ip, free_port=free_port,
            slice_id=os.getenv("DWT_SLICE_ID", "")),
            idem=self._next_idem())
        return resp.rdzv_round

    def get_comm_world(
        self, rdzv_name: str = RendezvousName.ELASTIC_TRAINING
    ) -> msg.RendezvousState:
        return self._call_critical("get", msg.CommWorldRequest(
            node_id=self.node_id, rdzv_name=rdzv_name))

    def num_nodes_waiting(
        self, rdzv_name: str = RendezvousName.ELASTIC_TRAINING
    ) -> int:
        resp = self._call_polling("get", msg.WaitingNodeNumRequest(
            node_id=self.node_id, rdzv_name=rdzv_name))
        return resp.waiting_num

    def network_check_success(self) -> Tuple[bool, str]:
        resp = self._call_critical("get", msg.NetworkReadyRequest())
        return resp.success, resp.reason

    def report_network_check_result(self, normal: bool, elapsed: float):
        return self._call_critical("report", msg.NetworkCheckResult(
            node_id=self.node_id, normal=normal, elapsed_time=elapsed))

    def get_stragglers(self) -> List[int]:
        resp = self._call_polling("get", msg.StragglerExistRequest())
        return resp.nodes

    # ------------------------------------------------------------- lifecycle

    def register_node(self, node_rank: int, addr: str = "",
                      accelerator_type: str = "tpu",
                      accelerator_num: int = 0):
        meta = msg.NodeMeta(
            node_type=self.node_type, node_id=self.node_id,
            node_rank=node_rank, addr=addr,
            accelerator_type=accelerator_type,
            accelerator_num=accelerator_num)
        self._registration = meta  # replayed on every epoch bump
        return self._call_critical("report", meta)

    def report_heart_beat(self, global_step: int = 0) -> str:
        return self.report_heart_beat_full(global_step).action

    def report_heart_beat_full(self, global_step: int = 0
                               ) -> msg.HeartbeatResponse:
        """Full response — carries rollback_before_step for spike rollbacks.

        Degraded mode: on an unreachable master the beat buffers and a
        no-action response returns — training never blocks on heartbeats."""
        return self._call_buffered(
            msg.HeartBeat(node_id=self.node_id, timestamp=time.time(),
                          global_step=global_step),
            default=msg.HeartbeatResponse())

    def report_failure(self, error_data: str, restart_count: int = 0,
                       level: str = "process"):
        return self._call_critical("report", msg.NodeFailure(
            node_id=self.node_id, restart_count=restart_count,
            error_data=error_data, level=level))

    def report_global_step(self, step: int,
                           elapsed_time_per_step: float = 0.0):
        return self._call_buffered(
            msg.GlobalStep(step=step, timestamp=time.time(),
                           elapsed_time_per_step=elapsed_time_per_step),
            default=msg.OkResponse())

    def report_node_event(self, event_type: str, message: str = "",
                          level: str = "info"):
        return self._call_buffered(
            msg.NodeEventReport(node_id=self.node_id,
                                node_type=self.node_type,
                                event_type=event_type, message=message,
                                level=level),
            default=msg.OkResponse())

    def report_custom_metric(self, data):
        """Push {metric_name: value} to the master; dwt_* names land in the
        master's exported metric registry."""
        return self._call_buffered(msg.CustomMetric(data=dict(data)),
                                   default=msg.OkResponse())

    def report_goodput_ledger(self, snapshot: Dict):
        """Push a cumulative ledger snapshot (telemetry/ledger.py
        ``GoodputLedger.snapshot()``) — BUFFERED: cumulative totals make
        drops and replays harmless (master keeps latest per node)."""
        return self._call_buffered(
            msg.GoodputLedgerReport(
                node_id=self.node_id,
                wall_s=float(snapshot.get("wall_s", 0.0)),
                states={str(k): float(v)
                        for k, v in snapshot.get("states", {}).items()},
                other_s=float(snapshot.get("other_s", 0.0)),
                goodput_fraction=float(
                    snapshot.get("goodput_fraction", 0.0)),
                sent_at=time.time()),
            default=msg.OkResponse())

    def get_goodput_summary(self) -> msg.GoodputSummary:
        """Job-level ledger aggregation (tools/goodput_report.py)."""
        return self._call_polling("get", msg.GoodputQuery())

    def report_perf_snapshot(self, snapshot: Dict):
        """Push the latest perf-observatory snapshot (telemetry/perf.py)
        — BUFFERED like the goodput ledger: the snapshot carries
        cumulative counters, so the master keeping latest-SENT per node
        makes drops and replays harmless."""
        return self._call_buffered(
            msg.PerfSnapshotReport(node_id=self.node_id,
                                   snapshot=dict(snapshot),
                                   sent_at=time.time()),
            default=msg.OkResponse())

    def get_perf_summary(self) -> msg.PerfSummary:
        """Job-level perf aggregation (tools/perf_report.py)."""
        return self._call_polling("get", msg.PerfQuery())

    def get_journal_stats(self) -> msg.JournalStats:
        """Journal group-commit gauges (fleet bench / perf_probe rpc)."""
        return self._call_polling("get", msg.JournalStatsQuery())

    # ------------------------------------------------------ adaptive policy

    def report_policy_decision(self, decision: msg.PolicyDecision
                               ) -> msg.PolicyDecisionAck:
        """Submit an externally computed decision (drills/operators) —
        CRITICAL + idem: the master journals it before acking, and a
        retry crossing a restart replays the ack."""
        return self._call_critical(
            "report",
            msg.PolicyDecisionReport(node_id=self.node_id,
                                     decision=decision),
            idem=self._next_idem())

    def get_policy_decision(self) -> msg.PolicyDecision:
        """Latest adaptive-policy decision; polled by the trainer at
        fusion boundaries (fail fast — the next boundary retries)."""
        return self._call_polling(
            "get", msg.PolicyStateRequest(node_id=self.node_id))

    def get_policy_history(self) -> List[Dict]:
        """Full decision history (journal-backed, oldest first)."""
        import json

        resp = self._call_polling(
            "get", msg.PolicyHistoryRequest(node_id=self.node_id))
        return json.loads(resp.content) if resp.content else []

    # ---------------------------------------------------- hot-swap re-mesh

    def get_mesh_transition(self) -> msg.MeshTransitionState:
        """Current hot-swap transition (tid 0 = none active).  POLLING
        class on the trainer's fusion-boundary cadence — fail fast, the
        next boundary retries."""
        return self._call_polling(
            "get", msg.MeshTransitionQuery(node_id=self.node_id))

    def report_mesh_transition_phase(self, transition_id: int, phase: str,
                                     ok: bool = True, detail: str = ""
                                     ) -> msg.OkResponse:
        """Ack one phase of the transition ladder — CRITICAL + idem: the
        master journals the ack before answering, and a retry crossing a
        master restart replays the recorded response instead of
        double-acking (acks advance the fenced state machine)."""
        return self._call_critical(
            "report",
            msg.MeshTransitionPhaseReport(
                node_id=self.node_id, transition_id=transition_id,
                phase=phase, ok=ok, detail=detail),
            idem=self._next_idem())

    # ---------------------------------------------------- incident timeline

    def get_timeline(self, ckpt_dir: str = "",
                     journal_dirs: Optional[List[str]] = None
                     ) -> msg.TimelineResponse:
        """Assembled incident timeline (tools/incident_report.py).

        POLLING class: a post-mortem query must fail fast against a dead
        master — the offline reconstruction from the same disk artifacts
        is the fallback, and it is byte-equal by contract.
        ``journal_dirs`` merges further journal dirs after the answering
        master's own (failover post-mortems span both masters' dirs)."""
        return self._call_polling(
            "get", msg.TimelineQuery(node_id=self.node_id,
                                     ckpt_dir=ckpt_dir,
                                     journal_dirs=list(journal_dirs or [])))

    # ------------------------------------------------------------- serving

    def submit_serve_requests(self, requests: List[msg.ServeRequest]
                              ) -> msg.ServeSubmitAck:
        """Enqueue inference requests — CRITICAL + idem: the master
        journals before acking, and a retry crossing a restart replays
        the ack instead of double-enqueueing."""
        return self._call_critical(
            "report",
            msg.ServeSubmitRequest(node_id=self.node_id,
                                   requests=list(requests)),
            idem=self._next_idem())

    def lease_serve_requests(self, max_requests: int = 1
                             ) -> List[msg.ServeRequest]:
        """Lease pending requests for this decode worker — CRITICAL +
        idem (like get_task: a retried lease must return the SAME
        requests or they strand in `leased`)."""
        resp = self._call_critical(
            "get",
            msg.ServeLeaseRequest(node_id=self.node_id,
                                  max_requests=max_requests),
            idem=self._next_idem())
        return list(resp.requests)

    def report_serve_results(self, results: List[msg.ServeResult]):
        """Durable result hand-off — CRITICAL + idem (drain correctness:
        the worker may exit only after this ack)."""
        return self._call_critical(
            "report",
            msg.ServeResultReport(node_id=self.node_id,
                                  results=list(results)),
            idem=self._next_idem())

    def get_serve_results(self, request_ids: List[str]
                          ) -> msg.ServeResultResponse:
        """Poll for finished results (fail fast; the client's next poll
        is the retry — re-delivery is deduped by request_id)."""
        return self._call_polling(
            "get", msg.ServeResultQuery(request_ids=list(request_ids)))

    def report_serve_stats(self, snapshot: Dict, active_slots: int = 0):
        """Push a cumulative serving-ledger snapshot (telemetry/serving
        ``ServeLedger.snapshot()``) — BUFFERED like the goodput ledger:
        cumulative totals make drops/replays harmless."""
        lat = snapshot.get("latency", {})
        return self._call_buffered(
            msg.ServeStatsReport(
                node_id=self.node_id,
                wall_s=float(snapshot.get("wall_s", 0.0)),
                states={str(k): float(v)
                        for k, v in snapshot.get("states", {}).items()},
                counters={str(k): int(v)
                          for k, v in snapshot.get("counters",
                                                   {}).items()},
                active_slots=int(active_slots),
                p50_ms=float(lat.get("p50_ms", 0.0)),
                p99_ms=float(lat.get("p99_ms", 0.0)),
                ttft_p50_ms=float(lat.get("ttft_p50_ms", 0.0)),
                ttft_p99_ms=float(lat.get("ttft_p99_ms", 0.0)),
                sent_at=time.time()),
            default=msg.OkResponse())

    def get_serve_summary(self) -> msg.ServeSummary:
        """Job-level serving aggregation (tools/serve_report.py)."""
        return self._call_polling("get", msg.ServeStatsQuery())

    def report_diagnosis(self, payload_type: str,
                         content: str) -> msg.DiagnosisAction:
        return self._call_buffered(msg.DiagnosisReport(
            node_id=self.node_id, payload_type=payload_type,
            content=content, timestamp=time.time()),
            default=msg.DiagnosisAction())

    def get_paral_config(self) -> msg.ParallelConfig:
        # advisory poll on the tuner's own cadence — fail fast, next poll
        # is the retry (a 120s-deadline wait here would pin the tuner
        # thread through a whole outage for a config that barely changes)
        return self._call_polling("get",
                                  msg.ParallelConfigRequest(
                                      node_id=self.node_id))

    # ------------------------------------------------------------- kv store

    def kv_store_set(self, key: str, value: bytes):
        return self._call_critical("report",
                                   msg.KVStoreSetRequest(key=key,
                                                         value=value))

    def kv_store_get(self, key: str) -> Optional[bytes]:
        resp = self._call_critical("get", msg.KVStoreGetRequest(key=key))
        return resp.value if resp.found else None

    def kv_store_multi_get(self, keys: List[str]) -> Optional[List[bytes]]:
        resp = self._call_critical("get",
                                   msg.KVStoreMultiGetRequest(keys=keys))
        return resp.values if resp.found else None

    def kv_store_add(self, key: str, amount: int = 1) -> int:
        resp = self._call_critical("get",
                                   msg.KVStoreAddRequest(key=key,
                                                         amount=amount),
                                   idem=self._next_idem())
        return resp.num

    class _KVNotReady(Exception):
        pass

    def kv_store_wait(self, keys: List[str], timeout: float = 300.0,
                      poll: float = 0.2) -> bool:
        """Block until every key exists; polls through the shared backoff
        helper (retry_call) instead of a fixed-interval spin, riding a
        master outage inside the window.  Raises TimeoutError (message
        carries the master's fencing epoch — a restarted master that lost
        un-journaled keys is the first thing to rule out) on expiry."""
        def probe():
            # fail-fast inner call: a long per-probe deadline would let one
            # probe swallow the whole wait window during a master outage
            try:
                resp = self._client._call(  # noqa: SLF001
                    "get", msg.KVStoreMultiGetRequest(keys=keys),
                    attempts=2)
            except MasterUnreachableError as e:
                raise MasterClient._KVNotReady() from e
            if not resp.found:
                raise MasterClient._KVNotReady()
            return True

        try:
            return retry_call(
                probe, attempts=None, deadline_s=timeout,
                base_delay_s=poll, max_delay_s=2.0, jitter=0.25,
                retry_on=(MasterClient._KVNotReady,))
        except MasterClient._KVNotReady:
            raise TimeoutError(
                f"kv_store_wait({keys!r}) timed out after {timeout:.0f}s "
                f"(master epoch={self.epoch})") from None

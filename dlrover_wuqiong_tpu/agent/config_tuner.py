"""ParalConfigTuner: master-tuned runtime config → file → trainer.

Parity: reference `elastic_agent/config/paral_config_tuner.py:101` — a
background loop in the agent that polls the master's tuned parallel config
(dataloader batch size / workers, checkpoint interval, mesh shape) and
writes it to the JSON file whose path the trainer reads from
`DWT_PARAL_CONFIG_PATH` (`ConfigPath.ENV_PARAL_CONFIG`).  The trainer side
(`ElasticDataLoader.load_config` and strategy re-planning) picks changes up
between steps without a restart.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Optional

from ..common.constants import ConfigPath
from ..common.log import get_logger

logger = get_logger("config_tuner")


class ParalConfigTuner:
    def __init__(self, master_client, config_path: Optional[str] = None,
                 interval: float = 30.0):
        self.mc = master_client
        self.config_path = config_path or os.getenv(
            ConfigPath.ENV_PARAL_CONFIG, ConfigPath.PARAL_CONFIG_DEFAULT)
        self.interval = interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_written = ""
        os.environ[ConfigPath.ENV_PARAL_CONFIG] = self.config_path

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dwt-paral-config-tuner")
        self._thread.start()

    def _loop(self):
        while not self._stopped.wait(self.interval):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001
                logger.debug("paral config poll failed", exc_info=True)

    def poll_once(self) -> bool:
        """Fetch + persist the tuned config; returns True when it changed."""
        cfg = self.mc.get_paral_config()
        payload = json.dumps(dataclasses.asdict(cfg), sort_keys=True)
        if payload == self._last_written:
            return False
        os.makedirs(os.path.dirname(self.config_path) or ".",
                    exist_ok=True)
        tmp = f"{self.config_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, self.config_path)  # readers never see a torn file
        self._last_written = payload
        logger.info("paral config updated: %s", payload)
        return True

    def stop(self):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


def read_paral_config(path: Optional[str] = None) -> Optional[dict]:
    """Trainer-side reader (parity: the trainer consuming the tuner file)."""
    path = path or os.getenv(ConfigPath.ENV_PARAL_CONFIG,
                             ConfigPath.PARAL_CONFIG_DEFAULT)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class ParalConfigListener:
    """Trainer-side change detector over the tuner file.

    Parity: reference `trainer/torch/elastic/dataloader.py:97-133` — the
    ElasticDataLoader's `load_config` hook that picks up the master's tuned
    batch size between steps.  `poll()` returns the parsed config dict only
    when its content changed since the last call (None otherwise), so the
    training loop can apply changes exactly once.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path or os.getenv(ConfigPath.ENV_PARAL_CONFIG,
                                      ConfigPath.PARAL_CONFIG_DEFAULT)
        self._last: Optional[str] = None

    def poll(self) -> Optional[dict]:
        try:
            with open(self.path) as f:
                payload = f.read()
        except OSError:
            return None
        if payload == self._last:
            return None
        try:
            cfg = json.loads(payload)
        except ValueError:
            return None  # mid-write torn read can't happen (atomic replace),
            # but tolerate hand-edited files
        self._last = payload
        return cfg

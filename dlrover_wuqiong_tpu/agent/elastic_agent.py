"""Elastic training agent: node-level supervisor of JAX worker processes.

Parity: reference `dlrover/python/elastic_agent/torch/training.py`
(`ElasticTrainingAgent` :362, `_invoke_run` :580, `_assign_worker_ranks` :484,
`_restart_workers` :704, `launch_agent` :734, `MasterRendezvousHandler` :179).

TPU redesign: instead of torch-elastic WorkerSpecs + NCCL process groups, the
agent forms a `jax.distributed` world from the master rendezvous — rank-0's
ip:port becomes the coordinator — then launches ONE worker process per host
(the JAX/TPU model: a process owns all local chips) with the world contract in
env vars.  Elasticity is restart-the-world: on failure or membership change the
agent persists the staged flash checkpoint, kills workers, re-joins rendezvous
and relaunches with the new world (goodput comes from detection + restore
speed, SURVEY.md §7 hard-part (a)).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..checkpoint.ckpt_saver import AsyncCheckpointSaver
from ..common.comm import find_free_port
from ..common.constants import JobConstant, NodeEnv, RendezvousName
from ..common.log import get_logger
from .master_client import MasterClient

logger = get_logger("elastic_agent")


@dataclass
class ElasticLaunchConfig:
    """Parity: reference ElasticLaunchConfig (training.py:117) +
    auto_configure_params (:153)."""

    min_nodes: int = 1
    max_nodes: int = 1
    nproc_per_node: int = 1
    max_restarts: int = 3
    network_check: bool = False
    node_unit: int = 1
    rdzv_timeout: float = 600.0
    monitor_interval: float = 1.0
    log_dir: str = ""

    def auto_configure_params(self):
        self.network_check = self.network_check or (
            os.getenv("DWT_NETWORK_CHECK", "") == "1")
        if self.max_nodes >= 4 and os.getenv(
                "DWT_NETWORK_CHECK", "auto") == "auto":
            self.network_check = True


class WorkerContext:
    """One launched training process + its world assignment."""

    def __init__(self, proc: subprocess.Popen, process_id: int,
                 num_processes: int, restart_count: int,
                 log_path: str = ""):
        self.proc = proc
        self.process_id = process_id
        self.num_processes = num_processes
        self.restart_count = restart_count
        self.log_path = log_path  # captures stderr for error classification


class RendezvousOutcome:
    def __init__(self, rdzv_round: int, process_id: int, num_processes: int,
                 coordinator_addr: str, local_world_size: int):
        self.rdzv_round = rdzv_round
        self.process_id = process_id
        self.num_processes = num_processes
        self.coordinator_addr = coordinator_addr
        self.local_world_size = local_world_size


class ElasticAgent:
    def __init__(self, config: ElasticLaunchConfig, master_client: MasterClient,
                 node_id: int, node_rank: int,
                 entrypoint: Optional[List[str]] = None,
                 worker_env: Optional[Dict[str, str]] = None):
        self.config = config
        self.mc = master_client
        self.node_id = node_id
        self.node_rank = node_rank
        self.entrypoint = entrypoint or []
        self.worker_env = worker_env or {}
        self._worker: Optional[WorkerContext] = None
        self._restart_count = 0
        self._rollback_before = -1  # loss-spike resume ceiling (one-shot)
        self._stopped = threading.Event()
        self._saver: Optional[AsyncCheckpointSaver] = None
        self._heartbeat_thread: Optional[threading.Thread] = None
        self._last_restart_ts = 0.0
        self._replica_server = None
        self._replica_manager = None
        self._warm_pool = None
        self._warm_generation = 0  # invalidates stale warm threads
        self._policy_seen = 0  # last adaptive-policy decision id applied
        # last rendezvous round this agent ran in, PER rendezvous name
        # (network-check and elastic-training managers count independently):
        # a re-join after failure must wait for a NEWER round — accepting
        # the stale completed world hands out a dead coordinator and the
        # restarted workers split across two worlds (deadlock until the jax
        # distributed init timeout)
        self._last_rdzv_round: Dict[str, int] = {}

    # ------------------------------------------------------------- rendezvous

    def rendezvous(self,
                   name: str = RendezvousName.ELASTIC_TRAINING
                   ) -> RendezvousOutcome:
        """Join + poll until the master forms the world.

        Parity: reference MasterRendezvousHandler.next_rendezvous (:250).
        """
        from ..telemetry import spans as tspans

        with tspans.span(f"rdzv:{name}:join", {"node": self.node_id}) as rec:
            out = self._rendezvous_poll(name, rec)
        return out

    def _rendezvous_poll(self, name: str, span_rec) -> RendezvousOutcome:
        free_port = find_free_port()
        self.mc.join_rendezvous(
            self.node_rank, self.config.nproc_per_node, rdzv_name=name,
            node_ip=os.getenv("DWT_NODE_IP", "127.0.0.1"),
            free_port=free_port)
        deadline = time.monotonic() + self.config.rdzv_timeout
        while time.monotonic() < deadline:
            state = self.mc.get_comm_world(rdzv_name=name)
            if state.complete and state.rdzv_round <= \
                    self._last_rdzv_round.get(name, -1):
                # stale world from before our re-join — wait for the next
                time.sleep(0.5)
                continue
            if state.complete:
                my_rank = None
                total_procs = 0
                ranks = sorted(int(r) for r in state.world)
                for rank in ranks:
                    nid, lws, ip, port = state.world[str(rank)]
                    if nid == self.node_id:
                        my_rank = rank
                    total_procs += 1
                if my_rank is None:
                    # we were not included (e.g. over max_nodes) — rejoin
                    time.sleep(1.0)
                    self.mc.join_rendezvous(
                        self.node_rank, self.config.nproc_per_node,
                        rdzv_name=name,
                        node_ip=os.getenv("DWT_NODE_IP", "127.0.0.1"),
                        free_port=free_port)
                    continue
                self._last_rdzv_round[name] = state.rdzv_round
                span_rec["attrs"]["round"] = state.rdzv_round
                span_rec["attrs"]["world"] = total_procs
                return RendezvousOutcome(
                    state.rdzv_round, my_rank, total_procs,
                    state.coordinator_addr, self.config.nproc_per_node)
            time.sleep(0.5)
        raise TimeoutError(f"rendezvous {name} did not complete")

    # ------------------------------------------------------------- lifecycle

    def _start_saver(self):
        if self._saver is None:
            self._saver = AsyncCheckpointSaver.start_async_saving_ckpt(
                job_name=os.getenv(NodeEnv.JOB_NAME, "dwt"),
                local_shard_num=1, node_rank=self.node_rank)
            self._saver.metric_hook = lambda kind, s: \
                self.mc.report_custom_metric(
                    {f"dwt_ckpt_{kind}_seconds": s})

    def _setup_replication(self, outcome: RendezvousOutcome):
        """Ring replication of staged checkpoints over agent TCP (DCN).

        Parity: flash_checkpoint/replica.py backup/gather — peer addresses
        rendezvous through the master KV store; a replacement node restores
        its staged segment from a peer before touching storage.
        """
        from ..common.global_context import get_context
        from ..checkpoint.replica import CkptReplicaManager, ReplicaServer

        replicas = get_context().checkpoint_replica
        if replicas <= 0:
            return
        job = os.getenv(NodeEnv.JOB_NAME, "dwt")
        if self._replica_server is None:
            self._replica_server = ReplicaServer()
            self._replica_server.start()
        my_ip = os.getenv("DWT_NODE_IP", "127.0.0.1")
        my_addr = f"{my_ip}:{self._replica_server.port}"
        rdzv = outcome.rdzv_round
        self.mc.kv_store_set(f"replica/{rdzv}/{outcome.process_id}",
                             my_addr.encode())
        peers = {}
        keys = [f"replica/{rdzv}/{r}" for r in range(outcome.num_processes)]
        try:
            self.mc.kv_store_wait(keys, timeout=60.0)
            vals = self.mc.kv_store_multi_get(keys) or []
            for r, v in enumerate(vals):
                if v:
                    peers[r] = v.decode() if isinstance(v, bytes) else v
        except TimeoutError as e:
            # replication is best-effort: run with the peers that showed up
            logger.warning("replica peer rendezvous incomplete: %s", e)
        self._replica_manager = CkptReplicaManager(
            rank=outcome.process_id, peers=peers, job_name=job,
            replica_count=replicas,
            # holder corruption must reach the master's event stream —
            # the agent is the process that owns the mc here
            health_hook=lambda reason: self.mc.report_node_event(
                "ckpt-health", f"replica: {reason}", level="warning"))
        if not self._replica_manager.has_local_segment():
            # replacement node (or first boot after a node swap): the staged
            # checkpoint exists only on a peer — pull it into local shm so
            # the worker restores in-memory instead of re-reading storage.
            # Gating on the MISSING local segment (not restart counts, which
            # reset with the agent process) also guarantees we never
            # clobber a newer local segment with a peer's older copy.
            restored = self._replica_manager.restore()
            if restored is not None:
                logger.info("replica restore: staged step %d recovered "
                            "from a peer", restored)

    def _launch_worker(self, outcome: RendezvousOutcome) -> WorkerContext:
        env = dict(os.environ)
        env.update(self.worker_env)
        # make this framework importable in the worker regardless of its cwd
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        pythonpath = env.get("PYTHONPATH", "")
        if pkg_root not in pythonpath.split(os.pathsep):
            env["PYTHONPATH"] = (f"{pkg_root}{os.pathsep}{pythonpath}"
                                 if pythonpath else pkg_root)
        env.update({
            NodeEnv.MASTER_ADDR: self.mc.master_addr,
            NodeEnv.NODE_ID: str(self.node_id),
            NodeEnv.NODE_RANK: str(self.node_rank),
            NodeEnv.COORDINATOR_ADDR: outcome.coordinator_addr,
            NodeEnv.PROCESS_ID: str(outcome.process_id),
            NodeEnv.NUM_PROCESSES: str(outcome.num_processes),
            NodeEnv.LOCAL_DEVICE_COUNT: str(outcome.local_world_size),
            NodeEnv.RESTART_COUNT: str(self._restart_count),
        })
        # trace context crosses the process boundary via env: the worker's
        # spans (restore tiers, rpc verbs) parent under this agent's trace
        from ..telemetry import spans as tspans

        with tspans.env_context() as trace_env:
            env.update(trace_env)
        env.setdefault("DWT_PROC_ROLE", "trainer")
        # one compile-cache dir across worker generations and warm
        # children: the restarted worker must read what the pool wrote
        from ..auto.compile_cache import default_cache_dir

        env.setdefault(NodeEnv.COMPILE_CACHE_DIR, default_cache_dir())
        if self._rollback_before >= 0:
            # one-shot: the relaunched worker resumes from the newest
            # committed ckpt BEFORE the spike step, then the ceiling clears
            env[NodeEnv.ROLLBACK_BEFORE_STEP] = str(self._rollback_before)
            self._rollback_before = -1
        stdout = None
        if self.config.log_dir:
            os.makedirs(self.config.log_dir, exist_ok=True)
            prune_prefix = f"worker_{self.node_rank}_"
            log_path = os.path.join(
                self.config.log_dir,
                f"{prune_prefix}r{self._restart_count}.log")
            stdout = open(log_path, "ab")
            stderr = subprocess.STDOUT
        else:
            # stderr always lands in a file: its tail (the traceback) is
            # what the master's error catalogue classifies on failure
            import tempfile

            log_dir = os.path.join(tempfile.gettempdir(), "dwt-worker-logs")
            os.makedirs(log_dir, exist_ok=True)
            prune_prefix = f"worker_{os.getpid()}_{self.node_rank}_"
            log_path = os.path.join(
                log_dir, f"{prune_prefix}r{self._restart_count}.stderr")
            stderr = open(log_path, "ab")
        proc = subprocess.Popen(
            self.entrypoint, env=env, stdout=stdout, stderr=stderr,
            start_new_session=True)
        # the child holds its own dups — close the parent copies, or the
        # agent leaks one fd per restart over a long elastic job
        for fh in (stdout, stderr):
            if hasattr(fh, "close"):
                fh.close()
        self._prune_worker_logs(os.path.dirname(log_path), prune_prefix,
                                keep=5)
        logger.info("launched worker pid=%d process_id=%d/%d coord=%s "
                    "(log %s)", proc.pid, outcome.process_id,
                    outcome.num_processes, outcome.coordinator_addr,
                    log_path)
        return WorkerContext(proc, outcome.process_id,
                             outcome.num_processes, self._restart_count,
                             log_path=log_path)

    def _prune_worker_logs(self, log_dir: str, prefix: str, keep: int = 5):
        """Cap this agent's per-restart worker logs (oldest deleted).

        `prefix` comes from the launch site so it always matches the active
        naming scheme (config.log_dir files have no pid component — a
        hardcoded pid prefix silently never pruned them).  Ordered by
        mtime, NOT filename — lexicographic sort would rank r10 before r2
        and delete the newest logs once restarts hit 10."""
        try:
            mine = sorted(
                (f for f in os.listdir(log_dir) if f.startswith(prefix)),
                key=lambda f: os.path.getmtime(os.path.join(log_dir, f)))
            for stale in mine[:-keep]:
                os.unlink(os.path.join(log_dir, stale))
        except OSError:
            pass

    def _worker_log_tail(self, max_bytes: int = 4000) -> str:
        """Last bytes of the failed worker's captured output — the
        traceback the master's error catalogue classifies."""
        path = getattr(self._worker, "log_path", "")
        if not path:
            return ""
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - max_bytes))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""

    def _stop_worker(self, timeout: float = 30.0):
        if self._worker is None:
            return
        proc = self._worker.proc
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                proc.wait(timeout=10)
        self._worker = None

    def _start_heartbeat(self):
        def _loop():
            while not self._stopped.wait(JobConstant.HEARTBEAT_INTERVAL_SECS):
                try:
                    resp = self.mc.report_heart_beat_full()
                    if resp.action == "restart":
                        # capture the ceiling BEFORE the worker-liveness
                        # check: the master clears it one-shot, and it must
                        # not be lost to a restart-in-progress race
                        if resp.rollback_before_step >= 0:
                            # loss-spike rollback: the relaunched worker must
                            # resume from a ckpt BEFORE the spike (ADVICE r4
                            # — the latest commit may postdate spike onset)
                            self._rollback_before = resp.rollback_before_step
                        if self._worker is not None:
                            logger.info("master requested worker restart"
                                        " (rollback_before=%d)",
                                        resp.rollback_before_step)
                            self._stop_worker()
                except Exception:  # noqa: BLE001
                    logger.warning("heartbeat failed", exc_info=True)
                try:
                    self._apply_policy_knobs()
                except Exception:  # noqa: BLE001 — knob pickup is
                    pass           # best-effort, never kills the heartbeat

        self._heartbeat_thread = threading.Thread(
            target=_loop, daemon=True, name="dwt-agent-heartbeat")
        self._heartbeat_thread.start()

    def _apply_policy_knobs(self):
        """Heartbeat-cadence pickup of the agent-owned policy knob: the
        replica ring fan-out (the trainer owns cadence/fused-K/tier —
        it applies them at fusion boundaries).  Decision ids are
        monotonic, so a replayed master re-serves the same decision and
        the dedup keeps this idempotent."""
        if self._replica_manager is None:
            return
        d = self.mc.get_policy_decision()
        did = int(getattr(d, "decision_id", 0) or 0)
        if did <= self._policy_seen:
            return
        self._policy_seen = did
        if int(getattr(d, "replica_count", -1)) >= 0:
            self._replica_manager.set_replica_count(d.replica_count)

    # --------------------------------------------------------------- run loop

    def _flush_flight(self, reason: str):
        """Dump the flight-recorder ring next to the checkpoints (best
        effort — the saver's latest persist path is the anchor)."""
        from ..telemetry.recorder import get_recorder

        path = (getattr(self._saver, "_latest_path", "") or
                os.getenv("DWT_CKPT_DIR", ""))
        if path:
            get_recorder().flush(path, reason)

    def run(self) -> int:
        """Supervisor loop. Parity: reference `_invoke_run` (:580)."""
        from ..telemetry import spans as tspans

        tspans.set_process_role("agent")
        self._start_saver()
        self._start_heartbeat()
        from .config_tuner import ParalConfigTuner

        self._config_tuner = ParalConfigTuner(self.mc)
        self._config_tuner.start()
        self.mc.register_node(self.node_rank,
                              accelerator_num=self.config.nproc_per_node)
        while not self._stopped.is_set():
            outcome = self.rendezvous()
            if self._saver is not None:
                # commit must wait for EVERY rank's done-file — tell the saver
                # the current world size (reference ckpt_saver.py:863).  Ranks
                # are re-assigned each rendezvous (compacted on scale-down),
                # so the saver's committer/global-rank identity must follow.
                # Routed through the event queue: applies on the saver thread,
                # never racing an in-flight save.
                from ..checkpoint.ckpt_saver import CheckpointEvent

                self._saver._event_queue.put(CheckpointEvent.update_world(
                    outcome.num_processes, outcome.process_id))
            try:
                self._setup_replication(outcome)
                if self._replica_manager is not None:
                    self._saver.post_save_hook = \
                        lambda step: self._replica_manager.backup()
            except Exception:  # noqa: BLE001 — replication is best-effort
                logger.exception("checkpoint replication setup failed")
            self._worker = self._launch_worker(outcome)
            self._kick_warm_pool(outcome)
            exit_code = self._monitor_worker()
            if exit_code == 0:
                logger.info("worker succeeded")
                return 0
            if exit_code is None:
                # membership change → restart workers into a new world
                logger.info("membership change — restarting worker")
                self._stop_worker()
                continue
            # failure path
            logger.warning("worker failed with exit code %s", exit_code)
            self._flush_flight("worker-fault")
            if self._saver is not None:
                try:
                    self._saver.save_shm_to_storage()
                except Exception:  # noqa: BLE001
                    logger.exception("failure-save failed")
            # normalize Python's negative signal codes to shell style
            # (-9 → 137) so the master's error catalogue can classify
            # signal deaths (SIGKILL=OOM-kill, SIGTERM=preemption)
            report_code = 128 - exit_code if exit_code < 0 else exit_code
            error_data = f"exit_code={report_code}"
            tail = self._worker_log_tail()
            if tail:
                error_data += "\n" + tail
                # stderr is captured to a file now — echo the tail so local
                # runs still show the traceback on the console
                logger.error("worker stderr tail:\n%s", tail[-1500:])
            resp = self.mc.report_failure(error_data,
                                          restart_count=self._restart_count)
            if resp is not None and not getattr(resp, "success", True):
                # master's error catalogue says restarts can't fix this
                # class (e.g. user-code error) — stop burning restarts
                logger.error("master: %s — not restarting",
                             getattr(resp, "reason", ""))
                return exit_code
            self._restart_count += 1
            if self._restart_count > self.config.max_restarts:
                logger.error("max restarts (%d) exhausted",
                             self.config.max_restarts)
                return exit_code
            self._stop_worker()
        return 1

    def _kick_warm_pool(self, outcome: RendezvousOutcome,
                        spec_wait_s: float = 120.0):
        """Speculatively compile the post-failure meshes while the world
        is healthy (auto/warm_pool.py).

        The worker publishes its compile spec (model + strategy + batch)
        once its own auto_accelerate runs; a daemon thread here waits for
        a spec matching THIS world, then launches warm children for the
        degraded worlds (N−1 nodes).  The agent owns the lifecycle: it
        survives worker death, so warming keeps running right through the
        window where it matters.  DWT_WARM_POOL=0 disables.
        """
        if os.getenv("DWT_WARM_POOL", "1") == "0":
            return
        if outcome.num_processes <= 1:
            return  # no degraded world below a single node
        self._warm_generation += 1
        generation = self._warm_generation
        world_devices = outcome.num_processes * outcome.local_world_size

        def _wait_and_warm():
            from ..auto.compile_cache import default_cache_dir
            from ..auto.warm_pool import WarmPool, load_current_spec

            cache_dir = os.getenv(NodeEnv.COMPILE_CACHE_DIR,
                                  default_cache_dir())
            deadline = time.monotonic() + spec_wait_s
            while time.monotonic() < deadline and not self._stopped.is_set() \
                    and generation == self._warm_generation:
                spec = load_current_spec(cache_dir)
                # only a spec from THIS world: a stale file from the
                # previous (larger) world would warm the wrong meshes
                if spec is not None and \
                        spec.n_devices == world_devices:
                    if self._warm_pool is None:
                        self._warm_pool = WarmPool(cache_dir)
                    procs = self._warm_pool.warm_degraded(
                        spec, num_nodes=outcome.num_processes,
                        devices_per_node=outcome.local_world_size)
                    if procs:
                        logger.info(
                            "warm pool: %d degraded-mesh compiles "
                            "launched for world of %d", len(procs),
                            world_devices)
                    return
                time.sleep(2.0)

        threading.Thread(target=_wait_and_warm, daemon=True,
                         name="dwt-warm-pool").start()

    def _monitor_worker(self) -> Optional[int]:
        """Wait for worker exit or membership change.

        Returns exit code, or None when a re-rendezvous is needed.
        """
        proc = self._worker.proc
        while not self._stopped.is_set():
            code = proc.poll()
            if code is not None:
                return code
            if self._membership_changed():
                return None
            time.sleep(self.config.monitor_interval)
        return proc.poll() if proc.poll() is not None else 1

    def _membership_changed(self) -> bool:
        """Parity: reference `_membership_changed` :711 (debounced)."""
        now = time.time()
        if now - self._last_restart_ts < JobConstant.RESTART_DEBOUNCE_SECS:
            return False
        try:
            waiting = self.mc.num_nodes_waiting()
        except Exception:  # noqa: BLE001
            return False
        if waiting > 0:
            self._last_restart_ts = now
            return True
        return False

    def stop(self):
        self._stopped.set()
        self._warm_generation += 1
        if self._warm_pool is not None:
            self._warm_pool.stop()
            self._warm_pool = None
        self._stop_worker()
        tuner = getattr(self, "_config_tuner", None)
        if tuner is not None:
            tuner.stop()
        if self._saver is not None:
            AsyncCheckpointSaver.reset()
            self._saver = None


def launch_agent(config: ElasticLaunchConfig, entrypoint: List[str],
                 master_addr: str, node_id: int, node_rank: int) -> int:
    """Parity: reference launch_agent (training.py:734)."""
    config.auto_configure_params()
    mc = MasterClient(master_addr, node_id)
    agent = ElasticAgent(config, mc, node_id, node_rank, entrypoint)
    if config.network_check:
        from .node_check import run_network_check
        ok = run_network_check(agent)
        if not ok:
            logger.error("node failed network check")
            return 3
    try:
        return agent.run()
    finally:
        agent.stop()

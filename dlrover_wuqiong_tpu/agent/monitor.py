"""Agent-side resource monitor: host cpu/mem (+ TPU runtime metrics) → master.

Parity: reference `elastic_agent/monitor/resource.py` (ResourceMonitor :86,
report_resource :157; psutil+pynvml there, psutil+libtpu-metrics here) and
`monitor/training.py` (TrainingProcessReporter).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from ..common.log import get_logger
from .master_client import MasterClient

logger = get_logger("monitor")

_PROC = None
_PROC_LOCK = threading.Lock()


def _psutil_process():
    """Cached, PRIMED psutil.Process.

    `cpu_percent(interval=None)` measures since the previous call on the
    same Process object — the first call has no baseline and always
    returns 0.0.  A fresh Process per report (the old code) therefore
    reported a flat 0% CPU forever.  Prime once at acquisition and reuse;
    re-acquire after fork/spawn (pid check) so a child never reads the
    parent's baseline."""
    global _PROC
    import psutil

    with _PROC_LOCK:
        if _PROC is None or _PROC.pid != os.getpid():
            proc = psutil.Process()
            proc.cpu_percent(interval=None)  # prime the baseline sample
            _PROC = proc
        return _PROC


def get_process_resource() -> Dict[str, float]:
    """Host usage of this process tree (no psutil dependency required)."""
    stats: Dict[str, float] = {"cpu_percent": 0.0, "memory_mb": 0.0}
    try:
        proc = _psutil_process()
        stats["cpu_percent"] = proc.cpu_percent(interval=None)
        stats["memory_mb"] = proc.memory_info().rss / (1 << 20)
    except ImportError:
        try:
            import resource

            usage = resource.getrusage(resource.RUSAGE_SELF)
            stats["memory_mb"] = usage.ru_maxrss / 1024.0
        except Exception:  # noqa: BLE001
            pass
    return stats


def get_accelerator_stats() -> Dict[str, float]:
    """TPU-side stats via jax (device memory where the backend exposes it)."""
    stats: Dict[str, float] = {}
    try:
        import jax

        devs = jax.local_devices()
        stats["num_devices"] = float(len(devs))
        for d in devs[:1]:
            mem = getattr(d, "memory_stats", None)
            if callable(mem):
                m = mem() or {}
                stats["hbm_bytes_in_use"] = float(
                    m.get("bytes_in_use", 0))
                stats["hbm_bytes_limit"] = float(
                    m.get("bytes_limit", 0))
    except Exception:  # noqa: BLE001
        pass
    return stats


class ResourceMonitor:
    def __init__(self, master_client: MasterClient,
                 interval: float = 30.0):
        self.mc = master_client
        self.interval = interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dwt-resource-monitor")
        self._thread.start()

    def _loop(self):
        from ..common import messages as msg

        while not self._stopped.wait(self.interval):
            try:
                host = get_process_resource()
                accel = get_accelerator_stats()
                self.mc._client.report(msg.ResourceStats(
                    node_id=self.mc.node_id,
                    cpu_percent=host["cpu_percent"],
                    memory_mb=host["memory_mb"],
                    accelerator_stats=accel))
            except Exception:  # noqa: BLE001
                logger.debug("resource report failed", exc_info=True)

    def stop(self):
        self._stopped.set()

"""Hybrid RLHF engine: separate train and decode meshes with weight sync.

Parity: reference `atorch/atorch/rl/ds_hybrid_engine/hybrid_engine.py:1-378`
(+ `ds_hook.py`) — DeepSpeed-hybrid keeps TRAINING sharded for throughput
(ZeRO partitions) but runs GENERATION on an inference-friendly layout,
gathering/re-partitioning the actor weights between the two phases each
iteration.

TPU redesign: both layouts are just NamedShardings over two meshes built
from the SAME devices —

- train mesh: fsdp-major (or any auto_accelerate plan): maximizes update
  throughput and state sharding;
- decode mesh: tp x dp — parameters sharded over tp ONLY (so the KV-cache
  decode scan runs without per-step fsdp all-gathers) and the batch over
  dp.

The "weight sync" of the reference's gather+scatter hooks collapses to one
resharding `jax.device_put(actor_params, decode_shardings)` — XLA emits
the all-gather/all-to-all pattern between the two placements.  Sync
latency is measured per call (`last_sync_s`).
"""

from __future__ import annotations

import time
from typing import Any, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..common.log import get_logger
from ..parallel.mesh import MeshPlan, build_mesh
from ..parallel.sharding import ShardingPlanner

logger = get_logger("rl_hybrid")


class HybridEngine:
    """Two placements of the actor over one device set + timed sync."""

    def __init__(self, devices, train_plan: Optional[MeshPlan] = None,
                 decode_tp: int = 1):
        devices = list(devices)
        n = len(devices)
        if decode_tp < 1 or n % decode_tp:
            raise ValueError(f"decode_tp={decode_tp} must be >= 1 and "
                             f"divide the {n} devices")
        self.train_mesh = build_mesh(train_plan or MeshPlan(fsdp=n),
                                     devices)
        self.train_planner = ShardingPlanner(self.train_mesh)
        self.decode_mesh = build_mesh(
            MeshPlan(tp=decode_tp, dp=n // decode_tp), devices)
        self.decode_planner = ShardingPlanner(self.decode_mesh)
        self._decode_sh = None
        self.last_sync_s = 0.0

    def place_train(self, params: Any) -> Any:
        return self.train_planner.shard_params(params)

    def sync_to_decode(self, actor_params: Any) -> Any:
        """Reshard trained actor weights onto the decode placement.

        The reference hybrid engine's ds_hook gather/scatter round-trip;
        here one device_put between shardings, timed for the README
        sync-latency number."""
        if self._decode_sh is None:
            self._decode_sh = self.decode_planner.param_shardings(
                actor_params)
        from ..common.util import sync_tree

        t0 = time.perf_counter()
        placed = jax.device_put(actor_params, self._decode_sh)
        # all-leaf readback, not block_until_ready (a NO-OP over the axon
        # tunnel) and not a single-leaf probe (a lower bound — other
        # leaves may still be in flight; r4 verdict weak #2).  The first
        # call also compiles the sync reduction — steady-state
        # last_sync_s is the second call onward.
        sync_tree(placed)
        self.last_sync_s = time.perf_counter() - t0
        return placed

    def place_prompts(self, prompts: jax.Array) -> jax.Array:
        """Batch over the decode mesh's dp axis."""
        return jax.device_put(
            prompts, NamedSharding(self.decode_mesh, P("dp")))

    def place_batch_train(self, x: jax.Array) -> jax.Array:
        """Batch over the train mesh's data axes (for the PPO update)."""
        return jax.device_put(x, self.train_planner.batch_sharding(
            x.ndim, None, 0))

"""Reward-model role: scoring head + Bradley-Terry preference training.

Parity: reference `atorch/atorch/rl/model_engine/model_engine.py:98,475` —
the engine auto-accelerates "reward_model"/"cost_model" roles alongside
actor/critic/ref, and rollouts score responses through them.  Here the
role is a flax module (GPT trunk + scalar head reading the LAST response
token), a pairwise trainer (Bradley-Terry: -log sigmoid(r_chosen -
r_rejected), the standard RLHF-RM objective), and an adapter producing
exactly the `reward_fn(tokens, prompt_len) -> (B,)` signature
`PPOTrainer` consumes — train a RM on preferences, plug it straight into
PPO.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..models.gpt import GPT, GPTConfig


class RewardModel(nn.Module):
    """GPT trunk + scalar reward head on the final token's hidden state."""

    config: GPTConfig

    @nn.compact
    def __call__(self, tokens) -> jax.Array:
        _, hidden = GPT(self.config, name="gpt")(tokens, return_hidden=True)
        scores = nn.Dense(1, dtype=jnp.float32, name="reward_head")(
            hidden.astype(jnp.float32))[..., 0]      # (B, T)
        return scores[:, -1]                          # (B,)

    def init_params(self, rng, batch: int = 1, seq: int = 8):
        return self.init(rng, jnp.zeros((batch, seq), jnp.int32))["params"]


def bradley_terry_loss(model: RewardModel, params, chosen, rejected):
    """-log sigmoid(r_chosen - r_rejected), plus pairwise accuracy."""
    r_c = model.apply({"params": params}, chosen)
    r_r = model.apply({"params": params}, rejected)
    margin = r_c - r_r
    loss = -jax.nn.log_sigmoid(margin).mean()
    acc = (margin > 0).mean()
    return loss, acc


@dataclasses.dataclass
class RewardModelTrainer:
    """Minimal pairwise-preference trainer for the RM role.

    `step(chosen, rejected)` consumes token batches of equal shape
    (B, T); chosen[i] is preferred over rejected[i].
    """

    model: RewardModel
    lr: float = 1e-4
    seed: int = 0

    def __post_init__(self):
        self.params = self.model.init_params(jax.random.PRNGKey(self.seed))
        self.opt = optax.adam(self.lr)
        self.opt_state = self.opt.init(self.params)

        @jax.jit
        def _step(params, opt_state, chosen, rejected):
            (loss, acc), grads = jax.value_and_grad(
                lambda p: bradley_terry_loss(self.model, p, chosen,
                                             rejected),
                has_aux=True)(params)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss, \
                acc

        self._step = _step

    def step(self, chosen, rejected) -> Dict[str, float]:
        self.params, self.opt_state, loss, acc = self._step(
            self.params, self.opt_state, jnp.asarray(chosen),
            jnp.asarray(rejected))
        return {"loss": float(loss), "pairwise_acc": float(acc)}


def as_reward_fn(model: RewardModel, params):
    """Adapter: trained RM -> the reward_fn signature PPOTrainer takes."""
    score = jax.jit(lambda p, t: model.apply({"params": p}, t))

    def reward_fn(tokens: np.ndarray, prompt_len: int) -> np.ndarray:
        return np.asarray(score(params, jnp.asarray(tokens)),
                          np.float32)

    return reward_fn

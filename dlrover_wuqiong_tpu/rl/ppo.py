"""PPO for language models — the RLHF training engine.

Parity: reference `atorch/atorch/rl/` — `ModelEngine`
(model_engine/model_engine.py:35: actor/critic/ref/reward roles),
`PPOTrainer` (trainer/ppo_trainer.py), PPO math (`ppo_utils/ppo_util.py`:
GAE, ratio clipping, value clipping, KL penalty vs the frozen reference
policy), and the replay buffer.

TPU redesign: one jitted update step over the mesh (GSPMD shards the
models exactly like pretraining); rollouts run through the KV-cache
`generate` scan.  The four model roles collapse to two parameter trees —
actor+critic share the transformer trunk with a value head (the standard
PPO-LM economy), and the frozen reference policy is a second tree.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..common.log import get_logger
from ..models.gpt import GPT, GPTConfig
from .generation import SampleConfig, generate

logger = get_logger("ppo")


class ActorCritic(nn.Module):
    """GPT trunk + scalar value head (parity: critic sharing the actor
    trunk, rl/model_utils model wrapping)."""

    config: GPTConfig

    @nn.compact
    def __call__(self, idx):
        cfg = self.config
        logits, hidden = GPT(cfg, name="gpt")(idx, return_hidden=True)
        values = nn.Dense(1, dtype=jnp.float32, name="value_head")(
            hidden.astype(jnp.float32))
        return logits, values[..., 0]

    def init_params(self, rng, batch: int = 1, seq: int = 8):
        idx = jnp.zeros((batch, seq), jnp.int32)
        return self.init(rng, idx)["params"]


@dataclasses.dataclass(frozen=True)
class PPOConfig:
    clip_ratio: float = 0.2
    value_clip: float = 0.2
    gamma: float = 1.0
    lam: float = 0.95
    kl_coef: float = 0.05           # penalty vs the reference policy
    vf_coef: float = 0.5
    entropy_coef: float = 0.0
    ppo_epochs: int = 2
    lr: float = 1e-5
    max_new_tokens: int = 16
    temperature: float = 1.0


def gae_advantages(rewards, values, gamma: float, lam: float):
    """Generalized advantage estimation over the response segment.

    rewards/values: (B, N) per response token (terminal bootstrap 0).
    Parity: ppo_util.py GAE.
    """
    def step(carry, xs):
        r, v, v_next = xs
        delta = r + gamma * v_next - v
        adv = delta + gamma * lam * carry
        return adv, adv

    v_next = jnp.concatenate([values[:, 1:],
                              jnp.zeros_like(values[:, :1])], axis=1)
    _, advs = jax.lax.scan(
        step, jnp.zeros(rewards.shape[0]),
        (rewards.T, values.T, v_next.T), reverse=True)
    advs = advs.T
    returns = advs + values
    return advs, returns


class Rollout(NamedTuple):
    tokens: jax.Array       # (B, P+N)
    logprobs: jax.Array     # (B, N) behavior-policy logprobs
    ref_logprobs: jax.Array  # (B, N)
    values: jax.Array       # (B, N)
    rewards: jax.Array      # (B, N) env reward + KL penalty folded in
    advantages: jax.Array   # (B, N)
    returns: jax.Array      # (B, N)
    prompt_len: int


def _response_logprobs_values(model: ActorCritic, params, tokens,
                              prompt_len: int):
    """Teacher-forced per-token logprobs/values for the response part."""
    logits, values = model.apply({"params": params}, tokens[:, :-1])
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    targets = tokens[:, 1:]
    tok_logp = jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
    sl = slice(prompt_len - 1, None)
    return tok_logp[:, sl], values[:, sl]


def ppo_loss(model: ActorCritic, params, rollout: Rollout,
             cfg: PPOConfig, prompt_len: int):
    """Clipped-ratio policy loss + clipped value loss + entropy.

    Parity: ppo_util.py loss terms (the KL penalty is folded into
    `rollout.rewards`, the TRL/reference convention).  `prompt_len` is
    static (slice boundaries must be compile-time constants).
    """
    logp, values = _response_logprobs_values(model, params, rollout.tokens,
                                             prompt_len)
    ratio = jnp.exp(logp - rollout.logprobs)
    adv = rollout.advantages
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    pg1 = -adv * ratio
    pg2 = -adv * jnp.clip(ratio, 1 - cfg.clip_ratio, 1 + cfg.clip_ratio)
    policy_loss = jnp.maximum(pg1, pg2).mean()
    v_clipped = rollout.values + jnp.clip(
        values - rollout.values, -cfg.value_clip, cfg.value_clip)
    vf_loss = 0.5 * jnp.maximum(
        (values - rollout.returns) ** 2,
        (v_clipped - rollout.returns) ** 2).mean()
    entropy = -(jnp.exp(logp) * logp).mean()
    total = (policy_loss + cfg.vf_coef * vf_loss
             - cfg.entropy_coef * entropy)
    return total, {"policy_loss": policy_loss, "value_loss": vf_loss,
                   "ratio": ratio.mean()}


class ReplayBuffer:
    """Host-side rollout store (parity rl replay buffer)."""

    def __init__(self, capacity: int = 64):
        self._items: List[Rollout] = []
        self.capacity = capacity

    def add(self, r: Rollout):
        self._items.append(r)
        if len(self._items) > self.capacity:
            self._items.pop(0)

    def sample_all(self) -> List[Rollout]:
        return list(self._items)

    def clear(self):
        self._items.clear()

    def __len__(self):
        return len(self._items)


class PPOTrainer:
    """actor-critic + frozen reference + reward fn → PPO updates.

    reward_fn(tokens (B, P+N) np.ndarray, prompt_len) -> (B,) np.ndarray
    of sequence-level rewards (assigned to the last response token,
    reference convention).
    """

    def __init__(self, cfg: GPTConfig, ppo: PPOConfig,
                 reward_fn: Callable, seed: int = 0,
                 devices=None, decode_tp: int = 1, train_plan=None):
        """`devices`: enable the hybrid engine (rl/hybrid.py) — training
        sharded over a train mesh, rollouts on a tp-only decode mesh with
        per-iteration weight sync (parity: reference
        ds_hybrid_engine/hybrid_engine.py)."""
        self.model_cfg = cfg
        self.ppo = ppo
        self.reward_fn = reward_fn
        self.model = ActorCritic(cfg)
        key = jax.random.PRNGKey(seed)
        self.params = self.model.init_params(key)
        self.engine = None
        if devices is not None:
            from .hybrid import HybridEngine

            self.engine = HybridEngine(devices, train_plan=train_plan,
                                       decode_tp=decode_tp)
            self.params = self.engine.place_train(self.params)
        self.ref_params = jax.tree.map(jnp.copy, self.params["gpt"])
        self.opt = optax.adam(ppo.lr)
        self.opt_state = self.opt.init(self.params)
        self._rng = jax.random.PRNGKey(seed + 1)
        self.buffer = ReplayBuffer()

        ppo_cfg = self.ppo

        import functools

        @functools.partial(jax.jit, static_argnums=(3,))
        def _update(params, opt_state, rollout: Rollout, prompt_len: int):
            (loss, aux), grads = jax.value_and_grad(
                lambda p: ppo_loss(self.model, p, rollout, ppo_cfg,
                                   prompt_len),
                has_aux=True)(params)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss, \
                aux

        self._update = _update

    # --------------------------------------------------------------- rollout

    def make_rollout(self, prompts: jax.Array) -> Rollout:
        self._rng, sub = jax.random.split(self._rng)
        sample = SampleConfig(max_new_tokens=self.ppo.max_new_tokens,
                              temperature=self.ppo.temperature)
        actor = self.params["gpt"]
        if self.engine is not None:
            # rollouts run on the DECODE mesh: actor weights sync to the
            # tp-only placement (timed), prompts shard over decode dp
            actor = self.engine.sync_to_decode(actor)
            prompts = self.engine.place_prompts(prompts)
        tokens, logprobs = generate(self.model_cfg, actor,
                                    prompts, sub, sample)
        if self.engine is not None:
            # scoring + PPO updates run on the TRAIN mesh
            tokens = self.engine.place_batch_train(tokens)
            logprobs = self.engine.place_batch_train(logprobs)
        P = prompts.shape[1]
        ref_logp, _ = _response_logprobs_values(
            self.model, dict(self.params, gpt=self.ref_params), tokens, P)
        _, values = _response_logprobs_values(self.model, self.params,
                                              tokens, P)
        env_reward = jnp.asarray(
            self.reward_fn(np.asarray(tokens), P), jnp.float32)
        # KL penalty per token + terminal env reward (reference convention)
        kl = logprobs - ref_logp
        rewards = -self.ppo.kl_coef * kl
        rewards = rewards.at[:, -1].add(env_reward)
        advs, rets = gae_advantages(rewards, values, self.ppo.gamma,
                                    self.ppo.lam)
        roll = Rollout(tokens=tokens, logprobs=logprobs,
                       ref_logprobs=ref_logp, values=values,
                       rewards=rewards,
                       advantages=jax.lax.stop_gradient(advs),
                       returns=jax.lax.stop_gradient(rets),
                       prompt_len=P)
        self.buffer.add(roll)
        return roll

    # ----------------------------------------------------------------- train

    def step(self, prompts: jax.Array) -> Dict[str, float]:
        """One PPO iteration: rollout + ppo_epochs of updates."""
        roll = self.make_rollout(prompts)
        out = {}
        for _ in range(self.ppo.ppo_epochs):
            self.params, self.opt_state, loss, aux = self._update(
                self.params, self.opt_state, roll, roll.prompt_len)
        out["loss"] = float(loss)
        out["reward"] = float(roll.rewards.sum(axis=1).mean())
        out["kl"] = float((roll.logprobs - roll.ref_logprobs).mean())
        if self.engine is not None:
            out["weight_sync_s"] = self.engine.last_sync_s
        for k, v in aux.items():
            out[k] = float(v)
        return out

"""RLHF engine: KV-cache generation + PPO (reference atorch/rl parity)."""

from .generation import SampleConfig, generate
from .reward import (
    RewardModel,
    RewardModelTrainer,
    as_reward_fn,
    bradley_terry_loss,
)
from .ppo import (
    ActorCritic,
    PPOConfig,
    PPOTrainer,
    ReplayBuffer,
    gae_advantages,
    ppo_loss,
)

__all__ = [
    "RewardModel",
    "RewardModelTrainer",
    "as_reward_fn",
    "bradley_terry_loss",
    "SampleConfig",
    "generate",
    "ActorCritic",
    "PPOConfig",
    "PPOTrainer",
    "ReplayBuffer",
    "gae_advantages",
    "ppo_loss",
]

"""RLHF engine: KV-cache generation + PPO (reference atorch/rl parity)."""

from .generation import SampleConfig, generate
from .ppo import (
    ActorCritic,
    PPOConfig,
    PPOTrainer,
    ReplayBuffer,
    gae_advantages,
    ppo_loss,
)

__all__ = [
    "SampleConfig",
    "generate",
    "ActorCritic",
    "PPOConfig",
    "PPOTrainer",
    "ReplayBuffer",
    "gae_advantages",
    "ppo_loss",
]

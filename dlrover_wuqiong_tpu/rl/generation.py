"""Autoregressive generation with a KV cache for the GPT family.

Parity: the reference RLHF engine's generation backend
(`atorch/atorch/rl/model_engine/model_engine.py:35` routes generation to a
vLLM backend; the capability is "sample responses from the actor policy").

TPU redesign: decode is a `lax.scan` over positions with static shapes —
(k, v) cache buffers of length `max_len` updated via dynamic_update_slice,
one fused step program for the whole sampling loop (no per-token dispatch).
The cached forward reuses the SAME parameter tree as `models/gpt.GPT`
(paths h_<i>/attn/..., wte, wpe, ln_f), so a policy trained with the
standard model generates without conversion.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..models.gpt import GPTConfig


def _ln(p, x, dtype):
    return nn.LayerNorm(dtype=dtype).apply({"params": p}, x)


def _dense(p, x, dtype):
    return (x @ p["kernel"].astype(dtype)) + p["bias"].astype(dtype)


def _cached_block(cfg: GPTConfig, p: Dict, x, cache_k, cache_v, pos):
    """One decoder block for ONE new token position with a KV cache.

    x: (B, 1, C); cache_k/v: (B, max_len, H, D); pos: scalar index.
    Returns (y, new_k, new_v).
    """
    B = x.shape[0]
    H, D = cfg.n_head, cfg.head_dim
    dtype = cfg.dtype
    h = _ln(p["ln_1"], x, dtype)
    qkv = _dense(p["attn"]["c_attn"], h, dtype)       # (B, 1, 3C)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, 1, H, D)
    k = k.reshape(B, 1, H, D)
    v = v.reshape(B, 1, H, D)
    cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, pos, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, pos, 0, 0))
    # attend over positions <= pos
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, cache_k) / jnp.sqrt(
        jnp.float32(D)).astype(dtype)
    mask = (jnp.arange(cache_k.shape[1]) <= pos)[None, None, None, :]
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    att = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(dtype)
    y = jnp.einsum("bhqk,bkhd->bqhd", att, cache_v).reshape(B, 1, H * D)
    y = _dense(p["attn"]["c_proj"], y, dtype)
    x = x + y
    h = _ln(p["ln_2"], x, dtype)
    h = _dense(p["mlp"]["c_fc"], h, dtype)
    h = jax.nn.gelu(h)
    h = _dense(p["mlp"]["c_proj"], h, dtype)
    return x + h, cache_k, cache_v


def _forward_one(cfg: GPTConfig, params: Dict, token, caches, pos):
    """token (B, 1) int → logits (B, vocab); updates all layer caches."""
    dtype = cfg.dtype
    tok = params["wte"]["embedding"][token].astype(dtype)    # (B, 1, C)
    pe = params["wpe"]["embedding"][pos][None, None].astype(dtype)
    x = tok + pe
    new_caches = []
    for i in range(cfg.n_layer):
        ck, cv = caches[i]
        x, ck, cv = _cached_block(cfg, params[f"h_{i}"], x, ck, cv, pos)
        new_caches.append((ck, cv))
    x = _ln(params["ln_f"], x, dtype)
    logits = jnp.einsum(
        "bte,ve->btv", x, params["wte"]["embedding"].astype(dtype))
    return logits[:, 0], new_caches


def _init_caches(cfg: GPTConfig, batch: int, max_len: int):
    return [(jnp.zeros((batch, max_len, cfg.n_head, cfg.head_dim),
                       cfg.dtype),
             jnp.zeros((batch, max_len, cfg.n_head, cfg.head_dim),
                       cfg.dtype)) for _ in range(cfg.n_layer)]


@dataclasses.dataclass(frozen=True)
class SampleConfig:
    max_new_tokens: int = 32
    temperature: float = 1.0
    top_k: int = 0           # 0 = full softmax
    eos_token: int = -1      # -1 = never stop early (static shapes)


def generate(cfg: GPTConfig, params: Dict, prompt: jax.Array,
             rng: jax.Array, sample: SampleConfig = SampleConfig()
             ) -> Tuple[jax.Array, jax.Array]:
    """Sample continuations. prompt (B, P) int32 → (tokens (B, P+N),
    logprobs (B, N)) — logprobs are the policy's per-sampled-token log
    probabilities (what PPO needs).
    """
    B, P = prompt.shape
    N = sample.max_new_tokens
    total = P + N
    if total > cfg.block_size:
        raise ValueError(f"prompt+new ({total}) exceeds block size "
                         f"{cfg.block_size}")
    caches = _init_caches(cfg, B, total)

    def prefill(carry, i):
        caches, _ = carry
        logits, caches = _forward_one(cfg, params, prompt[:, i][:, None],
                                      caches, i)
        return (caches, logits), None

    (caches, logits), _ = jax.lax.scan(
        prefill, (caches, jnp.zeros((B, cfg.vocab_size), jnp.float32)),
        jnp.arange(P))

    def _sample_token(logits, key):
        logits = logits.astype(jnp.float32) / max(sample.temperature, 1e-6)
        if sample.top_k > 0:
            kth = jax.lax.top_k(logits, sample.top_k)[0][:, -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        tok = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits, -1)
        return tok, jnp.take_along_axis(logp, tok[:, None], 1)[:, 0]

    def decode(carry, i):
        caches, logits, key = carry
        key, sub = jax.random.split(key)
        tok, logp = _sample_token(logits, sub)
        next_logits, caches = _forward_one(cfg, params, tok[:, None],
                                           caches, P + i)
        return (caches, next_logits, key), (tok, logp)

    (_, _, _), (toks, logps) = jax.lax.scan(
        decode, (caches, logits, rng), jnp.arange(N))
    tokens = jnp.concatenate([prompt, toks.T.astype(prompt.dtype)], axis=1)
    return tokens, logps.T

"""Autoregressive generation with a KV cache for the GPT family.

Parity: the reference RLHF engine's generation backend
(`atorch/atorch/rl/model_engine/model_engine.py:35` routes generation to a
vLLM backend; the capability is "sample responses from the actor policy").

TPU redesign: decode is a `lax.scan` over positions with static shapes —
(k, v) cache buffers of length `max_len` updated via dynamic_update_slice,
one fused step program for the whole sampling loop (no per-token dispatch).
The cached forward reuses the SAME parameter tree as `models/gpt.GPT`
(paths h_<i>/attn/..., wte, wpe, ln_f), so a policy trained with the
standard model generates without conversion.

This module is the ONE decode-step implementation in the repo: the
serving engine (serving/engine.py) drives the same `forward_step` with a
*vector* of per-slot positions (each batch row at its own sequence
position, continuous batching), while `generate` drives it with a scalar
position (all rows in lockstep, RLHF sampling).  The vector path writes
the new (k, v) through a one-hot `jnp.where` mask instead of
`dynamic_update_slice` — per-row dynamic starts are not expressible as
one slice, and masking keeps the step a single fused program (CLAUDE.md
cond-collective rule).  Every op is row-independent, which is what makes
a request's tokens bit-identical whether it decodes alone or packed in a
busy batch (tests/test_serving.py pins this).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..models.gpt import GPTConfig


def _ln(p, x, dtype):
    return nn.LayerNorm(dtype=dtype).apply({"params": p}, x)


def _dense(p, x, dtype):
    return (x @ p["kernel"].astype(dtype)) + p["bias"].astype(dtype)


def _cached_block(cfg: GPTConfig, p: Dict, x, cache_k, cache_v, pos):
    """One decoder block for ONE new token position with a KV cache.

    x: (B, 1, C); cache_k/v: (B, max_len, H, D); pos: scalar index (all
    rows at the same position) or (B,) int vector (per-row positions).
    Returns (y, new_k, new_v).
    """
    B = x.shape[0]
    H, D = cfg.n_head, cfg.head_dim
    dtype = cfg.dtype
    h = _ln(p["ln_1"], x, dtype)
    qkv = _dense(p["attn"]["c_attn"], h, dtype)       # (B, 1, 3C)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, 1, H, D)
    k = k.reshape(B, 1, H, D)
    v = v.reshape(B, 1, H, D)
    L = cache_k.shape[1]
    if jnp.ndim(pos) == 0:
        cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, pos, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, pos, 0, 0))
        # attend over positions <= pos
        mask = (jnp.arange(L) <= pos)[None, None, None, :]
    else:
        # per-row positions: write through a one-hot mask (a per-row
        # dynamic_update_slice start is not one slice) and build a
        # per-row causal mask — the whole step stays one fused program
        hit = (jnp.arange(L)[None, :] == pos[:, None])       # (B, L)
        cache_k = jnp.where(hit[:, :, None, None], k, cache_k)
        cache_v = jnp.where(hit[:, :, None, None], v, cache_v)
        mask = (jnp.arange(L)[None, :] <= pos[:, None])[:, None, None, :]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, cache_k) / jnp.sqrt(
        jnp.float32(D)).astype(dtype)
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    att = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(dtype)
    y = jnp.einsum("bhqk,bkhd->bqhd", att, cache_v).reshape(B, 1, H * D)
    y = _dense(p["attn"]["c_proj"], y, dtype)
    x = x + y
    h = _ln(p["ln_2"], x, dtype)
    h = _dense(p["mlp"]["c_fc"], h, dtype)
    h = jax.nn.gelu(h)
    h = _dense(p["mlp"]["c_proj"], h, dtype)
    return x + h, cache_k, cache_v


def forward_step(cfg: GPTConfig, params: Dict, token, caches, pos
                 ) -> Tuple[jax.Array, List[Tuple[jax.Array, jax.Array]]]:
    """token (B, 1) int → logits (B, vocab); updates all layer caches.

    ``pos`` is a scalar (lockstep decode, `generate`) or a (B,) vector
    (per-slot positions, serving/engine.py).  The token's (k, v) is
    written at ``pos`` and attention covers positions <= ``pos`` per row.
    """
    dtype = cfg.dtype
    tok = params["wte"]["embedding"][token].astype(dtype)    # (B, 1, C)
    if jnp.ndim(pos) == 0:
        pe = params["wpe"]["embedding"][pos][None, None].astype(dtype)
    else:
        pe = params["wpe"]["embedding"][pos][:, None].astype(dtype)
    x = tok + pe
    new_caches = []
    for i in range(cfg.n_layer):
        ck, cv = caches[i]
        x, ck, cv = _cached_block(cfg, params[f"h_{i}"], x, ck, cv, pos)
        new_caches.append((ck, cv))
    x = _ln(params["ln_f"], x, dtype)
    logits = jnp.einsum(
        "bte,ve->btv", x, params["wte"]["embedding"].astype(dtype))
    return logits[:, 0], new_caches


# backwards-compatible private alias (pre-serving name)
_forward_one = forward_step


def init_caches(cfg: GPTConfig, batch: int, max_len: int,
                dtype: Optional[Any] = None):
    """Zeroed per-layer (k, v) buffers: list of (B, max_len, H, D) pairs."""
    dtype = dtype if dtype is not None else cfg.dtype
    return [(jnp.zeros((batch, max_len, cfg.n_head, cfg.head_dim), dtype),
             jnp.zeros((batch, max_len, cfg.n_head, cfg.head_dim), dtype))
            for _ in range(cfg.n_layer)]


_init_caches = init_caches


def sample_token(logits, key, temperature: float = 1.0, top_k: int = 0):
    """One sampled token per row + its log-probability.

    temperature <= 0 means greedy argmax (deterministic, key unused).
    Shared by `generate` and the serving engine so "decoded alone" and
    "decoded in a busy batch" draw from the same program.
    """
    logits = logits.astype(jnp.float32)
    if temperature > 0:
        logits = logits / max(temperature, 1e-6)
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if temperature > 0:
        tok = jax.random.categorical(key, logits)
    else:
        tok = jnp.argmax(logits, axis=-1)
    logp = jax.nn.log_softmax(logits, -1)
    return tok, jnp.take_along_axis(logp, tok[:, None], 1)[:, 0]


@dataclasses.dataclass(frozen=True)
class SampleConfig:
    max_new_tokens: int = 32
    temperature: float = 1.0
    top_k: int = 0           # 0 = full softmax
    eos_token: int = -1      # -1 = never stop early (static shapes)


def generate(cfg: GPTConfig, params: Dict, prompt: jax.Array,
             rng: jax.Array, sample: SampleConfig = SampleConfig()
             ) -> Tuple[jax.Array, jax.Array]:
    """Sample continuations. prompt (B, P) int32 → (tokens (B, P+N),
    logprobs (B, N)) — logprobs are the policy's per-sampled-token log
    probabilities (what PPO needs).  Deterministic per key: the same
    (params, prompt, rng, sample) yields the same tokens on every call
    (tests/test_serving.py pins this).
    """
    B, P = prompt.shape
    N = sample.max_new_tokens
    total = P + N
    if total > cfg.block_size:
        raise ValueError(f"prompt+new ({total}) exceeds block size "
                         f"{cfg.block_size}")
    caches = init_caches(cfg, B, total)

    def prefill(carry, i):
        caches, _ = carry
        logits, caches = forward_step(cfg, params, prompt[:, i][:, None],
                                      caches, i)
        # f32 regardless of cfg.dtype: the carry init is f32 and scan
        # requires dtype-stable carries (bf16 configs hit this)
        return (caches, logits.astype(jnp.float32)), None

    (caches, logits), _ = jax.lax.scan(
        prefill, (caches, jnp.zeros((B, cfg.vocab_size), jnp.float32)),
        jnp.arange(P))

    def decode(carry, i):
        caches, logits, key = carry
        key, sub = jax.random.split(key)
        tok, logp = sample_token(logits, sub, sample.temperature,
                                 sample.top_k)
        next_logits, caches = forward_step(cfg, params, tok[:, None],
                                           caches, P + i)
        return (caches, next_logits.astype(jnp.float32), key), (tok, logp)

    (_, _, _), (toks, logps) = jax.lax.scan(
        decode, (caches, logits, rng), jnp.arange(N))
    tokens = jnp.concatenate([prompt, toks.T.astype(prompt.dtype)], axis=1)
    return tokens, logps.T

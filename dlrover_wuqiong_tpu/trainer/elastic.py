"""Worker-process side of elastic training: world init + trainer wrapper.

Parity: reference `dlrover/trainer/torch/elastic/trainer.py` (ElasticTrainer
:181 — fixed global batch via grad-accum under changing world size) and the
worker-side env contract consumed from the agent.

TPU redesign: `init_elastic()` reads the agent-injected env, initializes
`jax.distributed` when the world spans hosts, and returns an `ElasticContext`
that the training script uses for mesh construction, step reporting, and
dynamic-sharding dataloaders.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

from ..agent.master_client import MasterClient
from ..common.constants import NodeEnv
from ..common.log import get_logger

logger = get_logger("elastic_trainer")


@dataclass
class WorldInfo:
    process_id: int = 0
    num_processes: int = 1
    coordinator_addr: str = ""
    node_id: int = 0
    node_rank: int = 0
    restart_count: int = 0


def get_world_info() -> WorldInfo:
    return WorldInfo(
        process_id=int(os.getenv(NodeEnv.PROCESS_ID, "0")),
        num_processes=int(os.getenv(NodeEnv.NUM_PROCESSES, "1")),
        coordinator_addr=os.getenv(NodeEnv.COORDINATOR_ADDR, ""),
        node_id=int(os.getenv(NodeEnv.NODE_ID, "0")),
        node_rank=int(os.getenv(NodeEnv.NODE_RANK, "0")),
        restart_count=int(os.getenv(NodeEnv.RESTART_COUNT, "0")),
    )


class ElasticContext:
    """Per-worker handle to the elastic world + master services."""

    def __init__(self, world: WorldInfo,
                 master_client: Optional[MasterClient]):
        self.world = world
        self.mc = master_client
        self._step_report_interval = 15.0
        self._last_report = 0.0
        self._warm_pool = None

    @property
    def is_distributed(self) -> bool:
        return self.world.num_processes > 1

    @property
    def process_id(self) -> int:
        return self.world.process_id

    def report_step(self, step: int, force: bool = False):
        """Throttled global-step reporting feeding the SpeedMonitor."""
        if self.mc is None:
            return
        now = time.time()
        if force or now - self._last_report > self._step_report_interval:
            try:
                self.mc.report_global_step(step)
                self._last_report = now
            except Exception:  # noqa: BLE001
                logger.debug("step report failed", exc_info=True)

    def report_loss(self, step: int, loss: float):
        """Feed the master's loss-spike detector (diagnosis/loss_spike.py).

        Reported at the trainer's logging cadence — the detector works on
        a trailing window of samples, not every step."""
        if self.mc is None:
            return
        try:
            import json as _json

            self.mc.report_diagnosis(
                "loss", _json.dumps({"step": step, "loss": float(loss)}))
        except Exception:  # noqa: BLE001
            logger.debug("loss report failed", exc_info=True)

    def report_op_profile(self, evidence: str):
        """Push top-slow-collective evidence (utils/xplane.py) to the
        master's diagnosis chain — xpu_timer parity for hang localization."""
        if self.mc is None or not evidence:
            return
        try:
            self.mc.report_diagnosis("op_profile", evidence)
        except Exception:  # noqa: BLE001
            logger.debug("op profile report failed", exc_info=True)

    def sharding_client(self, dataset_name: str, batch_size: int,
                        dataset_size: int, **kwargs):
        from ..agent.sharding_client import IndexShardingClient

        if self.mc is None:
            return None
        return IndexShardingClient(self.mc, dataset_name, batch_size,
                                   dataset_size, **kwargs)

    def enable_warm_restarts(self, result, global_batch: int,
                             seq_len: int, model=None,
                             fused_steps: Optional[int] = None):
        """Publish this world's compile spec and start warming the worlds
        one failure away (auto/warm_pool.py).

        `result` is the AccelerateResult driving training; `global_batch`
        and `seq_len` pin the abstract batch the degraded compile must
        match (the framework holds the GLOBAL batch fixed across world
        changes — GradientAccumulator below).  Returns the WarmPool, or
        None when the model/strategy cannot be replayed in a warm child
        (non-registry model, callable-bearing strategy) — warming is an
        optimization, never a requirement.
        """
        import jax

        from ..auto.compile_cache import (
            active_cache_dir,
            default_cache_dir,
        )
        from ..auto.warm_pool import (
            WarmPool,
            WarmSpec,
            model_spec,
            publish_current_spec,
        )

        if getattr(result, "strategy_spec", None) is None:
            logger.info("warm restarts unavailable: strategy is not "
                        "replayable in a warm child")
            return None
        mspec = model_spec(model if model is not None else result.model)
        if mspec is None:
            logger.info("warm restarts unavailable: model not in the "
                        "warm-pool registry (gpt/llama)")
            return None
        cache_dir = active_cache_dir() or default_cache_dir()
        if fused_steps is None:
            # default to the K the result runs with (the trainer's
            # auto-tuned K when fusion is on) — a warm entry at the wrong
            # K is a cache miss for the restarted worker
            fused_steps = getattr(result, "fused_steps", 1)
        spec = WarmSpec(
            n_devices=len(jax.devices()),
            strategy=result.strategy_spec, model=mspec,
            batch_shape=[int(global_batch), int(seq_len)],
            accum_steps=result.strategy.accum_steps,
            platform=jax.default_backend(),
            fused_steps=max(1, int(fused_steps)))
        publish_current_spec(cache_dir, spec)
        if self._warm_pool is None:
            self._warm_pool = WarmPool(cache_dir)
        local = int(os.getenv(NodeEnv.LOCAL_DEVICE_COUNT, "0")) or \
            max(1, len(jax.local_devices()))
        self._warm_pool.warm_degraded(
            spec, num_nodes=self.world.num_processes,
            devices_per_node=local)
        return self._warm_pool


_context: Optional[ElasticContext] = None


def init_elastic(connect_master: bool = True) -> ElasticContext:
    """Initialize the JAX world from the agent's env contract.

    Call once at the top of the training script (before creating arrays).
    """
    global _context
    if _context is not None:
        return _context
    world = get_world_info()
    # warm restarts: compile through the persistent cache from the first
    # trace — a relaunched worker on a known topology then deserializes
    # its train step from disk instead of recompiling
    from ..auto.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    if world.num_processes > 1 and world.coordinator_addr:
        import jax

        logger.info("jax.distributed.initialize(coord=%s, n=%d, id=%d)",
                    world.coordinator_addr, world.num_processes,
                    world.process_id)
        jax.distributed.initialize(
            coordinator_address=world.coordinator_addr,
            num_processes=world.num_processes,
            process_id=world.process_id)
    mc = None
    master_addr = os.getenv(NodeEnv.MASTER_ADDR, "")
    if connect_master and master_addr:
        mc = MasterClient(master_addr, world.node_id)
    _context = ElasticContext(world, mc)
    return _context


def reset_elastic_context():
    global _context
    if _context is not None and _context.mc is not None:
        _context.mc.close()
    if _context is not None and _context._warm_pool is not None:
        _context._warm_pool.stop()
    _context = None


class GradientAccumulator:
    """Keep the global batch fixed as world size changes.

    Parity: reference ElasticTrainer/GradientState (trainer.py:53-181): with
    `global_batch_size` fixed, each process accumulates
    `global_batch_size / (num_processes * per_step_batch)` micro-steps before
    applying the update.  In JAX this folds into the train step as a
    `lax.scan` over micro-batches (compiler-friendly, no Python loop).
    """

    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 num_processes: int):
        denom = micro_batch_size * max(1, num_processes)
        self.accum_steps = max(1, global_batch_size // denom)
        self.global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def __repr__(self):
        return (f"GradientAccumulator(accum={self.accum_steps}, "
                f"global={self.global_batch_size})")

"""High-level Trainer — one object from model to trained checkpoint.

Parity: reference `atorch/atorch/trainer/atorch_trainer.py:136`
(`AtorchTrainer`, the HF-Trainer-style loop over auto_accelerate) and
`atorch_args.py` (TrainingArgs).

Composes the whole stack: `auto_accelerate` (strategy → compiled sharded
step), elastic context (rendezvous world + dynamic sharding when launched
by the agent), flash checkpoint (auto-resume + save cadence +
save-on-exit), the step profiler (always-on timing + windowed traces), lr
schedules, and periodic evaluation.

The hot loop runs the fused K-step driver by default
(`TrainingArgs.fused_steps=0` auto-tunes K from measured step time vs.
measured dispatch overhead): one dispatch and one metrics readback per K
optimizer steps, batches staged K-at-a-time by `FusedBatchStager` while
the current fusion executes.  Every elastic hook — logging, checkpoint
saves, shm staging, eval, master config polls, graceful SIGTERM
preemption, and the rollback resume — fires at fusion boundaries only;
K is clamped to divide the active cadences so those boundaries land
exactly where the unfused loop would have fired them.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Dict, Iterable, Optional

import numpy as np

from ..common.log import get_logger

logger = get_logger("trainer")


@dataclasses.dataclass
class TrainingArgs:
    """Parity: reference atorch_args.py — the knobs of the training loop."""

    output_dir: str = "/tmp/dwt-run"
    max_steps: int = 1000
    global_batch_size: int = 32
    seq_len: int = 1024
    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    warmup_steps: int = 100
    lr_schedule: str = "cosine"  # "cosine" | "linear" | "constant"
    min_lr_ratio: float = 0.1
    grad_accum_steps: int = 1
    strategy: Optional[list] = None          # auto_accelerate strategy
    logging_steps: int = 10
    save_steps: int = 200
    eval_steps: int = 0                      # 0 = no periodic eval
    max_eval_batches: int = 32
    seed: int = 0
    resume: bool = True                      # auto-resume from output_dir
    # "bf16" halves checkpoint bytes end to end (D2H staging, disk,
    # restore H2D) — lossy for f32 state (checkpointer docstring); for
    # restore-latency-critical deployments over slow host links
    ckpt_wire_dtype: Optional[str] = None

    def __post_init__(self):
        if self.ckpt_wire_dtype not in (None, "bf16"):
            # fail BEFORE Trainer runs param init + compile (CLAUDE.md:
            # bad knobs error at construction time, not minutes later)
            raise ValueError(
                f"unsupported ckpt_wire_dtype {self.ckpt_wire_dtype!r}; "
                f"use 'bf16' or None")
        if self.fused_steps < 0:
            raise ValueError(
                f"fused_steps must be >= 0 (0 = auto-tune), got "
                f"{self.fused_steps}")
        if self.perf_window_every < 0 or self.perf_regress_windows < 1 \
                or not 0.0 < self.perf_overhead_budget <= 1.0:
            raise ValueError(
                f"bad perf-observatory knobs: perf_window_every="
                f"{self.perf_window_every} (>= 0), perf_regress_windows="
                f"{self.perf_regress_windows} (>= 1), perf_overhead_budget="
                f"{self.perf_overhead_budget} (in (0, 1])")
        if self.tune_variants < 0 or not 0.0 <= self.tune_hysteresis < 1.0:
            raise ValueError(
                f"bad autotuner knobs: tune_variants={self.tune_variants} "
                f"(>= 0; 0 = off), tune_hysteresis={self.tune_hysteresis} "
                f"(in [0, 1))")
        if self.tune_loss_bound <= 0.0:
            raise ValueError(
                f"tune_loss_bound must be > 0 (relative divergence "
                f"margin), got {self.tune_loss_bound}")
        if self.tune_numerics and self.tune_variants <= 0:
            raise ValueError(
                "tune_numerics requires the autotuner "
                "(tune_variants > 0) — the fp8 quant axis only runs "
                "under the loss-divergence guard")
    profile_trace_dir: str = ""              # jax.profiler window target
    profile_start_step: int = -1
    profile_end_step: int = -1
    save_on_exit: bool = True
    tune_config_steps: int = 25              # poll master's paral config
    # every k steps (0 = off); applies dataloader batch size + ckpt cadence
    probe_interval: float = 30.0             # device-queue liveness probe
    # cadence for hang localization (0 = off; active only under the agent)
    # fused multi-step dispatch (trainer/train_step.py): 0 = auto-tune K
    # from measured step time vs. measured dispatch overhead (target <2%
    # overhead, clamped to a divisor of the active hook cadences so the
    # checkpoint cadence stays exactly reachable); 1 = unfused; K>1 =
    # explicit.  Elastic hooks (save/eval/logging/tune/preemption) fire
    # at fusion boundaries only.
    fused_steps: int = 0
    # SIGTERM (the agent's preemption signal, agent/elastic_agent.py)
    # finishes the in-flight fusion, saves, and exits cleanly instead of
    # dying mid-step
    graceful_preemption: bool = True
    # stage the train state to shm (save_to_memory) every N steps — at
    # fusion boundaries when fused — so the agent's save-on-failure
    # persists the last boundary; 0 = off
    flash_stage_steps: int = 0
    # poll the master's adaptive fault-tolerance decision (brain/policy.py)
    # every N steps — at fusion boundaries only; 0 = off.  Applies ckpt
    # cadence / restore-tier / replica knobs immediately; a fused-K change
    # first pre-compiles through the warm pool (K is part of the compile
    # cache key) and cuts over only once the entry is ready.
    policy_steps: int = 0
    # perf observatory (telemetry/perf.py): every Nth LOGGING boundary —
    # the boundary that already carries the one metrics readback — wraps
    # its fused dispatch in a StepProfiler window, folds the xplane op
    # split into a PerfSnapshot, and feeds the baseline store + regression
    # sentinel.  Windows self-limit to <perf_overhead_budget of wall and
    # never add a device readback.  0 = off.
    perf_window_every: int = 8
    perf_regress_windows: int = 3            # M consecutive beyond-MAD
    perf_overhead_budget: float = 0.01       # max profiling wall fraction
    # online variant autotuner (auto/tuner.py): N > 0 A/B-measures the
    # DWT_FA_* variant space with N perf-observatory windows per
    # candidate, interleaved (chip-load drift is ±10% run to run —
    # CLAUDE.md), each candidate pre-compiled through the warm pool
    # before its first measured window, winner persisted to
    # $ckpt_dir/perf/tuning.json so later runs start tuned.  0 = off.
    # Requires the perf observatory (perf_window_every > 0).
    tune_variants: int = 0
    tune_hysteresis: float = 0.05            # challenger must win by this
    # opt-in the NUMERICS-CHANGING quant axis (fp8 dense matmul via
    # DWT_FP8_DENSE) into the search.  Unlike the layout-neutral
    # DWT_FA_*/remat axes, fp8 changes the loss trajectory, so it only
    # runs under the tuner's loss-divergence guard: a measured window
    # whose loss rises more than tune_loss_bound (relative) above the
    # rolling reference median auto-reverts the variant — cut back to
    # the incumbent at the same boundary, revert journaled as a
    # PolicyDecision-style entry.  False = fp8 never enters the search.
    tune_numerics: bool = False
    tune_loss_bound: float = 0.05            # relative divergence margin
    # overlap the logging boundary's host work (metrics readback, perf
    # window close, master reports) with the next fused dispatch via the
    # metrics pump thread; False = inline (sync).  User callbacks force
    # the inline path regardless: they are the loop's synchronous
    # surface (request_stop, config pushes) and must observe the
    # boundary before the next fusion dispatches.
    async_metrics: bool = True


class _MetricsPump:
    """Single background consumer for the logging boundary's host work.

    Overlap: the per-fusion metrics readback (`float(loss)`), the perf
    window close (xplane parse + baseline publish fsync), the master
    reports and the user callbacks move off the hot loop onto ONE daemon
    thread draining a bounded queue — the next fused dispatch overlaps
    the host work instead of serializing behind it.  Invariants:

    - ledger CREDITS stay on the main thread at fusion boundaries
      (CLAUDE.md telemetry rules): a job ships the snapshot dict taken
      at its boundary, never the live ledger;
    - `metrics` is an executable OUTPUT — donation-immune (CLAUDE.md),
      so reading it back after the next dispatch has donated the inputs
      is safe;
    - at most `maxsize` boundaries ride in flight (put() backpressures
      the main loop instead of queueing unbounded device values), and at
      most ONE open perf window (the trainer gates `maybe_open` on
      `windows_inflight() == 0` — jax traces can't nest);
    - a consume error leaves `windows_inflight` elevated on purpose: a
      half-closed window may still hold the profiler trace, and a stuck
      gate (no further windows) is safe where a nested trace is not;
    - the RpcClient serializes frames under its own lock, so master
      verbs from this thread never interleave with the main loop's;
    - joined from train()'s finally (conftest thread-leak guard).

    `enabled=False` (async_metrics off) consumes inline on the caller's
    thread — same code path, synchronous semantics.
    """

    def __init__(self, trainer: "Trainer", enabled: bool = True,
                 maxsize: int = 2):
        import queue
        import threading

        self._trainer = trainer
        self._lock = threading.Lock()
        self._last_loss = float("nan")
        self._windows_inflight = 0
        self._drained = 0
        self._errors = 0
        self._q: Any = None
        self._thread: Any = None
        if enabled:
            self._q = queue.Queue(maxsize=maxsize)
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="dwt-metrics-pump")
            self._thread.start()

    def submit(self, job: Dict[str, Any]) -> None:
        if job.get("pw") is not None:
            with self._lock:
                self._windows_inflight += 1
        if self._thread is None:
            # inline path: exceptions propagate — a raising user callback
            # must abort training exactly as the pre-pump loop did
            self._note_done(job, self._trainer._consume_boundary(job))
        else:
            self._q.put(job)

    def windows_inflight(self) -> int:
        with self._lock:
            return self._windows_inflight

    def last_loss(self) -> float:
        with self._lock:
            return self._last_loss

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"drained": self._drained, "errors": self._errors}

    def stop(self, timeout: float = 60.0) -> None:
        """Flush queued boundaries and join (train()'s finally)."""
        if self._thread is None:
            return
        self._q.put(None)
        self._thread.join(timeout)
        self._thread = None

    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            self._consume(job)

    def _consume(self, job: Dict[str, Any]) -> None:
        # async path only: the pump can't propagate across threads, so a
        # failed boundary is logged and counted, never fatal
        try:
            loss = self._trainer._consume_boundary(job)
        except Exception:  # noqa: BLE001 — see docstring
            logger.exception("metrics pump: boundary %s failed",
                             job.get("step"))
            with self._lock:
                self._errors += 1
            return
        self._note_done(job, loss)

    def _note_done(self, job: Dict[str, Any], loss: float) -> None:
        with self._lock:
            self._last_loss = loss
            self._drained += 1
            if job.get("pw") is not None:
                self._windows_inflight -= 1


class Trainer:
    """HF-style: Trainer(model, args, train_data[, eval_data]).train().

    `train_data` / `eval_data`: iterables yielding host batches — dicts of
    arrays shaped (global_batch, ...) — or callables `(step) -> batch`
    (useful for synthetic/streaming data).
    """

    def __init__(self, model, args: TrainingArgs,
                 train_data: Any, eval_data: Any = None,
                 optimizer=None, loss_fn: Optional[Callable] = None,
                 callbacks: Optional[list] = None):
        import optax

        self.model = model
        self.args = args
        self.train_data = train_data
        self.eval_data = eval_data
        self.callbacks = callbacks or []
        self._loss_fn = loss_fn

        # elastic context: no-op when not launched by the agent
        from .elastic import init_elastic

        self.ctx = init_elastic()
        # hot-swap participant (trainer/hotswap.py) — attached by the
        # agent/drill when a replica ring exists; polled at fusion
        # boundaries alongside the policy decision
        self.hotswap = None

        schedule = self._make_schedule(optax)
        self.optimizer = optimizer or optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.adamw(schedule, weight_decay=args.weight_decay))

        from ..auto.accelerate import auto_accelerate

        self.res = auto_accelerate(
            model, optimizer=self.optimizer, strategy=args.strategy,
            loss_fn=loss_fn, accum_steps=args.grad_accum_steps,
            seq_len=args.seq_len)
        self.state = self.res.state

        from ..checkpoint.checkpointer import FlashCheckpointer

        self.ckpt = FlashCheckpointer(
            os.path.join(args.output_dir, "checkpoints"),
            job_name=os.getenv("DWT_JOB_NAME", "dwt"),
            wire_dtype=args.ckpt_wire_dtype)

        from ..utils.profiler import StepProfiler

        self.profiler = StepProfiler(
            trace_dir=args.profile_trace_dir or None,
            start_step=args.profile_start_step,
            end_step=args.profile_end_step)

        # perf observatory: in-train profiling windows + baseline store +
        # regression sentinel (telemetry/perf.py).  Registered as the
        # process singleton so flight-recorder dumps embed the latest
        # PerfSnapshot.  The baseline lives next to the checkpoints
        # ($ckpt_dir/perf/baseline.json) so it survives restarts with the
        # run, keyed by the full executable identity — a strategy / K /
        # backend / trace-env change never pollutes another key's stats.
        self._perf = None
        if args.perf_window_every > 0:
            from ..telemetry.perf import PerfObservatory, set_observatory

            self._perf = PerfObservatory(
                ckpt_dir=os.path.join(args.output_dir, "checkpoints"),
                every=args.perf_window_every,
                m_consecutive=args.perf_regress_windows,
                overhead_budget=args.perf_overhead_budget,
                on_event=self._on_perf_event,
                job_name=os.getenv("DWT_JOB_NAME", "dwt"))
            set_observatory(self._perf)

        # master-tuned runtime config (batch size / ckpt cadence) — closes
        # the loop master → agent ParalConfigTuner → file → trainer.
        # Gated on the env path the agent's tuner exports: a standalone run
        # must not pick up a dead job's file at the shared default path.
        from ..agent.config_tuner import ParalConfigListener
        from ..common.constants import ConfigPath

        self._tune_listener = (
            ParalConfigListener()
            if args.tune_config_steps and os.getenv(ConfigPath.ENV_PARAL_CONFIG)
            else None)

        # adaptive-policy state: last decision id applied (master ids are
        # monotonic — replays/duplicates after a reconnect are skipped),
        # a fused-K change parked until its warm-pool entry is ready, and
        # the applied-decision log (tests + post-mortem)
        self._policy_last_id = 0
        self._policy_pending_k: Optional[int] = None
        self._warm_pool = None
        self.policy_applied: list = []

        # online variant autotuner (auto/tuner.py): search only when no
        # winner is persisted for this executable FAMILY — later runs
        # start tuned.  Needs the perf observatory (windows are the
        # scorer's only signal).
        self._tuner = None
        self._tuner_reported = 0  # decisions surfaced so far (reverts
        # land mid-search, the winner at the end — incremental count)
        self._variant_active = "default"
        if args.tune_variants > 0 and self._perf is not None:
            self._init_tuner()

        # device-queue liveness probe → master hang localization
        self._prober = None
        if args.probe_interval > 0 and self.ctx.mc is not None:
            from ..diagnosis.probe import DeviceProber

            self._prober = DeviceProber(self.ctx.mc,
                                        interval=args.probe_interval)
            self._prober.start()

    # ------------------------------------------------------ paral-config

    def _batch_divisor(self) -> int:
        """A tuned batch size must divide the data-parallel axis product
        (batch-dim sharding) and the pipeline microbatch count."""
        import math

        mesh = self.res.mesh
        div = 1
        for ax in ("dp", "fsdp"):
            div *= mesh.shape.get(ax, 1)
        micro = getattr(self.res.model, "num_microbatches", 1)
        return div * micro // math.gcd(div, micro)

    def _apply_tuned_config(self, cfg: Dict) -> None:
        """Apply a master-pushed ParallelConfig between steps.

        Parity: reference elastic/dataloader.py:97-133 (batch size) +
        paral_config_tuner ckpt cadence.  Mesh-shape changes need a restart
        and are only logged here (the agent's restart path re-plans)."""
        bs = int(cfg.get("dataloader_batch_size") or 0)
        if bs > 0 and hasattr(self.train_data, "update_batch_size") and \
                bs != getattr(self.train_data, "batch_size", bs):
            div = self._batch_divisor()
            if bs % div:
                logger.warning(
                    "ignoring tuned batch size %d: not divisible by %d "
                    "(data-axis sharding x pipeline microbatches)", bs, div)
            else:
                self.train_data.update_batch_size(bs)
        ckpt_every = int(cfg.get("ckpt_interval_steps") or 0)
        if ckpt_every > 0 and ckpt_every != self.args.save_steps:
            logger.info("ckpt cadence %d -> %d steps",
                        self.args.save_steps, ckpt_every)
            self.args.save_steps = ckpt_every
        if cfg.get("mesh_shape"):
            logger.info("master proposes mesh %s (applies on next restart)",
                        cfg["mesh_shape"])

    # ------------------------------------------------- adaptive policy

    def _poll_mesh_transition(self) -> None:
        """Drive the hot-swap participant (trainer/hotswap.py) — fires
        only at fusion boundaries, on the policy-poll cadence.  The
        participant is attached by the agent/drill (it carries the
        replica ring + re-shard hooks the trainer doesn't own); without
        one this is a no-op."""
        hs = getattr(self, "hotswap", None)
        if hs is None:
            return
        try:
            hs.poll()
        except Exception:  # noqa: BLE001 — a broken participant must
            # degrade to classic restart-the-world, never kill the loop
            logger.exception("hot-swap poll failed")

    def _poll_policy(self) -> None:
        """Fetch the master's current PolicyDecision (polling verb — a
        dead master degrades to the last applied knobs, never an error)
        and apply it if it is new."""
        try:
            d = self.ctx.mc.get_policy_decision()
        except Exception:  # noqa: BLE001 — degraded mode keeps training
            return
        did = int(getattr(d, "decision_id", 0) or 0)
        if did <= self._policy_last_id:
            return
        self._policy_last_id = did
        self._apply_policy_decision(d)

    def _apply_policy_decision(self, d) -> None:
        """Apply one PolicyDecision's knobs.  Cadence/tier/replica apply
        immediately (next boundary / next backup / next load); a fused-K
        request is PARKED in _policy_pending_k — the loop cuts over only
        after _prewarm_fused_k confirms a ready warm-pool entry, because
        K changes the HLO and a cold mid-run compile would cost more than
        any cadence win."""
        applied: Dict[str, Any] = {"decision_id": d.decision_id}
        k_active = int(getattr(self, "_fused_k_active", 0) or 1)
        interval = int(getattr(d, "ckpt_interval_steps", 0) or 0)
        if interval > 0:
            if k_active > 1 and interval % k_active:
                # boundary-reachable: round UP to a fusion multiple so the
                # cadence the policy paid for is never silently skipped
                interval = ((interval + k_active - 1) // k_active) * k_active
            if interval != self.args.save_steps:
                logger.info("policy #%d: ckpt cadence %d -> %d steps",
                            d.decision_id, self.args.save_steps, interval)
                self.args.save_steps = interval
            applied["ckpt_interval_steps"] = interval
        tier = getattr(d, "preferred_tier", "") or ""
        if tier:
            try:
                self.ckpt.set_preferred_tier(tier)
                applied["preferred_tier"] = tier
            except ValueError as e:
                logger.warning("policy #%d: %s", d.decision_id, e)
        replicas = int(getattr(d, "replica_count", -1))
        if replicas >= 0:
            self.ckpt.set_replica_count(replicas)
            applied["replica_count"] = replicas
        k_req = int(getattr(d, "fused_steps", 0) or 0)
        if k_req > 0 and k_req != k_active:
            cad = self._hook_cadence()
            if k_req > 1 and cad and cad % k_req:
                logger.warning(
                    "policy #%d: fused_steps=%d does not divide the hook "
                    "cadence gcd %d — keeping K=%d", d.decision_id, k_req,
                    cad, k_active)
            elif getattr(self.res, "_fused_factory", None) is None \
                    and k_req > 1:
                logger.warning("policy #%d: no fused driver for this "
                               "strategy — keeping K=%d", d.decision_id,
                               k_active)
            else:
                self._policy_pending_k = k_req
                applied["fused_steps_requested"] = k_req
        self.policy_applied.append(applied)

    def _prewarm_fused_k(self, k: int) -> bool:
        """True when switching the fused driver to K will hit the compile
        cache.  Without a warm-pool cache dir there is nothing to consult
        (tests / standalone runs) — allow the cutover.  Otherwise derive
        the target spec from the published current spec at the new K:
        ready entry → go; else kick an async warm compile and stay at the
        current K until a later boundary finds it ready."""
        cache_dir = os.getenv("DWT_COMPILE_CACHE_DIR", "")
        if not cache_dir:
            return True
        from ..auto.warm_pool import WarmPool, load_current_spec

        if self._warm_pool is None:
            self._warm_pool = WarmPool(cache_dir)
        spec = load_current_spec(cache_dir)
        if spec is None:
            return True  # nothing published: no warm entry to wait for
        if int(getattr(spec, "fused_steps", 1)) != k:
            spec = dataclasses.replace(spec, fused_steps=k)
        if self._warm_pool._ready_entry_for(spec.spec_key()) is not None:
            return True
        self._warm_pool.warm_async(spec)
        logger.info("policy: warming fused_steps=%d in the pool — cutover "
                    "deferred until the entry is ready", k)
        return False

    # ------------------------------------------------- variant autotuner

    def _model_dims_fingerprint(self) -> str:
        """Width×depth fingerprint of the model config for shape_class
        ("d768x12"); "" when the model exposes no recognized dims."""
        cfg = getattr(self.model, "config", None)
        if cfg is None:
            return ""
        width = getattr(cfg, "n_embd", None) or \
            getattr(cfg, "hidden_size", None)
        depth = getattr(cfg, "n_layer", None) or \
            getattr(cfg, "num_layers", None)
        if not width or not depth:
            return ""
        return f"d{int(width)}x{int(depth)}"

    def _init_tuner(self) -> None:
        """Start tuned when a winner is persisted for this executable
        family (strategy + backend, excluding the tunables); otherwise
        build the interleaved search over the widened variant space.
        Corrupt/missing tuning.json falls through to re-learn (the store
        tolerates it) — never fatal.

        Winner lookup is PER-SHAPE first (batch × seq × model dims —
        ROADMAP 4c): the exact-geometry winner is preferred, the
        family-wide winner serves unseen shapes, and v1 shapeless stores
        keep serving as the fallback without re-learning.  The search
        space adds the remat-policy ladder when the model remats and
        the fp8 quant axis behind `tune_numerics` (loss-divergence
        guard armed via `tune_loss_bound`); candidate ORDER comes from
        the baseline store's op-category split (ROADMAP 4d) — a
        matmul-bound profile tries quant first, a collective-bound one
        pack/stream first.
        """
        import jax

        from ..auto import tuner as vt

        a = self.args
        backend = jax.default_backend()
        family = vt.family_key(self._strategy_fingerprint(), backend)
        shape = vt.shape_class(a.global_batch_size, a.seq_len,
                               self._model_dims_fingerprint())
        store = vt.TuningStore(
            vt.tuning_path(os.path.join(a.output_dir, "checkpoints")))
        winner = store.lookup(family, shape)
        if winner is not None:
            # apply before the first dispatch: the fused cache re-keys on
            # the env signature, so this retraces exactly once and the
            # compile credit below keeps it out of the baselines
            env = winner.get("exe_env") or winner.get("env") or {}
            vt.apply_variant({str(k): str(v) for k, v in env.items()})
            self._variant_active = str(winner.get("variant") or "default")
            if self._perf is not None:
                self._perf.set_tuned_variant(self._variant_active)
            k_win = int(winner.get("fused_steps") or 0)
            cad = self._hook_cadence()
            if k_win > 1 and a.fused_steps == 0 and \
                    (not cad or cad % k_win == 0):
                a.fused_steps = k_win  # skip the K re-measurement too
            logger.info("tuner: starting on persisted winner %r "
                        "(family %s, shape %s%s)", self._variant_active,
                        family, shape,
                        "" if winner.get("shape_class") == shape
                        else " via family fallback")
            return
        cfg = getattr(self.model, "config", None)
        remat_policies = ()
        if cfg is not None and getattr(cfg, "remat", False):
            # only non-offload policies: offload variants change the
            # host-transfer profile, not a pure compute trade — keep the
            # online ladder to the HBM-resident policies
            remat_policies = ("dots", "save_names")
        hint = None
        if self._perf is not None:
            hint = self._perf.store.aggregate_categories() or None
        self._tuner = vt.VariantAutotuner(
            vt.default_variants(backend, numerics=a.tune_numerics,
                                remat_policies=remat_policies),
            store=store, family=family,
            windows_per_variant=a.tune_variants,
            hysteresis=a.tune_hysteresis,
            shape_class=shape,
            loss_bound=a.tune_loss_bound if a.tune_numerics else 0.0,
            category_hint=hint)
        self._tuner.bind_executable_context(
            strategy_fingerprint=self._strategy_fingerprint(),
            fused_steps=max(a.fused_steps, 1), backend=backend)

    def _variant_full_env(self, variant) -> Dict[str, str]:
        """Full TRACE_ENV_VARS assignment for a variant — vars the
        variant leaves alone map to "" so `apply_variant` DELETES them
        (unset is a distinct value: DWT_FA_STREAMED unset means the
        sequence-length heuristic, not off)."""
        from ..auto.compile_cache import TRACE_ENV_VARS

        return {k: str(variant.env.get(k, "")) for k in TRACE_ENV_VARS}

    def _maybe_apply_variant(self, fused_k) -> None:
        """Fusion-boundary variant cutover, following the tuner's
        interleave schedule.  The next candidate pre-warms through the
        warm pool (its env rides WarmSpec.trace_env — every variant is a
        distinct compile-cache key), and the env flip happens only when
        the entry is ready, so no measured window ever pays a cold
        compile.  When the search settles, the decision surfaces as
        PolicyDecision-style history (policy_applied + a node event)
        with the measured before/after medians."""
        tuner = self._tuner
        if tuner is None:
            return
        with tuner._lock:
            pending = list(tuner.decisions[self._tuner_reported:])
        if pending:
            # incremental: loss-divergence REVERTS land mid-search, the
            # winner at the end — each surfaces exactly once
            self._tuner_reported += len(pending)
            from ..brain.policy import tuner_decision_effects

            effects = tuner_decision_effects(pending)
            self.policy_applied.extend(effects)
            if effects and self.ctx.mc is not None:
                import json as _json

                for eff in effects:
                    try:  # telemetry never kills the run
                        self.ctx.mc.report_node_event(
                            "tuner-decision",
                            _json.dumps(eff, sort_keys=True),
                            level="info")
                    except Exception:  # noqa: BLE001
                        pass
        desired = tuner.current()
        if desired.name == self._variant_active:
            return
        if not self._prewarm_variant(desired, fused_k):
            return  # entry still compiling: stay put, poll next boundary
        from ..auto.tuner import apply_variant

        apply_variant(self._variant_full_env(desired))
        self._variant_active = desired.name
        if self._perf is not None:
            self._perf.set_tuned_variant(desired.name)
        tuner.cutover(desired)
        if desired.fused_steps and fused_k is not None and \
                desired.fused_steps != (fused_k or 1):
            # K rides the existing policy cutover path (stager rebuild,
            # cadence clamp) — same boundary discipline as a DWT_FA_* flip
            self._policy_pending_k = int(desired.fused_steps)

    def _prewarm_variant(self, variant, fused_k) -> bool:
        """True when the variant's executable is already live here (its
        (K, env) mode was dispatched before) or the warm pool holds a
        ready entry.  No cache dir / no published spec → allow: the
        compile-credit path still keeps the first dispatch out of the
        perf windows via _compiled_modes."""
        from ..auto.tuner import env_signature, variant_env

        k = int(variant.fused_steps or (fused_k or 1))
        with variant_env(self._variant_full_env(variant)):
            mode = (k, env_signature())
        if mode in self._compiled_modes:
            return True
        cache_dir = os.getenv("DWT_COMPILE_CACHE_DIR", "")
        if not cache_dir:
            return True
        from ..auto.warm_pool import WarmPool, load_current_spec

        if self._warm_pool is None:
            self._warm_pool = WarmPool(cache_dir)
        spec = load_current_spec(cache_dir)
        if spec is None:
            return True
        spec = dataclasses.replace(
            spec, fused_steps=k,
            trace_env=self._variant_full_env(variant))
        if self._warm_pool._ready_entry_for(spec.spec_key()) is not None:
            return True
        self._warm_pool.warm_async(spec)
        logger.info("tuner: warming variant %r in the pool — cutover "
                    "deferred until the entry is ready", variant.name)
        return False

    # ------------------------------------------------------------- schedule

    def _make_schedule(self, optax):
        a = self.args
        peak = a.learning_rate
        if a.lr_schedule == "constant":
            return optax.linear_schedule(0.0, peak, max(1, a.warmup_steps))
        decay_steps = max(1, a.max_steps - a.warmup_steps)
        if a.lr_schedule == "linear":
            decay = optax.linear_schedule(peak, peak * a.min_lr_ratio,
                                          decay_steps)
        else:
            decay = optax.cosine_decay_schedule(
                peak, decay_steps, alpha=a.min_lr_ratio)
        warmup = optax.linear_schedule(0.0, peak, max(1, a.warmup_steps))
        return optax.join_schedules([warmup, decay], [a.warmup_steps])

    # ----------------------------------------------------------------- data

    def _batch_at(self, source, step: int):
        if callable(source):
            return source(step)
        if not hasattr(self, "_iters"):
            self._iters = {}
        it = self._iters.get(id(source))
        if it is None:
            it = iter(source)
            self._iters[id(source)] = it
        try:
            return next(it)
        except StopIteration:
            it = iter(source)  # new epoch
            self._iters[id(source)] = it
            return next(it)

    # --------------------------------------------------- fused dispatch

    def request_stop(self):
        """Graceful stop at the next fusion boundary (preemption path)."""
        self._preempted = True

    def _on_sigterm(self, signum, frame):
        logger.info("SIGTERM: finishing the in-flight fusion, then "
                    "saving and exiting (graceful preemption)")
        self._preempted = True

    def _hook_cadence(self) -> int:
        """gcd of the active step cadences — K must divide it so every
        hook (logging/save/eval/tune) lands exactly on a fusion boundary,
        keeping the checkpoint cadence from the preempt-table goodput
        curve reachable."""
        import math

        a = self.args
        cad = 0
        for c in (a.logging_steps, a.save_steps,
                  a.eval_steps if self.eval_data is not None else 0,
                  a.tune_config_steps if self._tune_listener is not None
                  else 0,
                  a.policy_steps if self.ctx.mc is not None else 0,
                  a.flash_stage_steps):
            if c:
                cad = math.gcd(cad, int(c))
        return cad

    def _initial_fused_k(self):
        """args.fused_steps resolved: 1 (off), K (explicit), or None —
        auto-tune after measuring the first unfused steps."""
        a = self.args
        if a.fused_steps == 1:
            return 1
        if getattr(self.res, "_fused_factory", None) is None:
            # local_sgd: no fused driver.  Auto quietly runs unfused;
            # an explicit K>1 surfaces the strategy conflict.
            if a.fused_steps > 1:
                self.res.fused_train_step(a.fused_steps)  # raises
            logger.info("fused dispatch unavailable for this strategy; "
                        "running unfused")
            return 1
        if a.fused_steps > 1:
            return a.fused_steps
        return None  # auto

    def _dispatch_overhead_s(self) -> float:
        """Per-dispatch overhead estimate for the ledger's
        dispatch_overhead state — the cached backend probe (or the
        DWT_DISPATCH_OVERHEAD_S pin), never a readback on step outputs."""
        if not hasattr(self, "_disp_overhead"):
            from ..common.util import measure_dispatch_overhead_s

            self._disp_overhead = measure_dispatch_overhead_s()
        return self._disp_overhead

    def _autotune_fused_k(self, step_time_s: float) -> int:
        from .train_step import auto_fused_steps

        k = auto_fused_steps(step_time_s, cadence=self._hook_cadence())
        if k > 1:
            logger.info("fused_steps auto-tuned to %d "
                        "(measured step %.1fms)", k, step_time_s * 1e3)
        return k

    # ----------------------------------------------------- perf observatory

    def _strategy_fingerprint(self) -> str:
        """Strategy identity shared by the perf baseline key and the
        tuner's family key — excludes the tunables (env, K)."""
        try:
            return repr((self.res.strategy.plan.describe(),
                         self.res.strategy_spec))
        except Exception:  # noqa: BLE001
            return repr(self.args.strategy)

    def _perf_key(self, fused_k: int) -> str:
        """Executable identity for the perf baseline — the same facts that
        key the compile cache (strategy fingerprint, fused-K, backend,
        trace-env toggles), so baseline stats never mix executables and a
        tuner cutover lands on a NEW key instead of firing the regression
        sentinel against the old variant's baseline."""
        import jax

        from ..telemetry.perf import executable_key

        return executable_key(self._strategy_fingerprint(), int(fused_k),
                              jax.default_backend())

    def _on_perf_event(self, event: Dict) -> None:
        """Sentinel verdicts → master node-event stream (the same surface
        the checkpoint engine uses for ckpt-health).  Telemetry never
        kills the run."""
        import json as _json

        if self.ctx.mc is None:
            return
        try:
            self.ctx.mc.report_node_event(
                str(event.get("kind", "perf-regression")),
                _json.dumps(event, sort_keys=True), level="warning")
        except Exception:  # noqa: BLE001
            pass

    def _user_trace_active(self, s0: int, k_eff: int) -> bool:
        """True while the opt-in StepProfiler window overlaps this fusion —
        two jax.profiler traces can't nest, so perf windows yield."""
        a = self.args
        if not a.profile_trace_dir or a.profile_start_step < 0:
            return False
        return a.profile_start_step < s0 + k_eff and \
            s0 <= max(a.profile_end_step, a.profile_start_step)

    # ------------------------------------------------- boundary consumer

    def _consume_boundary(self, job: Dict[str, Any]) -> float:
        """One logging boundary's host work — runs on the metrics pump
        thread (inline when async_metrics=False).  The ONE readback per
        fusion lives here; that sync also flushes the fused block's
        device work into any open perf window's trace.  Reads trainer
        state but never writes it — results flow back through the pump's
        lock-guarded fields."""
        step = job["step"]
        # metrics is an executable OUTPUT: donation-immune, safe to read
        # after the main thread has dispatched the next fusion
        loss = float(job["metrics"]["loss"])
        snap = None
        pw = job.get("pw")
        if pw is not None:
            # the readback above synced the block, so the trace holds the
            # device work: fold the xplane op split + step time into a
            # PerfSnapshot, update the baseline, run the regression
            # sentinel, and ship it on the buffered latest-SENT-wins verb
            snap = self._perf.close(pw)
        tps = job["steps"] * job["tokens_per_step"] / \
            max(job["dt_s"], 1e-9)
        logger.info("step %d loss=%.4f tokens/s=%.0f", step, loss, tps)
        self.ctx.report_step(step)
        self.ctx.report_loss(step, loss)
        if self.ctx.mc is not None:
            try:  # buffered verbs; telemetry never kills the run
                if snap:
                    self.ctx.mc.report_perf_snapshot(snap)
                self.ctx.mc.report_goodput_ledger(job["ledger"])
            except Exception:  # noqa: BLE001
                pass
        if snap and self._tuner is not None and \
                job.get("tune_variant") == self._variant_active:
            # credit the window to the variant that actually executed it
            # (note_window is lock-guarded); the returned next candidate
            # is picked up by the main loop's boundary poll.  The loss
            # rides along for the numerics divergence guard — it is the
            # SAME already-read boundary loss, zero new device syncs.
            self._tuner.note_window(
                float(snap.get("step_time_s") or 0.0), loss=loss)
        for cb in self.callbacks:
            cb(step, {"loss": loss, "tokens_per_sec": tps})
        return loss

    # ---------------------------------------------------------------- train

    def train(self) -> Dict[str, float]:
        import signal as _signal

        import jax

        from ..auto.tuner import env_signature
        from ..telemetry.ledger import get_ledger
        from ..telemetry.recorder import get_recorder

        a = self.args
        led = get_ledger()
        led.start()
        start_step = 0
        # rollback rework ceiling: steps below this were trained before a
        # loss-spike rollback and are re-executed ("rework", not goodput)
        self._rework_until = -1
        if a.resume:
            from ..common.constants import NodeEnv

            # one-shot rollback ceiling injected by the agent after a
            # loss-spike diagnosis: resume from BEFORE the spike, not from
            # the latest commit (which can postdate onset)
            try:
                rb = int(os.getenv(NodeEnv.ROLLBACK_BEFORE_STEP, "-1"))
            except ValueError:  # empty/garbage env: resume normally,
                rb = -1        # don't wedge the restart loop
            restored = self.ckpt.load_checkpoint(
                self.state, before_step=rb if rb >= 0 else None)
            if restored is not None:
                self.state = restored
                start_step = int(np.asarray(
                    jax.tree.leaves(self.state.step)[0]))
                if rb >= 0:
                    self._rework_until = rb
                rep = self.ckpt.last_restore_report
                logger.info("resumed from step %d (tier=%s%s)", start_step,
                            rep.get("tier", "?"),
                            ", degraded" if rep.get("fallbacks") else "")
                if rep.get("fallbacks") and self.ctx.mc is not None:
                    # checkpoint-health event: the master's event stream
                    # is where operators see that a tier was corrupt and
                    # which generation actually served the resume
                    self.ctx.mc.report_node_event(
                        "ckpt-health",
                        f"degraded resume: tier={rep.get('tier')} "
                        f"step={rep.get('step')} "
                        f"fallbacks={rep.get('fallbacks')}",
                        level="warning")

        last_loss = float("nan")
        metrics = None
        t_log = time.monotonic()
        steps_since_log = 0
        self._preempted = False
        prev_sigterm = None
        if a.graceful_preemption:
            try:
                prev_sigterm = _signal.signal(_signal.SIGTERM,
                                              self._on_sigterm)
            except ValueError:  # not the main thread: leave the default
                prev_sigterm = None
        fused_k = self._initial_fused_k()
        stager = None
        step_time_s = 0.0
        step = start_step
        # goodput ledger: the trainer owns productive / dispatch_overhead /
        # data_stall / compile / rework; the checkpoint engine credits
        # ckpt_stage/persist + restore tiers; master_client credits
        # degraded.  All accounting happens HERE at fusion boundaries from
        # host-side timers — never inside the jitted step, never via an
        # extra device readback.  Modes are (K, trace-env signature): a
        # variant cutover's first dispatch is a compile, not overhead.
        self._compiled_modes: set = set()
        # callbacks are synchronous user hooks (request_stop, config
        # pushes assert their effect on the NEXT fusion) — their presence
        # forces the inline path
        self._pump = _MetricsPump(
            self, enabled=a.async_metrics and not self.callbacks)
        try:
            while step < a.max_steps and not self._preempted:
                t_iter0 = time.monotonic()
                if fused_k is None and step - start_step >= 2:
                    # two unfused steps measured (the first compiles):
                    # decide K, then fuse the rest of the run
                    fused_k = self._autotune_fused_k(step_time_s)
                if self._policy_pending_k is not None and \
                        fused_k is not None:
                    # fusion-boundary K cutover: only once the warm pool
                    # holds a ready entry at the new K (never a cold
                    # compile mid-run); the stager rebuilds below at the
                    # new width, K=1 falls back to the unfused path
                    if self._policy_pending_k == fused_k:
                        self._policy_pending_k = None
                    elif self._prewarm_fused_k(self._policy_pending_k):
                        logger.info("policy: fused_steps %d -> %d at "
                                    "boundary %d", fused_k,
                                    self._policy_pending_k, step)
                        fused_k = self._policy_pending_k
                        self._policy_pending_k = None
                        stager = None
                if self._tuner is not None and fused_k is not None:
                    # variant cutover at the boundary, warm-pool gated —
                    # only after the K auto-tune settles (the unfused
                    # measurement steps must not race an env flip)
                    self._maybe_apply_variant(fused_k)
                self._fused_k_active = fused_k or 0
                if fused_k is not None and fused_k > 1 and stager is None:
                    from ..data.elastic_dataset import FusedBatchStager

                    stager = iter(FusedBatchStager(
                        lambda s: dict(self._batch_at(self.train_data, s)),
                        self.res.place_fused_batch, fused_k,
                        step, a.max_steps,
                        place_single=self.res.place_batch))
                with led.window("data_stall"):
                    if stager is not None:
                        s0, k_eff, batch = next(stager)
                    else:
                        s0, k_eff = step, 1
                        batch = self.res.place_batch(
                            dict(self._batch_at(self.train_data, step)))
                data_s = time.monotonic() - t_iter0
                if self._tune_listener is not None and \
                        s0 % a.tune_config_steps == 0:
                    tuned = self._tune_listener.poll()
                    if tuned:
                        self._apply_tuned_config(tuned)
                if a.policy_steps and self.ctx.mc is not None and \
                        s0 % a.policy_steps == 0:
                    self._poll_policy()
                    self._poll_mesh_transition()
                pw = None
                env_mode = (k_eff, env_signature())
                if self._perf is not None and a.logging_steps and \
                        (s0 + k_eff) % a.logging_steps == 0 and \
                        env_mode in self._compiled_modes and \
                        self._pump.windows_inflight() == 0 and \
                        (self._tuner is None or
                         self._tuner.current().name ==
                         self._variant_active) and \
                        not self._user_trace_active(s0, k_eff):
                    # perf window: only on a boundary that already carries
                    # the logging readback (that sync flushes the fused
                    # block's device work into the trace — zero NEW
                    # readbacks), never on the compile dispatch (compile
                    # wall is not a step-time baseline), never while the
                    # opt-in trace window is live or a pump-held window is
                    # still closing (jax traces can't nest), and — when
                    # tuning — only while execution matches the tuner's
                    # current candidate, so a deferred cutover never
                    # credits the old variant's windows to the new one.
                    # maybe_open applies the every-Nth cadence and the
                    # <1%-overhead self-limit.
                    self._perf.key = self._perf_key(k_eff)
                    pw = self._perf.maybe_open(s0, k_eff)
                prof_before = self.profiler.last_profile
                t_blk0 = time.monotonic()
                with self.profiler.step(s0):
                    if k_eff > 1:
                        self.state, metrics = self.res.fused_train_step(
                            k_eff)(self.state, batch)
                    else:
                        t0 = time.perf_counter()
                        # width-1 through the variant-aware fused cache:
                        # identical to train_step until a DWT_FA_* cutover
                        # changes the env signature, which must retrace
                        # instead of reusing the old trace
                        self.state, metrics = self.res.fused_train_step(1)(
                            self.state, batch)
                        if fused_k is None:
                            # auto-tune measurement: sync so the timing is
                            # the real step, not the async dispatch
                            float(metrics["loss"])
                            step_time_s = time.perf_counter() - t0
                blk_s = time.monotonic() - t_blk0
                if env_mode not in self._compiled_modes:
                    # first dispatch at this (fusion width, variant env)
                    # traces+compiles
                    self._compiled_modes.add(env_mode)
                    led.account("compile", blk_s)
                    credited_blk = blk_s
                else:
                    credited_blk = min(blk_s, self._dispatch_overhead_s())
                    led.account("dispatch_overhead", credited_blk)
                if self.profiler.last_profile is not prof_before:
                    # a trace window just closed: surface slow collectives
                    self.ctx.report_op_profile(
                        self.profiler.last_profile.collective_evidence())
                step = s0 + k_eff
                steps_since_log += k_eff
                hooks_excl_s = 0.0  # save/eval time: credited elsewhere
                # (engine ledger states) or left to the other_s residual
                # ---- boundary hooks: K divides every active cadence, so
                # these fire exactly as in the unfused loop ----
                if a.logging_steps and step % a.logging_steps == 0:
                    # the boundary's host work — the ONE readback per
                    # fusion, the perf-window close, the master reports
                    # and the callbacks — goes to the metrics pump so the
                    # next fused dispatch overlaps it instead of
                    # serializing behind the sync.  Ledger CREDITS stayed
                    # above on this thread; the pump only ships the
                    # snapshot dict taken here at the boundary.
                    dt = time.monotonic() - t_log
                    t_log = time.monotonic()
                    # re-read the live batch size: the master may retune it
                    tokens_per_step = a.seq_len * getattr(
                        self.train_data, "batch_size", a.global_batch_size)
                    self._pump.submit({
                        "step": step, "metrics": metrics, "pw": pw,
                        "dt_s": dt, "steps": steps_since_log,
                        "tokens_per_step": tokens_per_step,
                        "ledger": led.snapshot(),
                        "tune_variant": self._variant_active,
                    })
                    pw = None
                    steps_since_log = 0
                saved = False
                if a.save_steps and step % a.save_steps == 0:
                    t_h = time.monotonic()
                    self._save(step)
                    hooks_excl_s += time.monotonic() - t_h
                    saved = True
                if a.flash_stage_steps and not saved and \
                        step % a.flash_stage_steps == 0:
                    # shm staging (save_to_memory): the agent's
                    # save-on-failure persists this boundary if the next
                    # fusion never completes
                    from ..checkpoint.checkpointer import StorageType

                    t_h = time.monotonic()
                    self.ckpt.save_checkpoint(
                        step, self.state, storage_type=StorageType.MEMORY)
                    hooks_excl_s += time.monotonic() - t_h
                if a.eval_steps and self.eval_data is not None and \
                        step % a.eval_steps == 0:
                    t_h = time.monotonic()
                    eval_loss = self.evaluate()
                    hooks_excl_s += time.monotonic() - t_h
                    logger.info("step %d eval_loss=%.4f", step, eval_loss)
                # remainder of the iteration is the fused window itself:
                # wall - data stall - credited dispatch/compile - hook time
                # (saves are credited by the engine as ckpt_stage/persist;
                # eval falls to the other_s residual by design)
                window_s = max(0.0, (time.monotonic() - t_iter0) - data_s
                               - credited_blk - hooks_excl_s)
                led.account(
                    "rework" if s0 < self._rework_until else "productive",
                    window_s)
            if self._preempted and step < a.max_steps:
                logger.info("preempted at fusion boundary %d — saving and "
                            "exiting", step)
        except BaseException:
            # fault flight dump: ring buffer + ledger snapshot land next
            # to the checkpoints so post-mortem tooling finds them
            get_recorder().flush(self.ckpt.checkpoint_dir, "fault")
            raise
        finally:
            # flush queued boundaries + join (thread-leak guard) BEFORE
            # the final cumulative ledger ship, so latest-wins ordering
            # holds at the master
            self._pump.stop()
            pump_loss = self._pump.last_loss()
            if pump_loss == pump_loss:
                last_loss = pump_loss
            if self._preempted:
                get_recorder().flush(self.ckpt.checkpoint_dir, "sigterm")
            if self.ctx.mc is not None:
                try:  # final cumulative snapshot (latest-wins at master)
                    self.ctx.mc.report_goodput_ledger(led.snapshot())
                except Exception:  # noqa: BLE001
                    pass
            if prev_sigterm is not None:
                try:
                    _signal.signal(_signal.SIGTERM, prev_sigterm)
                except ValueError:
                    pass
            if self._prober is not None:
                self._prober.stop()
            if a.save_on_exit:
                final = int(np.asarray(
                    jax.tree.leaves(self.state.step)[0]))
                if getattr(self, "_last_saved_step", -1) != final:
                    # don't re-stage a step the cadence save just staged:
                    # two concurrent saves of one step race on the same
                    # shard files
                    self._save(final)
                self.ckpt.wait_latest_checkpoint(600)
            self.profiler.close()
        if last_loss != last_loss and metrics is not None:
            last_loss = float(metrics["loss"])  # only short runs never log
        return {"final_step": a.max_steps, "final_loss": last_loss,
                "stopped_at": step}

    def _save(self, step: int):
        from ..checkpoint.checkpointer import StorageType

        # mesh/world shape + fused-K travel in the staging extras and land
        # in the committed generation's manifest (checkpoint/integrity.py)
        # — restore tooling can tell what world wrote a checkpoint
        mesh = getattr(self.res, "mesh", None)
        extra = {"mesh_shape": ({k: int(v) for k, v in
                                 dict(mesh.shape).items()}
                                if mesh is not None else {}),
                 "fused_steps": int(getattr(self, "_fused_k_active", 0)
                                    or self.args.fused_steps)}
        blocked = self.ckpt.save_checkpoint(
            step, self.state, storage_type=StorageType.DISK,
            extra_meta=extra)
        self._last_saved_step = step
        logger.info("checkpoint step %d staged (blocked %.3fs)", step,
                    blocked)

    # ----------------------------------------------------------------- eval

    def evaluate(self) -> float:
        """Mean loss over up to max_eval_batches of eval_data."""
        import jax

        if self.eval_data is None:
            raise ValueError("no eval_data")
        if not hasattr(self, "_eval_fn"):
            loss_fn = self.res.loss_fn

            @jax.jit
            def _eval(params, batch):
                return loss_fn(params, batch)

            self._eval_fn = _eval
        params = getattr(self.state, "params", None)
        if params is None:  # DiLoCo state: evaluate the synced outer params
            params = self.state.outer_params
        losses = []
        for i in range(self.args.max_eval_batches):
            try:
                batch = self.res.place_batch(
                    dict(self._batch_at(self.eval_data, i)))
            except StopIteration:  # pragma: no cover
                break
            losses.append(float(self._eval_fn(params, batch)))
        return float(np.mean(losses)) if losses else float("nan")

"""Sharded training step builder: the hot loop, compiled once under jit.

Parity: reference training hot loop after `auto_accelerate` (SURVEY.md §3.4
tail — FSDP/TP modules with per-layer NCCL collectives).  TPU redesign: one
jit'd step over the global mesh; GSPMD inserts all collectives from the
in/out shardings.  Gradient accumulation (reference ElasticTrainer's fixed
global batch) is a `lax.scan` over microbatches inside the step.

Fused multi-step dispatch (`fused_steps=K`): a second `lax.scan` level
wraps the whole step over K pre-staged batches, so ONE dispatch drives K
optimizer updates and ONE host readback per fusion syncs all K metrics.
The fixed per-dispatch cost (~5-8ms over the axon tunnel, CLAUDE.md) then
amortizes to <2% of a fusion instead of dominating small steps —
`auto_fused_steps` picks K from measured step time vs. measured dispatch
overhead, clamped so the trainer's hook cadences (checkpoint/logging/eval)
stay exactly reachable at fusion boundaries.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from ..common.log import get_logger
from ..parallel.sharding import ShardingPlanner

logger = get_logger("train_step")


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any

    @classmethod
    def create(cls, params, optimizer: optax.GradientTransformation):
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=optimizer.init(params))


def accumulate_grads(grad_fn, params, batch, accum_steps: int):
    """Mean loss + mean grads over the leading microbatch axis of `batch`.

    `grad_fn(micro) -> (loss, grads)`; f32 accumulators shaped like
    `params`.  Shared by the plain train step and the DiLoCo inner step so
    the accumulation semantics cannot diverge."""
    def body(carry, micro):
        loss_sum, grads_sum = carry
        loss, grads = grad_fn(micro)
        return (loss_sum + loss,
                jax.tree.map(jnp.add, grads_sum, grads)), ()

    zero = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    (loss_sum, grads), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), zero), batch)
    return (loss_sum / accum_steps,
            jax.tree.map(lambda g: g / accum_steps, grads))


def make_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    planner: Optional[ShardingPlanner] = None,
    accum_steps: int = 1,
    donate: bool = True,
    value_and_grad_fn: Optional[Callable] = None,
    opt_host_shardings: Any = None,
    opt_device_shardings: Any = None,
    fused_steps: int = 1,
):
    """Returns jit'd `step(state, batch) -> (state, metrics)`.

    `batch` leaves have a leading microbatch axis of size `accum_steps` when
    accumulation is on: shape (accum, per_device_batch * data_axes, ...).
    `value_and_grad_fn(params, batch) -> (loss, grads)` overrides the default
    autodiff path (used by the manual 1F1B pipeline schedule).
    `opt_host_shardings`/`opt_device_shardings` (both or neither): the
    optimizer state lives in host memory between steps (optimizer_offload
    strategy) — the step hops it to device for the update and back.

    `fused_steps=K > 1` returns the fused driver `step(state, batches) ->
    (state, metrics)` instead: `lax.scan` of the SAME per-step math over K
    pre-staged batches (leaves carry a leading fused axis of size K) inside
    ONE jit — one dispatch per K optimizer steps instead of K, which
    amortizes the fixed per-dispatch overhead (~5-8ms over the axon
    tunnel, CLAUDE.md) that otherwise caps small-step throughput.  Metrics
    accumulate ON DEVICE in the scan outputs: `metrics["losses"]` /
    `metrics["grad_norms"]` are per-step arrays of shape (K,) and
    `metrics["loss"]` / `metrics["grad_norm"]` are the LAST step's values,
    so one host readback per fusion syncs the whole block — no per-step
    `float(...)` sync survives on the hot path.  Donation semantics are
    unchanged: the carried state is donated exactly as in the K=1 case
    (and still rejected under optimizer_offload below).
    """

    def _grads(params, batch):
        if value_and_grad_fn is not None:
            return value_and_grad_fn(params, batch)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        return loss, grads

    def train_step(state: TrainState, batch):
        if accum_steps == 1:
            loss, grads = _grads(state.params, batch)
        else:
            loss, grads = accumulate_grads(
                lambda micro: _grads(state.params, micro), state.params,
                batch, accum_steps)
        opt_in = state.opt_state
        if opt_host_shardings is not None:
            opt_in = jax.device_put(opt_in, opt_device_shardings)
        updates, opt_state = optimizer.update(grads, opt_in, state.params)
        if opt_host_shardings is not None:
            opt_state = jax.device_put(opt_state, opt_host_shardings)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(state.step + 1, params, opt_state)
        return new_state, {"loss": loss, "grad_norm": gnorm}

    # offloaded opt states: donation would let XLA alias a pinned_host
    # input buffer onto a device-memory output (same shape/dtype) and the
    # runtime rejects the memory-kind mismatch.  Silently disabling the
    # flag hid the conflict from callers; now it is an explicit resolve-
    # time error (graftlint donation-alias — auto_accelerate resolves
    # donate=None to the right value before calling here).
    if donate and opt_host_shardings is not None:
        raise ValueError(
            "graftlint[donation-alias]: donate=True with host-offloaded "
            "optimizer state — XLA would alias a pinned_host input onto a "
            "device-memory output and the runtime rejects the memory-kind "
            "mismatch; pass donate=False (auto_accelerate's donate=None "
            "resolves this automatically)")
    donate_argnums = (0,) if donate else ()
    if fused_steps <= 1:
        return jax.jit(train_step, donate_argnums=donate_argnums)

    def fused_train_step(state: TrainState, batches):
        def body(st, b):
            st, m = train_step(st, b)
            return st, m

        state, stacked = jax.lax.scan(body, state, batches,
                                      length=fused_steps)
        metrics = {
            "loss": stacked["loss"][-1],
            "grad_norm": stacked["grad_norm"][-1],
            "losses": stacked["loss"],
            "grad_norms": stacked["grad_norm"],
        }
        return state, metrics

    return jax.jit(fused_train_step, donate_argnums=donate_argnums)


def auto_fused_steps(step_time_s: float, overhead_s: Optional[float] = None,
                     target_overhead: float = 0.02, cap: int = 64,
                     cadence: int = 0) -> int:
    """Pick K so the per-dispatch overhead is < `target_overhead` of a
    K-step fusion: K >= overhead / (target * step_time).

    `cap` bounds staging memory (K batches live on device at once) and the
    reaction latency of fusion-boundary hooks.  `cadence` (the gcd of the
    trainer's active step cadences — logging/save/eval/tune) clamps K to
    its largest divisor so checkpoint cadence stays exactly reachable:
    hooks fire only at fusion boundaries, and the preempt-table goodput
    curve (chaos.py) is meaningful only if the chosen ckpt interval is a
    boundary."""
    import math

    if overhead_s is None:
        from ..common.util import measure_dispatch_overhead_s

        overhead_s = measure_dispatch_overhead_s()
    if step_time_s <= 0:
        k = cap
    else:
        k = math.ceil(overhead_s / (target_overhead * step_time_s))
    k = max(1, min(k, cap))
    if cadence > 0:
        k = min(k, cadence)
        while cadence % k:
            k -= 1
    return k


def shard_train_state(state: TrainState, planner: ShardingPlanner
                      ) -> Tuple[TrainState, Any]:
    """Place params/opt-state on the mesh; returns (state, state_shardings).

    Prefer `train_state_shardings` + jit-with-out_shardings init (see
    auto/accelerate.py) for new code: this entry materializes the full
    unsharded tree first, which an 8B-class model cannot afford."""
    state_sh = train_state_shardings(state, planner)
    placed = jax.device_put(state, state_sh)
    return placed, state_sh


def train_state_shardings(state_like: TrainState, planner: ShardingPlanner,
                          offload_opt: bool = False) -> TrainState:
    """Shardings for a TrainState, from a concrete OR abstract
    (jax.eval_shape) instance — never touches leaf values, so the full
    tree need not exist (sharded-by-construction init, parity
    atorch/utils/meta_model_utils.py:759 deferred materialization).

    offload_opt=True places the param-shaped optimizer moments in HOST
    memory (pinned_host memory kind): at 8B-class scale Adam states
    dominate the HBM budget (parity: reference adam_offload.py:87
    PartitionAdam).  XLA streams them device<->host around the update."""
    state = state_like
    param_sh = planner.param_shardings(state.params)
    repl = planner.replicated()
    opt_moment_sh = param_sh
    if offload_opt:
        from jax.sharding import NamedSharding

        opt_moment_sh = jax.tree.map(
            lambda sh: NamedSharding(sh.mesh, sh.spec,
                                     memory_kind="pinned_host"),
            param_sh,
            is_leaf=lambda x: isinstance(x, NamedSharding))

    # optimizer moments (adam mu/nu, etc.) mirror the param pytree: any
    # opt_state subtree whose structure equals the param tree gets the param
    # shardings leaf-for-leaf; everything else (counts, scalars) replicates.
    # Matching by position, not shape — two same-shaped params can carry
    # different PartitionSpecs (e.g. P('fsdp','tp') vs P('tp','fsdp')).
    param_treedef = jax.tree.structure(state.params)
    param_shapes = [getattr(p, "shape", None)
                    for p in jax.tree.leaves(state.params)]

    def _is_param_shaped(sub):
        # structure alone is not enough: adafactor's v_row/v_col subtrees
        # mirror the param treedef with reduced leaf shapes
        try:
            if jax.tree.structure(sub) != param_treedef:
                return False
            return [getattr(x, "shape", None)
                    for x in jax.tree.leaves(sub)] == param_shapes
        except Exception:  # noqa: BLE001
            return False

    opt_sh = jax.tree.map(
        lambda sub: (opt_moment_sh if _is_param_shaped(sub)
                     else jax.tree.map(lambda _: repl, sub)),
        state.opt_state, is_leaf=_is_param_shaped)
    return TrainState(step=repl, params=param_sh, opt_state=opt_sh)


def make_lm_loss(model_apply: Callable) -> Callable:
    """Standard causal-LM loss over a batch dict {input_ids, labels}.

    Collects sown auxiliary losses (MoE load-balancing) when present."""
    from ..models.gpt import cross_entropy_loss

    def loss_fn(params, batch):
        logits, updates = model_apply(
            {"params": params}, batch["input_ids"],
            mutable=["intermediates"])
        loss = cross_entropy_loss(logits, batch["labels"])
        inter = updates.get("intermediates", {})
        if inter:
            from ..models.moe import collect_moe_aux_loss

            loss = loss + collect_moe_aux_loss(inter)
        return loss

    return loss_fn

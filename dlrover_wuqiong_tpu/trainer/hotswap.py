"""Worker-side hot-swap participant: survivor phase work + acks.

Parity axis: the reference's worker-side recovery
(`dlrover/python/elastic_agent/torch/training.py` restart paths) tears
the whole process group down and rebuilds it through a fresh rendezvous
— every survivor pays a restart even though only one rank died.  The
TPU redesign keeps the survivors ALIVE: they pause at a fusion
boundary, absorb the dead rank's shards from ring replicas, and resume
on a pre-compiled degraded-mesh executable — no teardown, no storage
round trip, no cold compile.

Counterpart of `master/mesh_transition.py` — the master owns the
journaled phase ladder, a survivor owns the work each phase names:

- **propose**: nothing to compute — being asked at all means the caller
  is parked at a FUSION BOUNDARY (poll() only ever runs there), so the
  ack simply confirms the pause.
- **fence**: adopt the bumped fencing epoch — after this ack the
  survivor will not dispatch into the old world again.
- **hydrate**: pull the dead rank's staged shards from its ring-replica
  holders (checkpoint/replica.py fetch_peer — digest-verified BEFORE the
  bytes are decoded; an unverifiable ring is a nack, never a silent
  skip).  Wall time credits the ledger's ``restore_replica`` state.
- **cutover**: hand the hydrated shards to the caller's re-shard hook
  (the degraded-mesh executable is pre-compiled via the warm pool —
  CLAUDE.md: a mesh change is a new compile-cache key, so cutover must
  never pay a cold compile mid-incident).  Wall time credits ``rework``
  — the swap re-derives state that a restart would have replayed.
- **release**: master-side only (world rewrite); the survivor polls
  until the transition leaves the ladder, then resumes under the new
  world/round.

Donation rule (CLAUDE.md): hydrated bytes headed for a donating step
must be laundered through one jitted identity copy before any donation
path touches them — the cutover hook owns device placement and is the
place to do it (checkpoint/engine.py restore_pytree is the sanctioned
launderer).

Acks ride ``report_mesh_transition_phase`` (CRITICAL + idem — the
master journals each ack before answering); the state poll rides the
POLLING class (fail fast — a dead master degrades to "keep training on
the old world", and the master's own transition timeout aborts the
ladder if survivors stay unreachable).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from ..common import messages as msg
from ..common.log import get_logger

logger = get_logger("hotswap")


class HotSwapParticipant:
    """Drives one survivor through the transition ladder.

    Call ``poll()`` at fusion boundaries only.  Returns the phase that
    was acknowledged this call (or ``"done"``/``"aborted"`` once the
    tracked transition leaves the ladder), ``None`` when idle.
    """

    def __init__(self, mc, node_id: int,
                 replica_manager=None,
                 hydrate_cb: Optional[Callable] = None,
                 cutover_cb: Optional[Callable] = None,
                 fence_cb: Optional[Callable] = None,
                 ledger=None):
        self.mc = mc
        self.node_id = int(node_id)
        self.replica = replica_manager
        self.hydrate_cb = hydrate_cb
        self.cutover_cb = cutover_cb
        self.fence_cb = fence_cb
        self.ledger = ledger
        self.fence_epoch = 0
        #: (step, flat_state, extra) of the dead rank after hydrate
        self.hydrated: Optional[Tuple[int, Dict, Dict]] = None
        self._acked: set = set()       # (tid, phase) pairs already acked
        self._tracking = 0             # tid we are mid-ladder on

    @property
    def mid_ladder(self) -> bool:
        """True while a tracked transition is still on the ladder — the
        caller should stay parked at its fusion boundary and keep
        polling until this clears."""
        return bool(self._tracking)

    # ----------------------------------------------------------------- poll

    def poll(self) -> Optional[str]:
        try:
            st = self.mc.get_mesh_transition()
        except Exception:  # noqa: BLE001 — POLLING class: next boundary
            # retries; the master's timeout is the ladder's backstop
            return None
        tid = int(getattr(st, "transition_id", 0) or 0)
        phase = getattr(st, "phase", "") or ""
        if self._tracking and (tid != self._tracking
                               or phase in ("done", "aborted")):
            # the transition we were working left the ladder
            finished = phase if tid == self._tracking else "done"
            logger.info("hot-swap transition %d finished: %s",
                        self._tracking, finished)
            self._tracking = 0
            return finished
        if tid == 0 or phase in ("done", "aborted", "release"):
            return None
        if self.node_id not in (st.survivors or []):
            return None
        if (tid, phase) in self._acked:
            return None
        self._tracking = tid
        ok, detail = True, ""
        if phase == "fence":
            self.fence_epoch = int(st.fence_epoch)
            if self.fence_cb is not None:
                try:
                    self.fence_cb(self.fence_epoch)
                except Exception as e:  # noqa: BLE001 — a fence hook
                    # failure must nack, not crash the boundary
                    ok, detail = False, f"fence hook failed: {e}"
        elif phase == "hydrate":
            ok, detail = self._hydrate(st)
        elif phase == "cutover":
            ok, detail = self._cutover(st)
        elif phase == "propose":
            detail = "paused at fusion boundary"
        try:
            resp = self.mc.report_mesh_transition_phase(
                tid, phase, ok=ok, detail=detail)
        except Exception:  # noqa: BLE001 — the idem key makes a later
            # retry of this ack at-most-once; drop and re-poll
            return None
        if getattr(resp, "success", True):
            self._acked.add((tid, phase))
        logger.info("hot-swap %d: acked phase %s ok=%s %s", tid, phase,
                    ok, detail)
        return phase

    # ---------------------------------------------------------------- phases

    def _hydrate(self, st: msg.MeshTransitionState) -> Tuple[bool, str]:
        from contextlib import nullcontext

        from ..checkpoint.shm_handler import blob_state_dict

        win = (self.ledger.window("restore_replica")
               if self.ledger is not None else nullcontext())
        with win:
            if self.hydrate_cb is not None:
                try:
                    self.hydrated = self.hydrate_cb(st)
                except Exception as e:  # noqa: BLE001 — nack with cause
                    return False, f"hydrate hook failed: {e}"
                if self.hydrated is None:
                    return False, "hydrate hook returned nothing"
                return True, f"step {self.hydrated[0]}"
            if self.replica is None:
                return False, "no replica ring attached"
            fetched = self.replica.fetch_peer(int(st.dead_rank))
            if fetched is None:
                return False, (f"no verified replica of rank "
                               f"{st.dead_rank} reachable")
            step, blob = fetched
            parsed = blob_state_dict(blob)  # blob already digest-verified
            if parsed is None:
                return False, "verified blob failed to decode"
            pstep, flat, extra = parsed
            self.hydrated = (pstep, flat, extra)
            return True, f"step {step}"

    def _cutover(self, st: msg.MeshTransitionState) -> Tuple[bool, str]:
        from contextlib import nullcontext

        win = (self.ledger.window("rework")
               if self.ledger is not None else nullcontext())
        with win:
            if self.cutover_cb is None:
                # nothing to re-shard (caller only wanted the fence +
                # hydrate choreography) — confirm
                return True, "no cutover hook"
            try:
                out = self.cutover_cb(self.hydrated, st)
            except Exception as e:  # noqa: BLE001 — nack with cause
                return False, f"cutover failed: {e}"
            if out is False:
                return False, "cutover hook declined"
            return True, f"resharded onto {len(st.survivors)}-node mesh"

"""Continuous-batching inference on the decode mesh.

Parity: the reference delegates serving to vLLM
(`atorch/atorch/rl/model_engine/model_engine.py:35` — generation routes
to an external engine); DLRover itself has no serving plane.  Here
serving is a first-class subsystem of the elastic framework: the same
master that dispatches training shards dispatches inference requests
(journaled + idempotent verbs), the same telemetry pillars attribute
serving time (telemetry/serving.py) and trace each request, and the
same chaos harness kills decode workers mid-traffic (`chaos
serve-drain`) asserting zero dropped in-flight requests.

TPU redesign — continuous (in-flight) batching with STATIC shapes:

- Slot-based KV cache: fixed ``(max_slots, max_len)`` ring of per-layer
  (k, v) buffers.  A finished request frees its slot; a new request is
  admitted at a scan-window boundary by prefilling a one-row mini cache
  and `dynamic_update_slice`-ing it into the big buffers.  The decode
  step stays ONE fused jit program — no per-token or per-admission
  recompiles (the compile-cache key covers slot count / max_len / quant
  mode, serving/engine.py).
- Inactive slots are frozen with ``jnp.where`` masks, never `lax.cond`
  (the CLAUDE.md cond-collective rule), and stale cache positions are
  harmless by write-then-attend: position p is (over)written by the
  same forward that first attends it.
- Sampling is keyed by ``fold_in(request_key, absolute_position)``, so
  a request's tokens are bit-identical whether it decodes alone or
  packed in a busy batch with slot churn (tests/test_serving.py).
- Decode weights can be int8/fp8-quantized (ops/quantization.py) with a
  one-hop ``sync_from_trainer`` handoff from a live trainer.
"""

from .engine import ServeSpec, ServingEngine, serve_step_cache_key  # noqa: F401
from .scheduler import LocalServer, SlotScheduler  # noqa: F401

"""Slot-based continuous-batching decode engine (three jitted programs).

Parity: vLLM's continuous batching (the reference's serving backend,
`atorch/atorch/rl/model_engine/model_engine.py:35`) keeps a paged KV
cache and admits/evicts requests every iteration.  On TPU the same idea
must survive XLA's static-shape contract, so the design inverts: the
cache is a fixed ``(max_slots, max_len)`` ring and ALL dynamism lives in
traced *values* (positions, active masks, slot indices), never in
shapes.  Three programs compile once per (spec, model, quant, backend):

- ``admit``: prefill one request's prompt through a one-row mini cache
  (`lax.scan` over the static ``max_prompt_len``), sample its first
  token with ``fold_in(request_key, prompt_len)``, and
  `dynamic_update_slice` the mini cache into the big buffers at a
  *traced* slot index.
- ``decode``: `lax.scan` of ``fused_tokens`` steps over the shared
  forward (rl/generation.py `forward_step`) with a per-row position
  vector; inactive rows are frozen via ``jnp.where`` (their pos/tok do
  not advance).  ONE dispatch and ONE host readback — the (K, S) token
  block — per window (the fused K-step dispatch rule).
- retirement is free: the active mask is a host-side input, so freeing
  a slot is a host array write at the window boundary.

Correctness of stale cache state (pad positions beyond a prompt, a
previous tenant's kv) is by WRITE-THEN-ATTEND: row r attends position p
only when its pos >= p, and the forward at pos == p (over)writes p
before attending, so garbage is never read.  Every op is row-
independent, which makes a request's tokens a pure function of
(weights, prompt, seed) — independent of batch composition and slot
churn (the equivalence invariant tests/test_serving.py pins).

The engine's ``cache_key`` folds spec + model + quant + TRACE_ENV_VARS
into the framework compile-cache registry (auto/compile_cache.py), and
`auto/warm_pool.py` accepts a ``serve`` WarmSpec field to AOT-compile
these programs ahead of a cutover.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..auto.compile_cache import (
    TRACE_ENV_VARS,
    canonicalize,
    note_train_step_served,
)
from ..models.gpt import GPTConfig
from ..ops.quantization import (
    dequantize_int8_blockwise,
    fp8_dequantize,
    fp8_quantize,
    quantize_int8_blockwise,
)
from ..rl.generation import forward_step, init_caches

_QUANT_MODES = ("", "int8", "fp8")


@dataclasses.dataclass(frozen=True)
class ServeSpec:
    """Static shape/compile parameters of one serving engine.

    Everything here is part of the compile-cache key: changing any field
    is a new executable (warm-pool it before cutover).  ``top_k`` is
    engine-static rather than per-request — a per-request top-k would
    change the sampling program shape.
    """

    max_slots: int = 4        # batch rows / concurrent requests
    max_len: int = 128        # per-slot KV length (prompt + generated)
    max_prompt_len: int = 32  # static prefill scan length
    fused_tokens: int = 8     # K decode steps per dispatch
    quant: str = ""           # "" | "int8" | "fp8" decode weights
    top_k: int = 0            # 0 = full softmax


def serve_step_cache_key(model_config: Any, spec: ServeSpec,
                         backend: Optional[str] = None) -> str:
    """Digest of everything the serving trace depends on (the serving
    counterpart of auto/compile_cache.train_step_cache_key — same
    TRACE_ENV_VARS rule: two processes with different DWT_FA_* values
    emit different HLO from the same python call)."""
    payload = {
        "kind": "serve",
        "model": canonicalize(model_config),
        "spec": canonicalize(spec),
        "env": {k: os.getenv(k, "") for k in TRACE_ENV_VARS},
        "backend": backend or jax.default_backend(),
        "jax": jax.__version__,
    }
    blob = json.dumps(payload, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# ------------------------------------------------------------ quant store


def _quantize_tree(params: Dict, mode: str) -> Tuple[Dict, Dict]:
    """Split params into a (store, meta) pair: `store` holds arrays (the
    jit argument — weights must be arguments, not closure constants, so
    a weight refresh never retraces), `meta` holds the static dequant
    recipe per leaf (closure — it IS part of the trace)."""
    store: Dict = {}
    meta: Dict = {}

    def rec(src, dst, mdst):
        for k, v in src.items():
            if isinstance(v, dict):
                dst[k], mdst[k] = {}, {}
                rec(v, dst[k], mdst[k])
                continue
            arr = jnp.asarray(v)
            # quantize matrices/embeddings; 1-D leaves (bias, LN) stay
            # exact — they are tiny and scale-sensitive
            if mode and arr.ndim >= 2 and \
                    jnp.issubdtype(arr.dtype, jnp.floating):
                if mode == "int8":
                    q, s = quantize_int8_blockwise(arr)
                else:
                    q, s = fp8_quantize(arr)
                dst[k] = {"q": q, "s": s}
                mdst[k] = (mode, int(arr.size), tuple(arr.shape))
            else:
                dst[k] = arr
                mdst[k] = None

    rec(params, store, meta)
    return store, meta


def _materialize(store: Dict, meta: Dict, dtype) -> Dict:
    """Dequantize the store back into a forward-ready param tree
    (traced — runs once per dispatch inside the jitted programs)."""
    out: Dict = {}
    for k, m in meta.items():
        if isinstance(m, dict):
            out[k] = _materialize(store[k], m, dtype)
        elif m is None:
            out[k] = store[k]
        else:
            mode, size, shape = m
            leaf = store[k]
            if mode == "int8":
                out[k] = dequantize_int8_blockwise(
                    leaf["q"], leaf["s"], size, shape, dtype=dtype)
            else:
                out[k] = fp8_dequantize(leaf["q"], leaf["s"],
                                        dtype=dtype).reshape(shape)
    return out


# ------------------------------------------------------------ sampling


def _sample_rows(logits, keys, temps, top_k: int):
    """Per-row sampling: logits (S, V) f32, keys (S, 2) uint32 (already
    position-folded), temps (S,).  temp <= 0 means greedy.  Both the
    sampled and greedy branches are computed and selected with
    ``jnp.where`` — no data-dependent control flow in the program."""
    logits = logits.astype(jnp.float32)
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.vmap(jax.random.categorical)(keys, scaled)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(temps > 0, sampled, greedy)


# ------------------------------------------------------------- engine


class ServingEngine:
    """Owns the big KV buffers (device) + slot registers (host).

    Device state is ONLY the per-layer cache buffers; the small per-slot
    registers (next token, position, active mask, PRNG key, temperature)
    live host-side and ride into each dispatch as inputs — freeing a
    slot is a host write, no device program.  Cache buffers are donated
    through the admit/decode programs (they only ever originate as
    executable outputs, so the device_put→donate freed-memory hazard in
    CLAUDE.md does not apply).
    """

    def __init__(self, cfg: GPTConfig, params: Dict, spec: ServeSpec,
                 cache_dir: Optional[str] = None):
        if spec.quant not in _QUANT_MODES:
            raise ValueError(f"quant mode {spec.quant!r} not in "
                             f"{_QUANT_MODES}")
        if spec.max_len > cfg.block_size:
            raise ValueError(f"max_len {spec.max_len} exceeds model "
                             f"block_size {cfg.block_size}")
        if not (0 < spec.max_prompt_len <= spec.max_len):
            raise ValueError("need 0 < max_prompt_len <= max_len")
        if spec.max_slots < 1 or spec.fused_tokens < 1:
            raise ValueError("need max_slots >= 1 and fused_tokens >= 1")
        self.cfg = cfg
        self.spec = spec
        self._store, self._meta = _quantize_tree(params, spec.quant)
        self.cache_key = serve_step_cache_key(cfg, spec)
        # registry note: warm restarts can tell whether this topology was
        # compiled by a prior process (tools/warm_report.py aggregates)
        note_train_step_served(
            cache_dir or os.getenv("DWT_COMPILE_CACHE_DIR", ""),
            self.cache_key,
            {"kind": "serve", "spec": dataclasses.asdict(spec)})
        S = spec.max_slots
        # caches start as executable OUTPUTS (jitted zeros), which keeps
        # the donate chain free of device_put-origin arrays
        self.caches = jax.jit(
            lambda: init_caches(cfg, S, spec.max_len))()
        # host-side slot registers
        self.tok = np.zeros(S, np.int32)
        self.pos = np.zeros(S, np.int32)
        self.active = np.zeros(S, bool)
        self.keys = np.zeros((S, 2), np.uint32)
        self.temps = np.ones(S, np.float32)
        self._admit_fn = jax.jit(self._admit_impl, donate_argnums=(0,))
        self._decode_fn = jax.jit(self._decode_impl, donate_argnums=(0,))

    # ------------------------------------------------------------ programs

    def _admit_impl(self, caches, store, prompt, prompt_len, slot, key,
                    temp):
        """Prefill one request; splice its cache into `slot`.

        prompt: (max_prompt_len,) int32, zero-padded.  Pad positions
        beyond prompt_len DO write garbage kv into the mini cache, but
        write-then-attend makes them unreachable: decode at position p
        overwrites p before any row attends it.
        """
        cfg, spec = self.cfg, self.spec
        params = _materialize(store, self._meta, cfg.dtype)
        mini = init_caches(cfg, 1, spec.max_prompt_len)

        def pre(carry, i):
            mini, sel = carry
            logits, mini = forward_step(cfg, params, prompt[i][None, None],
                                        mini, i)
            # keep the logits of the LAST real prompt token
            sel = jnp.where(i == prompt_len - 1,
                            logits.astype(jnp.float32), sel)
            return (mini, sel), None

        (mini, sel), _ = jax.lax.scan(
            pre, (mini, jnp.zeros((1, cfg.vocab_size), jnp.float32)),
            jnp.arange(spec.max_prompt_len))
        # token at absolute position t is sampled with fold_in(key, t):
        # the first generated token sits at position prompt_len
        kf = jax.random.fold_in(key, prompt_len)
        first = _sample_rows(sel, kf[None], temp[None], spec.top_k)[0]
        out = []
        for (big_k, big_v), (mk, mv) in zip(caches, mini):
            big_k = jax.lax.dynamic_update_slice(big_k, mk, (slot, 0, 0, 0))
            big_v = jax.lax.dynamic_update_slice(big_v, mv, (slot, 0, 0, 0))
            out.append((big_k, big_v))
        return out, first.astype(jnp.int32)

    def _decode_impl(self, caches, store, tok, pos, active, keys, temps):
        """K fused decode steps over all slots; returns (K, S) tokens."""
        cfg, spec = self.cfg, self.spec
        params = _materialize(store, self._meta, cfg.dtype)
        L = spec.max_len

        def step(carry, _):
            caches, tok, pos = carry
            pos_s = jnp.minimum(pos, L - 1)
            logits, caches = forward_step(cfg, params, tok[:, None],
                                          caches, pos_s)
            nxt = pos_s + 1
            kf = jax.vmap(jax.random.fold_in)(keys, nxt)
            sampled = _sample_rows(logits, kf, temps,
                                   spec.top_k).astype(tok.dtype)
            # frozen slots: pos/tok do not advance (jnp.where, not cond)
            tok = jnp.where(active, sampled, tok)
            pos = jnp.where(active, nxt, pos)
            return (caches, tok, pos), sampled

        (caches, _, _), toks = jax.lax.scan(
            step, (caches, tok, pos), None, length=spec.fused_tokens)
        return caches, toks

    # ------------------------------------------------------------- host API

    def free_slots(self) -> List[int]:
        return [i for i in range(self.spec.max_slots)
                if not self.active[i]]

    def admit(self, slot: int, prompt: List[int], seed: int,
              temperature: float = 1.0, max_new_tokens: int = 0) -> int:
        """Admit a request into a free slot; returns its FIRST generated
        token (the one readback this boundary op pays — it is also the
        time-to-first-token mark)."""
        spec = self.spec
        plen = len(prompt)
        if not (0 < plen <= spec.max_prompt_len):
            raise ValueError(f"prompt length {plen} not in "
                             f"(0, {spec.max_prompt_len}]")
        if plen + max(1, max_new_tokens) > spec.max_len:
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_len {spec.max_len}")
        if self.active[slot]:
            raise ValueError(f"slot {slot} is occupied")
        padded = np.zeros(spec.max_prompt_len, np.int32)
        padded[:plen] = np.asarray(prompt, np.int32)
        key = np.asarray(jax.random.PRNGKey(seed), np.uint32)
        self.caches, first = self._admit_fn(
            self.caches, self._store, jnp.asarray(padded),
            jnp.int32(plen), jnp.int32(slot), jnp.asarray(key),
            jnp.float32(temperature))
        first_tok = int(first)  # boundary readback (TTFT mark)
        self.tok[slot] = first_tok
        self.pos[slot] = plen
        self.active[slot] = True
        self.keys[slot] = key
        self.temps[slot] = temperature
        return first_tok

    def retire(self, slot: int):
        """Free a slot — host write only; the row freezes via the active
        mask on the next dispatch and its cache is overwritten by the
        next tenant (write-then-attend)."""
        self.active[slot] = False

    def decode_window(self) -> np.ndarray:
        """One fused K-token dispatch over all slots.

        Returns the (K, S) token block — the single host readback of the
        window; rows of inactive slots are garbage and must be masked by
        the caller's slot bookkeeping.
        """
        self.caches, toks = self._decode_fn(
            self.caches, self._store, jnp.asarray(self.tok),
            jnp.asarray(self.pos), jnp.asarray(self.active),
            jnp.asarray(self.keys), jnp.asarray(self.temps))
        out = np.asarray(toks)  # the ONE readback per fused window
        k = self.spec.fused_tokens
        act = self.active
        if act.any():
            self.tok[act] = out[-1, act]
            self.pos[act] += k
        return out

    def sync_from_trainer(self, params: Dict):
        """One-hop weight refresh from a live trainer (compose with
        rl/hybrid.HybridEngine.sync_to_decode for the mesh hop).  Same
        tree structure → the store stays a jit *argument* and no program
        retraces; in-flight requests keep their caches (they continue
        under the new weights, the standard continuous-batching
        contract)."""
        store, meta = _quantize_tree(params, self.spec.quant)
        if jax.tree_util.tree_structure((store, meta)) != \
                jax.tree_util.tree_structure((self._store, self._meta)):
            raise ValueError("refreshed params have a different tree "
                             "structure — build a new engine")
        self._store = store

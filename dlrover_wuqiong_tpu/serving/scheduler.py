"""Host-side slot scheduler: admission, window bookkeeping, telemetry.

Parity: the admission/iteration loop of a vLLM-style engine (the
reference's serving backend), reshaped around the TPU engine's window
contract: ALL scheduling decisions happen at fused-window boundaries
(serving/engine.py) — admissions, retirements, deadline checks and
ledger credits — never inside the device program.

The scheduler owns everything per-request: remaining-token budgets,
output accumulation, deadlines, the serving ledger marks
(telemetry/serving.py) and the per-request trace tree.  Trace ids are
DERIVED from the request id (md5), so when a killed worker's requests
are re-admitted on another worker, both workers' spans join ONE tree
per request — the property the serve-drain drill reconstructs from
flight dumps.

Over-generation is by design: the engine's fused window emits K tokens
for every active slot; a request finishing mid-window simply has its
surplus tokens discarded here (rows are independent, so computing them
costs nothing extra and keeps the program static).
"""

from __future__ import annotations

import hashlib
import time
from typing import Dict, List, Optional

from ..common.messages import ServeRequest, ServeResult
from ..telemetry import spans as tspans
from ..telemetry.serving import get_serve_ledger


def request_trace_id(request_id: str) -> str:
    """Deterministic trace id: spans for one request form one tree even
    when its lifecycle spans two worker processes (kill + re-admit)."""
    return hashlib.md5(request_id.encode()).hexdigest()[:16]


def _span_for(request_id: str, name: str, attrs: Dict):
    """Record a lifecycle span under the request's own trace."""
    with tspans.extract({"trace_id": request_trace_id(request_id),
                         "span_id": ""}):
        tspans.span_event(name, {"request_id": request_id, **attrs})


class _Slot:
    def __init__(self, req: ServeRequest, t_admit: float):
        self.req = req
        self.tokens: List[int] = []
        self.t_admit = t_admit
        self.t_first = 0.0


class SlotScheduler:
    """Drives one ServingEngine: queue → slots → results."""

    def __init__(self, engine, ledger=None):
        self.engine = engine
        self.ledger = ledger or get_serve_ledger()
        self.queue: List[ServeRequest] = []
        self.slots: Dict[int, _Slot] = {}
        self.results: List[ServeResult] = []

    # ------------------------------------------------------------ intake

    def submit(self, req: ServeRequest):
        self.ledger.count("submitted")
        self.queue.append(req)

    def pending(self) -> int:
        return len(self.queue)

    def active(self) -> int:
        return len(self.slots)

    def idle(self) -> bool:
        return not self.queue and not self.slots

    # ------------------------------------------------------------ window

    def _admit_one(self, slot: int, req: ServeRequest):
        eng = self.engine
        t0 = time.monotonic()
        with self.ledger.window("prefill"):
            first = eng.admit(slot, list(req.prompt), int(req.seed),
                              temperature=float(req.temperature),
                              max_new_tokens=int(req.max_new_tokens))
        st = _Slot(req, t0)
        st.t_first = time.monotonic()  # first token rides the admit
        st.tokens.append(first)
        self.slots[slot] = st
        self.ledger.note_admit(req.request_id)
        self.ledger.count("tokens_out")  # the admit's first token
        # the admit prefill produces the first token in the same dispatch
        self.ledger.note_first_token(req.request_id)
        _span_for(req.request_id, "serve:admit",
                  {"slot": slot, "prompt_len": len(req.prompt)})
        if len(st.tokens) >= max(1, int(req.max_new_tokens)):
            self._finish(slot, "length")

    def _finish(self, slot: int, reason: str):
        st = self.slots.pop(slot)
        self.engine.retire(slot)
        now = time.monotonic()
        res = ServeResult(
            request_id=st.req.request_id,
            tokens=[int(t) for t in st.tokens],
            finish_reason=reason,
            latency_s=now - st.t_admit,
            ttft_s=st.t_first - st.t_admit)
        self.results.append(res)
        # tokens_out was already credited as tokens were produced (admit
        # + windows) — counting len(tokens) here would double-count
        self.ledger.note_finish(st.req.request_id)
        _span_for(st.req.request_id, "serve:finish",
                  {"slot": slot, "tokens": len(st.tokens),
                   "finish_reason": reason,
                   "latency_s": res.latency_s})

    def step(self) -> int:
        """One boundary + one fused window.  Returns generated-token
        count (0 when fully idle)."""
        with self.ledger.window("admission"):
            for slot in self.engine.free_slots():
                if not self.queue:
                    break
                self._admit_one(slot, self.queue.pop(0))
        if not self.slots:
            return 0
        with self.ledger.window("decode"):
            out = self.engine.decode_window()  # (K, S)
        produced = 0
        k = out.shape[0]
        for slot in list(self.slots):
            st = self.slots[slot]
            want = max(1, int(st.req.max_new_tokens)) - len(st.tokens)
            take = min(k, want)  # surplus window tokens are discarded
            st.tokens.extend(int(t) for t in out[:take, slot])
            produced += take
            self.ledger.count("tokens_out", take)
            if len(st.tokens) >= max(1, int(st.req.max_new_tokens)):
                self._finish(slot, "length")
            elif st.req.deadline_s and \
                    time.monotonic() - st.t_admit > st.req.deadline_s:
                self._finish(slot, "deadline")
        return produced

    def take_results(self) -> List[ServeResult]:
        out, self.results = self.results, []
        return out


class LocalServer:
    """In-process serving front (bench.py, tests, __graft_entry__):
    submit requests, run windows until drained, collect results."""

    def __init__(self, engine):
        self.scheduler = SlotScheduler(engine)

    def submit(self, request_id: str, prompt: List[int],
               max_new_tokens: int = 16, seed: int = 0,
               temperature: float = 1.0):
        self.scheduler.submit(ServeRequest(
            request_id=request_id, prompt=list(prompt),
            max_new_tokens=max_new_tokens, seed=seed,
            temperature=temperature, submitted_at=time.time()))

    def drain(self, max_windows: int = 10_000) -> Dict[str, List[int]]:
        """Run windows until every submitted request finished; returns
        {request_id: tokens}."""
        out: Dict[str, List[int]] = {}
        windows = 0
        while not self.scheduler.idle():
            if windows >= max_windows:
                raise RuntimeError(f"drain exceeded {max_windows} windows")
            self.scheduler.step()
            windows += 1
            for res in self.scheduler.take_results():
                out[res.request_id] = list(res.tokens)
        return out

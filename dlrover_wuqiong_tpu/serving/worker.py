"""Master-backed decode worker: lease → decode windows → durable results.

Parity: reference `dlrover/python/elastic_agent/master_client.py` task
loop (get_task → work → report_task_result) — the serving worker is the
same shape over the Serve* verb family: lease requests (CRITICAL +
idem, like get_task), run fused windows, report results (CRITICAL +
idem — the ack is what lets the master release the lease, so a SIGKILL
between decode and ack re-queues the requests via `recover_node` and
nothing is dropped).

Every control-plane touch goes through MasterClient (retry_call-routed);
a master outage degrades gracefully: the worker keeps decoding what it
holds, credits ``degraded`` on the serving ledger for the time it spent
blocked, and re-leases when the master answers again.

The span buffer is flushed to the flight recorder directory with every
stats push, so a worker killed mid-traffic leaves its request spans on
disk — the serve-drain drill reconstructs one trace tree per request
from the dumps of BOTH worker generations (trace ids are derived from
request ids, scheduler.request_trace_id).
"""

from __future__ import annotations

import time
from typing import Optional

from ..common.comm import MasterUnreachableError, RpcError
from ..common.log import get_logger
from ..telemetry import spans as tspans
from ..telemetry.recorder import get_recorder
from ..telemetry.serving import get_serve_ledger
from .scheduler import SlotScheduler

logger = get_logger("serving.worker")


class ServingWorker:
    """One decode worker process driving one ServingEngine."""

    def __init__(self, client, engine, ckpt_dir: str = "",
                 stats_every: int = 4, idle_sleep_s: float = 0.05):
        self.client = client
        self.engine = engine
        self.scheduler = SlotScheduler(engine)
        self.ledger = self.scheduler.ledger
        self.ckpt_dir = ckpt_dir
        self.stats_every = max(1, stats_every)
        self.idle_sleep_s = idle_sleep_s
        self._windows = 0

    # ------------------------------------------------------------ plumbing

    def _lease(self):
        free = len(self.engine.free_slots()) - self.scheduler.pending()
        if free <= 0:
            return
        try:
            leased = self.client.lease_serve_requests(max_requests=free)
        except (RpcError, MasterUnreachableError) as e:
            # unreachable time is attributed, not hidden: the worker
            # keeps decoding what it already holds
            self.ledger.account("degraded", 0.0)
            logger.warning("lease failed (%s) — continuing with held "
                           "requests", type(e).__name__)
            return
        for req in leased:
            self.scheduler.submit(req)

    def _report_results(self):
        results = self.scheduler.take_results()
        if not results:
            return
        if self.ckpt_dir:
            # durability ORDER: spans hit disk before the master learns
            # the request finished — once a result is master-visible its
            # trace tree must be reconstructable even if a SIGKILL lands
            # on the very next instruction (serve-drain pins this)
            get_recorder().flush(self.ckpt_dir, "serve-results")
        t0 = time.monotonic()
        try:
            self.client.report_serve_results(results)
        except (RpcError, MasterUnreachableError):
            # results must not be lost: put them back for the next loop
            self.ledger.account("degraded", time.monotonic() - t0)
            self.scheduler.results.extend(results)
            logger.warning("result report failed — will retry %d results",
                           len(results))

    def _push_stats(self, force: bool = False):
        if not force and self._windows % self.stats_every:
            return
        try:
            self.client.report_serve_stats(
                self.ledger.snapshot(),
                active_slots=self.scheduler.active())
        except (RpcError, MasterUnreachableError):
            pass  # BUFFERED path already absorbs outages; belt+braces
        if self.ckpt_dir:
            # spans → disk so a SIGKILL cannot erase this worker's part
            # of the per-request trace trees
            get_recorder().flush(self.ckpt_dir, "serve-stats")

    # ------------------------------------------------------------ run loop

    def run(self, max_seconds: Optional[float] = None):
        """Serve until `max_seconds` (None = forever / until killed)."""
        tspans.set_process_role("serve-worker")
        self.ledger.start()
        t0 = time.monotonic()
        while max_seconds is None or time.monotonic() - t0 < max_seconds:
            self._lease()
            if self.scheduler.idle():
                with self.ledger.window("idle"):
                    time.sleep(self.idle_sleep_s)
            else:
                self.scheduler.step()
            self._report_results()
            self._windows += 1
            self._push_stats()
        self._report_results()
        self._push_stats(force=True)

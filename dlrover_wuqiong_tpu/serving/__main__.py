"""Standalone decode-worker entrypoint.

Parity: reference `dlrover/python/elastic_agent/torch/training.py`'s
node entrypoint (agent process joining a master by address) — here the
node is a SERVING worker joining the same control plane.

    python -m dlrover_wuqiong_tpu.serving --master HOST:PORT --node-id N \
        [--slots 4] [--max-len 64] [--max-prompt-len 16] \
        [--fused-tokens 4] [--quant int8] [--seconds 30] \
        [--ckpt-dir DIR] [--model-seed 0]

Builds a GPTConfig.nano() model with seed-deterministic params (every
worker generation materializes the SAME weights, so a request re-admitted
after a worker kill continues bit-identically — the serve-drain drill
depends on this), then runs the ServingWorker loop against the master's
Serve* verbs.  CPU-only self-provisioning mirrors __graft_entry__.py:
the env var must be set BEFORE jax initializes in this process.
"""

from __future__ import annotations

import os
import sys


def main(argv) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS", "cpu"))

    args = {"master": "", "node_id": 1, "slots": 4, "max_len": 64,
            "max_prompt_len": 16, "fused_tokens": 4, "quant": "",
            "seconds": 0.0, "ckpt_dir": "", "model_seed": 0,
            "stats_every": 2}
    it = iter(argv)
    for a in it:
        key = a.lstrip("-").replace("-", "_")
        if key in args:
            raw = next(it)
            cur = args[key]
            args[key] = type(cur)(raw) if not isinstance(cur, str) \
                else raw
        else:
            print(f"unknown arg {a}", file=sys.stderr)
            return 2
    if not args["master"]:
        print("--master HOST:PORT is required", file=sys.stderr)
        return 2

    from ..agent.master_client import MasterClient
    from ..models.gpt import GPT, GPTConfig
    from .engine import ServeSpec, ServingEngine
    from .worker import ServingWorker

    cfg = GPTConfig.nano()
    params = GPT(cfg).init_params(jax.random.PRNGKey(args["model_seed"]))
    spec = ServeSpec(max_slots=args["slots"], max_len=args["max_len"],
                     max_prompt_len=args["max_prompt_len"],
                     fused_tokens=args["fused_tokens"],
                     quant=args["quant"])
    engine = ServingEngine(cfg, params, spec)
    client = MasterClient(args["master"], node_id=args["node_id"],
                          node_type="serve-worker")
    try:
        client.register_node(node_rank=args["node_id"])
    except Exception:  # noqa: BLE001 — registration is best-effort for
        # standalone drills; leases work without it
        pass
    worker = ServingWorker(client, engine, ckpt_dir=args["ckpt_dir"],
                           stats_every=args["stats_every"])
    try:
        worker.run(max_seconds=args["seconds"] or None)
    finally:
        client.close()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

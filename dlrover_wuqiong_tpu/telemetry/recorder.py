"""Flight recorder: bounded per-process event ring, flushed on faults.

Parity: reference `dlrover/python/master/node/event_callback.py` +
`diagnosis/diagnostician.py` (the reference reacts to faults with live
callbacks but keeps no bounded pre-fault history — post-mortems grep pod
logs).  An aircraft-FDR-style ring fixes that: the LAST N structured
events (spans, node events, ledger state transitions, free-form marks)
are always in memory, and a fault/SIGTERM/diagnosis-restart flushes them
to ``$ckpt_dir/flight/`` where they survive the process.

Dump layout (ADD-ONLY schema, pinned by tests/test_telemetry.py):

    $ckpt_dir/flight/<role>-<pid>-<reason>-<seq>.json
    {"schema": 1, "role", "pid", "reason", "flushed_at", "flushed_mono",
     "ledger": <ledger snapshot or null>,
     "serve_ledger": <serve-ledger snapshot or null>,
     "perf": <latest PerfSnapshot or null — telemetry/perf.py>,
     "events": [...]}

Events are ``{"t_wall", "t_mono", "kind", "name", "data"}``; ``kind`` is
one of span | node_event | state | mark.  Spans recorded here carry
their full trace fields, so one restore reconstructs as a single trace
tree across agent/master/saver dumps (tools/goodput_report.py --flight).

Clocks: each event carries BOTH the wall clock (cross-process alignment)
and the monotonic clock; the envelope's ``flushed_at``/``flushed_mono``
pair anchors the process's monotonic timeline to the wall at flush time,
so telemetry/timeline.py can order a process's own events immune to wall
steps (``wall = t_mono + (flushed_at - flushed_mono)``).  Dumps written
before the monotonic fields existed fall back to ``t_wall`` there.

Writes are write-tmp-then-rename (atomic publish); flushing is
best-effort and must never take down the faulting process's last words.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

FLIGHT_SCHEMA_VERSION = 1

#: ring capacity (drop-oldest); big enough for minutes of control-plane
#: activity, small enough to never matter for memory
_MAX_EVENTS = 4096


def flight_dir(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, "flight")


class FlightRecorder:
    """Bounded ring of recent structured events for one process."""

    def __init__(self, max_events: int = _MAX_EVENTS):
        self._lock = threading.Lock()
        self._ring: "deque[Dict]" = deque(maxlen=max_events)
        self._seq = 0

    def record(self, kind: str, name: str, data: Optional[Dict] = None):
        # t_wall is a persisted cross-process timestamp (sanctioned wall
        # use); t_mono is the anchor-safe sibling timeline.py orders by
        evt = {"t_wall": time.time(), "t_mono": time.monotonic(),
               "kind": kind, "name": name, "data": data or {}}
        with self._lock:
            self._ring.append(evt)

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def flush(self, ckpt_dir: str, reason: str) -> Optional[str]:
        """Dump the ring to ``$ckpt_dir/flight/``; returns the path or
        None (flush failures are swallowed — last words, not a new
        fault)."""
        if not ckpt_dir:
            return None
        try:
            from .ledger import get_ledger
            from .perf import latest_snapshot as latest_perf_snapshot
            from .serving import get_serve_ledger
            from .spans import process_role

            out_dir = flight_dir(ckpt_dir)
            os.makedirs(out_dir, exist_ok=True)
            with self._lock:
                self._seq += 1
                seq = self._seq
            name = (f"{process_role()}-{os.getpid()}-"
                    f"{reason.replace('/', '_')}-{seq}.json")
            path = os.path.join(out_dir, name)
            payload = {
                "schema": FLIGHT_SCHEMA_VERSION,
                "role": process_role(),
                "pid": os.getpid(),
                "reason": reason,
                # the wall/monotonic PAIR is the anchor: both stamped
                # back to back so their difference maps this process's
                # t_mono values onto the shared wall timeline
                "flushed_at": time.time(),
                "flushed_mono": time.monotonic(),
                "ledger": (get_ledger().snapshot()
                           if get_ledger().started() else None),
                "serve_ledger": (get_serve_ledger().snapshot()
                                 if get_serve_ledger().started()
                                 else None),
                "perf": latest_perf_snapshot(),
                "events": self.snapshot(),
            }
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return path
        except Exception:  # noqa: BLE001 — never raise from a fault path
            return None


def load_flight_dumps(ckpt_dir: str) -> List[Dict]:
    """All parseable dumps under ``$ckpt_dir/flight/``, oldest first."""
    out_dir = flight_dir(ckpt_dir)
    dumps: List[Dict] = []
    if not os.path.isdir(out_dir):
        return dumps
    for name in sorted(os.listdir(out_dir)):
        if not name.endswith(".json") or ".tmp" in name:
            continue
        try:
            with open(os.path.join(out_dir, name)) as f:
                d = json.load(f)
            d["_file"] = name
            dumps.append(d)
        except (OSError, ValueError):
            continue
    dumps.sort(key=lambda d: d.get("flushed_at", 0.0))
    return dumps


_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def get_recorder() -> FlightRecorder:
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder()
        return _RECORDER


def reset_recorder() -> FlightRecorder:
    global _RECORDER
    with _RECORDER_LOCK:
        _RECORDER = FlightRecorder()
        return _RECORDER

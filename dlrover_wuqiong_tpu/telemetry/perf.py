"""Perf observatory: always-on in-train profiling windows, a versioned
perf-baseline store, and a regression sentinel wired into the policy loop.

Parity: reference `atorch/dev/xpu_timer/common/manager.cc` (always-on
kernel/collective timing exported to Prometheus) and the Brain-side
anomaly intent of `dlrover/python/master/stats/reporter.py` — but the
reference detects *hangs*, not *slow*: a job that silently loses 15%
throughput (a DWT_FA_* env drift, a retrace storm, a degraded remat
choice after a re-mesh) passes every liveness check it has.

TPU redesign: per-op host hooks (LD_PRELOAD shims) don't exist on TPU,
so the observatory samples instead of intercepting — every N fusion
boundaries the trainer wraps ONE fused dispatch in a `StepProfiler`
window (utils/profiler.py) and this module folds the xplane op-category
split (utils/xplane.py) plus host step-time into a `PerfSnapshot` dict:

- windows are SELF-LIMITING: the measured profiling overhead (trace
  start/stop + xplane parse, host-side only — zero new device readbacks)
  is ledger-credited to the ``profile`` state and the next window is
  skipped until that overhead amortizes below ``overhead_budget`` (1%)
  of wall;
- snapshots are keyed by the FULL executable identity — strategy
  fingerprint, fused-K, backend and the trace-time env toggles
  (auto/compile_cache.py TRACE_ENV_VARS) — because each of those changes
  the HLO, and comparing step times across different executables is how
  perf dashboards lie;
- the baseline store (``$ckpt_dir/perf/baseline.json``) keeps ROBUST
  rolling stats per executable key (median + MAD — shared-tunnel chip
  drift is ±10% run-to-run, so means/stddevs would both chase outliers),
  published atomic tmp+rename like the preempt table;
- the regression sentinel fires a ``perf-regression`` event only after
  M CONSECUTIVE windows beyond the MAD bound (one slow window on a noisy
  tunnel is weather, M in a row is climate), attributing the op category
  that moved; windows beyond the bound are NOT folded into the baseline
  (a sustained regression must not become the new normal);
- a compile/retrace observatory snapshots the persistent-cache counters
  (auto/compile_cache.py) per window: cache misses GROWING in steady
  state mean something is retracing the step — itself a ``retrace``
  event, because a retrace storm is a perf regression whose step time
  may look fine between compiles.

The sentinel/baseline math is deliberately jax-free (plain floats) so
`__graft_entry__.py`'s perf smoke and the chaos ``perf-regress`` drill
exercise the exact firing logic without a backend.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..common.log import get_logger

logger = get_logger("perf")

PERF_SCHEMA = 1

# ADD-ONLY (tests/test_perf.py pins it): consumers — flight dumps, the
# PerfSnapshotReport verb, tools/perf_report.py — key into this dict, so
# fields extend, never rename.
PERF_SNAPSHOT_KEYS = (
    "schema", "key", "step", "fused_k", "step_time_s",
    "baseline_median_s", "baseline_mad_s", "baseline_n", "categories",
    "overhead_s", "overhead_frac", "windows", "skipped",
    "cache_hits", "cache_misses", "retraces", "regressions",
    "last_event", "captured_at", "tuned_variant",
)

# ADD-ONLY: the perf-regression / retrace event envelope (node-event
# message payloads + incident timeline rows embed it verbatim).
PERF_EVENT_KEYS = (
    "kind", "key", "step", "step_time_s", "baseline_median_s",
    "baseline_mad_s", "deviation", "consecutive", "category",
    "category_delta_s",
)

# MAD → sigma for a normal distribution; the bound math quotes
# deviations in sigma-equivalents so thresholds read like z-scores.
_MAD_SIGMA = 1.4826


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _mad(xs: List[float], med: Optional[float] = None) -> float:
    if not xs:
        return 0.0
    m = _median(xs) if med is None else med
    return _median([abs(x - m) for x in xs])


# fallback when auto/compile_cache is unimportable (it is jax-free today;
# this guards the jax-free smoke against a future jax import there)
_TRACE_ENV_FALLBACK = ("DWT_FA_NO_FUSED", "DWT_FA_PACK", "DWT_FA_STREAMED",
                       "DWT_FP8_DENSE", "DWT_REMAT_POLICY")


def executable_key(strategy_fingerprint: str, fused_steps: int,
                   backend: str) -> str:
    """Digest of the full executable identity a step time belongs to.

    Folds the same trace-time env toggles as the framework compile-cache
    key (auto/compile_cache.py train_step_cache_key): two processes with
    different DWT_FA_* values run DIFFERENT HLO from the same python
    call, and their step times must never share a baseline row.
    """
    try:
        from ..auto.compile_cache import TRACE_ENV_VARS
    except Exception:  # noqa: BLE001 — keep the sentinel math importable
        TRACE_ENV_VARS = _TRACE_ENV_FALLBACK
    blob = json.dumps({
        "strategy": str(strategy_fingerprint),
        "fused": int(fused_steps),
        "backend": str(backend),
        "env": {k: os.environ.get(k, "") for k in TRACE_ENV_VARS},
    }, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class BaselineStore:
    """Rolling per-executable-key window stats at
    ``$ckpt_dir/perf/baseline.json`` (versioned, atomic tmp+rename like
    the preempt table — a crashed writer never tears the baseline).

    With an empty path the store is memory-only (drills, tests, jobs
    without a checkpoint dir)."""

    SCHEMA = 1

    def __init__(self, path: str = "", max_samples: int = 64):
        self.path = path
        self.max_samples = max_samples
        self._data: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------- state
    def _load(self) -> Dict[str, Any]:
        if self._data is not None:
            return self._data
        data: Dict[str, Any] = {"schema": self.SCHEMA, "keys": {}}
        if self.path and os.path.isfile(self.path):
            try:
                with open(self.path) as f:
                    raw = json.load(f)
                if isinstance(raw, dict) and isinstance(
                        raw.get("keys"), dict):
                    data["keys"] = raw["keys"]
            except (OSError, ValueError):
                # a torn/corrupt baseline is re-learned, never fatal
                logger.warning("unreadable perf baseline %s — starting "
                               "fresh", self.path, exc_info=True)
        self._data = data
        return data

    def _row(self, key: str) -> Dict[str, Any]:
        keys = self._load()["keys"]
        row = keys.get(key)
        if not isinstance(row, dict) or "step_s" not in row:
            row = {"step_s": [], "categories": {}}
            keys[key] = row
        return row

    # ----------------------------------------------------------- updates
    def update(self, key: str, step_time_s: float,
               categories: Optional[Dict[str, float]] = None) -> None:
        row = self._row(key)
        row["step_s"].append(float(step_time_s))
        del row["step_s"][:-self.max_samples]
        for cat, sec in (categories or {}).items():
            xs = row["categories"].setdefault(str(cat), [])
            xs.append(float(sec))
            del xs[:-self.max_samples]

    def stats(self, key: str) -> Optional[Dict[str, float]]:
        xs = self._row(key)["step_s"]
        if not xs:
            return None
        med = _median(xs)
        return {"median": med, "mad": _mad(xs, med), "n": len(xs)}

    def category_medians(self, key: str) -> Dict[str, float]:
        return {cat: _median(xs)
                for cat, xs in self._row(key)["categories"].items() if xs}

    def aggregate_categories(self) -> Dict[str, float]:
        """Per-category medians SUMMED across every executable key — the
        coarse op-category profile (matmul vs collective vs host) of the
        whole run so far.  The variant autotuner orders its candidate
        matrix by this split (auto/tuner.py order_variants, ROADMAP 4d):
        a matmul-bound profile tries quant variants first, a
        collective-bound one tries pack/stream first.  Empty until some
        key has categorized windows — the tuner then falls back to
        declaration order."""
        out: Dict[str, float] = {}
        for key in list(self._load()["keys"]):
            for cat, med in self.category_medians(key).items():
                out[cat] = out.get(cat, 0.0) + med
        return out

    # ----------------------------------------------------------- publish
    def publish(self) -> bool:
        """Atomic write-tmp-then-rename (fsync'd) — same durability shape
        as checkpoint markers and the preempt table."""
        if not self.path:
            return False
        data = self._load()
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(data, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            return True
        except OSError:
            logger.warning("perf baseline publish to %s failed", self.path,
                           exc_info=True)
            return False


class RegressionSentinel:
    """M-consecutive-windows-beyond-the-MAD-bound detector (per key).

    The bound is ``median + max(nsig * 1.4826 * MAD, min_rel * median)``:
    the MAD term tracks the key's OBSERVED drift, the relative floor
    keeps a suspiciously quiet baseline (MAD≈0) from firing on noise the
    shared tunnel is known to produce (±10% run-to-run)."""

    def __init__(self, store: BaselineStore, m_consecutive: int = 3,
                 nsig: float = 3.0, min_rel: float = 0.08,
                 min_baseline: int = 5):
        self.store = store
        self.m_consecutive = max(1, m_consecutive)
        self.nsig = nsig
        self.min_rel = min_rel
        self.min_baseline = max(1, min_baseline)
        self._streak: Dict[str, int] = {}

    def observe(self, key: str, step_time_s: float,
                categories: Optional[Dict[str, float]] = None,
                step: int = -1) -> Tuple[bool, Optional[Dict]]:
        """(beyond_bound, fired_event). Fires exactly once per excursion,
        on the M-th consecutive beyond-bound window."""
        stats = self.store.stats(key)
        if stats is None or stats["n"] < self.min_baseline:
            self._streak[key] = 0
            return False, None
        med, mad = stats["median"], stats["mad"]
        bound = med + max(self.nsig * _MAD_SIGMA * mad,
                          self.min_rel * med)
        if step_time_s <= bound:
            self._streak[key] = 0
            return False, None
        streak = self._streak.get(key, 0) + 1
        self._streak[key] = streak
        if streak != self.m_consecutive:
            return True, None
        cat, delta = self._attribute(key, categories)
        sigma = max(_MAD_SIGMA * mad, 1e-12)
        return True, {
            "kind": "perf-regression",
            "key": key,
            "step": step,
            "step_time_s": step_time_s,
            "baseline_median_s": med,
            "baseline_mad_s": mad,
            "deviation": (step_time_s - med) / sigma,
            "consecutive": streak,
            "category": cat,
            "category_delta_s": delta,
        }

    def _attribute(self, key: str,
                   categories: Optional[Dict[str, float]]
                   ) -> Tuple[str, float]:
        """The op category whose device time grew most vs its baseline
        median — 'what moved', not just 'something is slow'."""
        if not categories:
            return "", 0.0
        base = self.store.category_medians(key)
        best, best_delta = "", 0.0
        for cat, sec in categories.items():
            delta = float(sec) - base.get(cat, 0.0)
            if delta > best_delta:
                best, best_delta = cat, delta
        if not best:  # no category grew (host-side slowdown): largest wins
            best = max(categories, key=lambda c: categories[c])
            best_delta = 0.0
        return best, best_delta


class _Window:
    """One open profiling window (StepProfiler trace around one fused
    dispatch). Created by PerfObservatory.maybe_open, closed by .close."""

    def __init__(self, prof, ctx, span_ctx, step: int, fused_k: int,
                 tdir: str, open_cost_s: float, t_run0: float,
                 key: str = ""):
        self.prof = prof
        self.ctx = ctx
        self.span_ctx = span_ctx
        self.step = step
        self.fused_k = max(1, fused_k)
        self.tdir = tdir
        self.open_cost_s = open_cost_s
        self.t_run0 = t_run0
        # executable key CAPTURED at open time: `close` may run on the
        # trainer's metrics-pump thread while the main loop re-keys the
        # observatory for a variant cutover (auto/tuner.py) — the window
        # must fold into the baseline row of the executable it measured,
        # not whichever key is current when the pump drains it
        self.key = key


class PerfObservatory:
    """Window scheduler + snapshot folder + sentinel/retrace wiring.

    The trainer calls ``maybe_open(step, fused_k)`` at each eligible
    fusion boundary (one that already carries a host readback — the
    window must contain a sync so the trace holds the device work it
    claims to time, and reusing the existing one keeps the
    blocking-readback budget at ZERO new readbacks) and ``close(win)``
    right after that readback."""

    def __init__(self, key: str = "", ckpt_dir: str = "",
                 every: int = 8, m_consecutive: int = 3,
                 overhead_budget: float = 0.01,
                 nsig: float = 3.0, min_rel: float = 0.08,
                 min_baseline: int = 5, max_samples: int = 64,
                 registry=None, on_event: Optional[Callable] = None,
                 job_name: str = "dwt"):
        path = (os.path.join(ckpt_dir, "perf", "baseline.json")
                if ckpt_dir else "")
        self.store = BaselineStore(path, max_samples=max_samples)
        self.sentinel = RegressionSentinel(
            self.store, m_consecutive=m_consecutive, nsig=nsig,
            min_rel=min_rel, min_baseline=min_baseline)
        self.key = key
        self.every = max(1, every)
        self.overhead_budget = overhead_budget
        self.on_event = on_event
        self._job = job_name
        self._reg = registry
        self._t_start = time.monotonic()
        # counters shared between the trainer's main loop (maybe_open)
        # and its metrics-pump thread (close): one lock guards them all.
        # Blocking work — the baseline publish's fsync, the profiler
        # trace teardown — stays OUTSIDE the lock (graftlint
        # blocking-under-lock); store/sentinel internals need no lock of
        # their own because `close` runs on exactly one thread at a time
        # (the pump is a single consumer; without a pump it is the main
        # loop itself).
        self._lock = threading.Lock()
        self._overhead_s = 0.0
        self._eligible = 0
        self._windows = 0
        self._skipped = 0
        self._retraces = 0
        self._regressions = 0
        self._last_event: Optional[Dict] = None
        self._cache_seen: Optional[Tuple[int, int]] = None
        self._snapshot: Optional[Dict] = None
        # active autotuner variant name ("" = untuned/default run) —
        # written by the trainer at cutover, read by the pump's close()
        self._tuned_variant = ""

    def set_tuned_variant(self, name: str) -> None:
        """Label snapshots with the variant-autotuner's active choice
        (auto/tuner.py) so PerfQuery/flight consumers can attribute a
        step-time shift to a cutover instead of a regression."""
        with self._lock:
            self._tuned_variant = str(name)

    # ----------------------------------------------------------- helpers
    def _registry(self):
        if self._reg is None:
            from ..master.metrics import get_registry

            self._reg = get_registry()
        return self._reg

    def overhead_fraction(self) -> float:
        wall = max(time.monotonic() - self._t_start, 1e-9)
        with self._lock:
            overhead = self._overhead_s
        return overhead / wall

    def snapshot(self) -> Optional[Dict]:
        with self._lock:
            return self._snapshot

    # ----------------------------------------------------------- windows
    def maybe_open(self, step: int, fused_k: int) -> Optional[_Window]:
        """Open a window on every ``every``-th eligible boundary, unless
        the self-limiter says profiling already costs ≥ budget of wall."""
        with self._lock:
            self._eligible += 1
            eligible = self._eligible
            windows = self._windows
        if (eligible - 1) % self.every:
            return None
        if windows and self.overhead_fraction() >= self.overhead_budget:
            with self._lock:
                self._skipped += 1
            return None
        from ..utils.profiler import StepProfiler

        from .spans import span

        t0 = time.monotonic()
        tdir = tempfile.mkdtemp(prefix="dwt-perf-win-")
        span_ctx = span("perf:window", {"step": step, "key": self.key,
                                        "fused_k": fused_k})
        span_ctx.__enter__()
        prof = StepProfiler(trace_dir=tdir, start_step=step, end_step=step,
                            registry=self._registry(), job_name=self._job)
        ctx = prof.step(step)
        try:
            ctx.__enter__()
        except Exception:  # noqa: BLE001 — observability must not kill train
            span_ctx.__exit__(None, None, None)
            shutil.rmtree(tdir, ignore_errors=True)
            logger.warning("perf window open failed", exc_info=True)
            return None
        return _Window(prof, ctx, span_ctx, step, fused_k, tdir,
                       open_cost_s=time.monotonic() - t0,
                       t_run0=time.monotonic(), key=self.key)

    def close(self, win: _Window) -> Optional[Dict]:
        """Fold the window into a PerfSnapshot; returns the snapshot.

        Call AFTER the boundary's existing host readback: the measured
        step time then covers dispatch + device completion, and the
        trace holds the device work."""
        t_run = time.monotonic() - win.t_run0
        t1 = time.monotonic()
        try:
            win.ctx.__exit__(None, None, None)
            win.prof.close()
        except Exception:  # noqa: BLE001 — observability must not kill train
            logger.warning("perf window close failed", exc_info=True)
        win.span_ctx.__exit__(None, None, None)
        overhead = win.open_cost_s + (time.monotonic() - t1)
        shutil.rmtree(win.tdir, ignore_errors=True)
        with self._lock:
            self._overhead_s += overhead
            self._windows += 1
        self._credit_overhead(overhead)

        key = win.key or self.key
        step_s = t_run / win.fused_k
        prof = win.prof.last_profile
        cats = ({k: float(v) for k, v in prof.categories.items()}
                if prof is not None else {})
        beyond, event = self.sentinel.observe(key, step_s, cats,
                                              step=win.step)
        if not beyond:
            # beyond-bound windows stay OUT of the baseline: a sustained
            # regression must not median its way into normal
            self.store.update(key, step_s, cats)
            self.store.publish()
        if event is not None:
            with self._lock:
                self._regressions += 1
            self._fire(event)
        self._observe_compile_counters(win.step)
        return self._fold_snapshot(win, key, step_s, cats)

    def _credit_overhead(self, seconds: float) -> None:
        try:
            from .ledger import get_ledger

            get_ledger().account("profile", seconds)
        except Exception:  # noqa: BLE001 — telemetry must never break train
            pass

    def _fire(self, event: Dict) -> None:
        with self._lock:
            self._last_event = event
        counter = {"perf-regression": "dwt_perf_regression_events",
                   "retrace": "dwt_perf_retrace_events"}.get(event["kind"])
        if counter:
            try:
                self._registry().inc(
                    counter, labels={"job": self._job},
                    help="perf observatory events by kind")
            except Exception:  # noqa: BLE001
                pass
        try:
            from .recorder import get_recorder

            get_recorder().record("perf_event", event["kind"], dict(event))
        except Exception:  # noqa: BLE001
            pass
        if self.on_event is not None:
            try:
                self.on_event(event)
            except Exception:  # noqa: BLE001 — callbacks must not kill train
                logger.warning("perf on_event callback failed",
                               exc_info=True)

    def _observe_compile_counters(self, step: int) -> None:
        """Retrace observatory: cache misses growing in steady state mean
        the step is retracing — an event even when step time looks fine."""
        try:
            from ..auto.compile_cache import counters
        except Exception:  # noqa: BLE001
            return
        now = counters.snapshot()
        with self._lock:
            prev, self._cache_seen = self._cache_seen, now
        if prev is None:
            return  # first window: compiles before it are expected
        miss_delta = now[1] - prev[1]
        if miss_delta > 0:
            with self._lock:
                self._retraces += miss_delta
            self._fire({
                "kind": "retrace", "key": self.key, "step": step,
                "step_time_s": 0.0, "baseline_median_s": 0.0,
                "baseline_mad_s": 0.0, "deviation": 0.0,
                "consecutive": miss_delta, "category": "compile",
                "category_delta_s": 0.0,
            })

    def _fold_snapshot(self, win: _Window, key: str, step_s: float,
                       cats: Dict[str, float]) -> Dict:
        stats = self.store.stats(key) or {"median": 0.0, "mad": 0.0,
                                          "n": 0}
        overhead_frac = self.overhead_fraction()
        with self._lock:
            hits, misses = self._cache_seen or (0, 0)
            snap = {
                "schema": PERF_SCHEMA,
                "key": key,
                "step": win.step,
                "fused_k": win.fused_k,
                "step_time_s": step_s,
                "baseline_median_s": stats["median"],
                "baseline_mad_s": stats["mad"],
                "baseline_n": int(stats["n"]),
                "categories": {k: round(v, 6)
                               for k, v in sorted(cats.items())},
                "overhead_s": round(self._overhead_s, 6),
                "overhead_frac": round(overhead_frac, 6),
                "windows": self._windows,
                "skipped": self._skipped,
                "cache_hits": int(hits),
                "cache_misses": int(misses),
                "retraces": self._retraces,
                "regressions": self._regressions,
                "last_event": self._last_event,
                # wall stamp: persisted into flight dumps and compared
                # across processes by the latest-SENT-wins verb (never
                # duration math)
                "captured_at": time.time(),
                "tuned_variant": self._tuned_variant,
            }
            self._snapshot = snap
        return snap


# ------------------------------------------------------------- singleton

_observatory: Optional[PerfObservatory] = None


def set_observatory(obs: Optional[PerfObservatory]) -> None:
    global _observatory
    _observatory = obs


def get_observatory() -> Optional[PerfObservatory]:
    return _observatory


def reset_observatory() -> None:
    set_observatory(None)


def latest_snapshot() -> Optional[Dict]:
    """The flight recorder's embed hook (telemetry/recorder.py flush)."""
    obs = get_observatory()
    return obs.snapshot() if obs is not None else None

"""Incident timeline: ONE causally-ordered event stream across the plane.

Parity: reference `dlrover/python/diagnosis/diagnostician.py` +
`master/node/event_callback.py` — the reference diagnoses incidents from
live in-memory state and leaves post-mortems to grepping pod logs across
processes.  Here the five observability sources this repo grew — master
journal (master/journal.py), flight-recorder dumps (recorder.py), trace
spans (spans.py), goodput/serve ledgers (ledger.py / serving.py) and
PolicyDecision history (brain/policy.py, journaled as "policy" frames) —
merge into ONE event stream a post-mortem or the live `TimelineQuery`
verb can reason over, and the replay substrate ROADMAP item 5's what-if
simulator builds on.

Ordering model (the TPU redesign, not just a sort):

- **Master events** come from the journal and are causally ordered by
  ``(fencing epoch, seq)`` — the wall ``ts`` each frame carries (add-only,
  journal.py) is used ONLY to interleave with worker events; within the
  journal a stepped wall clock cannot reorder frames because assembly
  clamps ``t_wall`` nondecreasing in (epoch, seq) order.
- **Worker events** come from flight dumps and are ordered by per-process
  monotonic→wall anchoring: each event carries ``t_mono``, each dump
  envelope carries the ``flushed_at``/``flushed_mono`` pair, and
  ``wall = t_mono + (flushed_at - flushed_mono)`` — so a worker whose
  wall clock stepped mid-incident still lands its own events in true
  order.  Dumps from before the monotonic fields fall back to ``t_wall``.
- **Correlation** is by ``trace_id`` across processes and worker
  generations; spans dedupe by ``(trace_id, span_id)`` because the
  recorder ring re-flushes cumulatively.

DETERMINISM CONTRACT: `assemble_incident` is a pure function of the disk
artifacts (no clock reads, no process state), and `incident_json` is
canonical (sorted keys, fixed separators) — the live TimelineQuery
answer and the offline `tools/incident_report.py --journal/--flight`
reconstruction are byte-equal, which chaos master-kill and serve-drain
gate on.

Across a warm-standby failover (ISSUE 20) the incident spans TWO
journal dirs — the old primary's and the promoted standby's.  Because
journal shipping mirrors frames VERBATIM (master/journal.py
ingest_frames) the shared prefix is byte-identical in both dirs, so
`read_journal_events_multi` dedups on ``(epoch, seq, kind)`` first-wins
in dir order and the union still reads as ONE causal log; the ``epoch``
frame a `failover` frame announced narrates as a ``failover`` incident
instead of a ``master_restart``.

The event envelope (`TIMELINE_EVENT_KEYS`) is ADD-ONLY, pinned by
tests/test_timeline.py.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple

TIMELINE_SCHEMA_VERSION = 1

#: ADD-ONLY event envelope (tests/test_timeline.py pins this): every
#: event in the assembled stream carries exactly these keys.
TIMELINE_EVENT_KEYS = (
    "schema", "source", "kind", "name", "t_wall", "epoch", "seq",
    "role", "pid", "trace_id", "span_id", "dur_s", "data",
)

#: ledger states the narrative attributes to a worker-failure incident
_RESTORE_STATES = ("restore_shm", "restore_replica", "restore_storage",
                   "rework")

#: ledger states a hot-swap transition credits (trainer/hotswap.py):
#: hydrate rides ``restore_replica``, cutover rides ``rework``
_HOTSWAP_STATES = ("restore_replica", "rework")

_JOURNAL_FILE = "journal.frames"
_SNAPSHOT_FILE = "snapshot.frame"


def _event(source: str, kind: str, name: str, t_wall: float,
           epoch: int = 0, seq: int = 0, role: str = "", pid: int = 0,
           trace_id: str = "", span_id: str = "", dur_s: float = 0.0,
           data: Optional[Dict] = None) -> Dict:
    return {
        "schema": TIMELINE_SCHEMA_VERSION,
        "source": source, "kind": kind, "name": name,
        "t_wall": round(float(t_wall), 6),
        "epoch": int(epoch), "seq": int(seq),
        "role": str(role), "pid": int(pid),
        "trace_id": str(trace_id), "span_id": str(span_id),
        "dur_s": round(float(dur_s), 6),
        "data": data or {},
    }


# --------------------------------------------------------- journal side


def _plain(v: Any) -> Any:
    """Typed-JSON wire encoding → plain JSON (common/serialize.py shape).

    ``{"__msg__": T, "fields": {...}}`` collapses to its fields WITHOUT
    instantiating message classes — assembly must stay deterministic and
    JSON-serializable even for frame kinds newer than this reader.
    """
    if isinstance(v, dict):
        if "__msg__" in v:
            return {k: _plain(x) for k, x in v.get("fields", {}).items()}
        return {k: _plain(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_plain(x) for x in v]
    return v


def _summary(data: Any, depth: int = 0) -> Dict:
    """Compact, deterministic projection of a frame's data: scalars and
    short scalar lists survive, big payloads become counts — the
    timeline carries event IDENTITY, not the full payload."""
    if not isinstance(data, dict):
        return {"value": data if isinstance(data, (int, float, bool, str))
                else repr(type(data).__name__)}
    out: Dict = {}
    for k, v in sorted(data.items()):
        k = str(k)
        if v is None or isinstance(v, (bool, int, float)):
            out[k] = v
        elif isinstance(v, str):
            out[k] = v if len(v) <= 120 else v[:117] + "..."
        elif isinstance(v, list):
            if len(v) <= 16 and all(
                    isinstance(x, (bool, int, float, str)) for x in v):
                out[k] = v
            else:
                out[k + "_n"] = len(v)
        elif isinstance(v, dict):
            if depth < 1:
                out[k] = _summary(v, depth + 1)
            else:
                out[k + "_keys"] = sorted(str(x) for x in v)[:8]
    return out


def _frame_data(kind: str, data: Dict) -> Dict:
    """Per-kind summary; serve_result keeps its request ids — the
    exactly-once drill gate needs result identity, not token payloads."""
    out = _summary(data)
    if kind == "serve_result" and isinstance(data.get("results"), list):
        out["request_ids"] = [
            str(r.get("request_id", "")) for r in data["results"]
            if isinstance(r, dict)]
    return out


def read_journal_events(journal_dir: str) -> List[Dict]:
    """All intact journal frames as timeline events, (epoch, seq) order.

    Reads raw lines (same torn-tail drop as MasterJournal.load, which
    never acked the torn frame) and tags each frame with the fencing
    epoch current at append time; the snapshot contributes one event
    carrying its watermark.  ``t_wall`` is clamped nondecreasing in
    stream order so a wall step between master incarnations cannot fold
    the merge order back over the causal order.
    """
    events: List[Dict] = []
    if not journal_dir or not os.path.isdir(journal_dir):
        return events
    epoch = 0
    last_wall = 0.0
    snap_path = os.path.join(journal_dir, _SNAPSHOT_FILE)
    if os.path.exists(snap_path):
        try:
            with open(snap_path, "rb") as f:
                frame = json.loads(f.read().decode("utf-8"))
            epoch = int(frame.get("epoch", 0))
            last_wall = float(frame.get("ts", 0.0) or 0.0)
            state = frame.get("state") or {}
            events.append(_event(
                "journal", "snapshot", "journal:snapshot", last_wall,
                epoch=epoch, seq=int(frame.get("seq", 0)), role="master",
                data={"covers_seq": int(frame.get("seq", 0)),
                      "state_keys": sorted(str(k) for k in state),
                      "policy_n": len(state.get("policy") or [])}))
        except (OSError, ValueError):
            pass
    path = os.path.join(journal_dir, _JOURNAL_FILE)
    if not os.path.exists(path):
        return events
    with open(path, "rb") as f:
        lines = f.read().split(b"\n")
    for line in lines:
        if not line.strip():
            continue
        try:
            frame = json.loads(line.decode("utf-8"))
        except ValueError:
            break  # torn tail — never acked, drop (journal.py contract)
        kind = str(frame.get("kind", ""))
        seq = int(frame.get("seq", 0))
        data = _plain(frame.get("data") or {})
        if kind == "epoch":
            epoch = int(data.get("epoch", epoch))
        # old frames have no ts: inherit the last seen wall (tolerant
        # replay, satellite contract) — ordering is (epoch, seq) anyway
        wall = float(frame.get("ts", 0.0) or 0.0)
        last_wall = max(last_wall, wall)
        events.append(_event(
            "journal", kind, f"journal:{kind}", last_wall, epoch=epoch,
            seq=seq, role="master", data=_frame_data(kind, data)))
    return events


def read_journal_events_multi(journal_dirs: List[str]) -> List[Dict]:
    """Events from one or more journal dirs as ONE (epoch, seq) stream.

    A warm standby's journal (master/standby.py) is a verbatim mirror
    of the primary's plus its own post-promotion tail, so across a
    failover the SAME frame exists byte-identical in both dirs: dedup
    is ``(epoch, seq, kind)`` first-wins in dir order, then the union
    sorts by ``(epoch, seq)`` and ``t_wall`` re-clamps nondecreasing.
    With zero or one dirs this IS `read_journal_events` — the
    single-journal path stays byte-identical.
    """
    dirs = [d for d in journal_dirs if d]
    if len(dirs) <= 1:
        return read_journal_events(dirs[0] if dirs else "")
    seen: set = set()
    merged: List[Dict] = []
    for d in dirs:
        for e in read_journal_events(d):
            key = (e["epoch"], e["seq"], e["kind"])
            if key in seen:
                continue
            seen.add(key)
            merged.append(e)
    merged.sort(key=lambda e: (e["epoch"], e["seq"]))
    last_wall = 0.0
    for e in merged:
        last_wall = max(last_wall, e["t_wall"])
        e["t_wall"] = last_wall
    return merged


# ---------------------------------------------------------- flight side


def anchored_wall(dump: Dict, evt: Dict) -> float:
    """Monotonic→wall anchor for one event of one dump.

    ``wall = t_mono + (flushed_at - flushed_mono)`` when both clocks are
    present (recorder.py stamps them back to back at flush); pre-anchor
    dumps fall back to the event's recorded wall clock.
    """
    fa = dump.get("flushed_at")
    fm = dump.get("flushed_mono")
    tm = evt.get("t_mono")
    if fa is not None and fm is not None and tm is not None:
        return float(tm) + (float(fa) - float(fm))
    return float(evt.get("t_wall", 0.0) or 0.0)


def read_flight_events(ckpt_dir: str) -> Tuple[List[Dict], List[Dict]]:
    """(events, latest_ledgers) from ``$ckpt_dir/flight/`` dumps.

    Spans dedupe by (trace_id, span_id), other events by their recorded
    clocks — the ring re-flushes cumulatively, and an event must appear
    ONCE no matter how many dumps carried it.  First flush wins the
    anchor (deterministic: load_flight_dumps orders by flushed_at, then
    filename).  ``latest_ledgers`` is one entry per (role, pid): the
    last embedded goodput/serve ledger snapshots, for the narrative.
    """
    from .recorder import load_flight_dumps

    events: List[Dict] = []
    ledgers: Dict[Tuple[str, int], Dict] = {}
    if not ckpt_dir:
        return events, []
    seen_spans: set = set()
    seen_other: set = set()
    for dump in load_flight_dumps(ckpt_dir):
        role = str(dump.get("role", ""))
        pid = int(dump.get("pid", 0) or 0)
        ledgers[(role, pid)] = {
            "role": role, "pid": pid,
            "ledger": dump.get("ledger"),
            "serve_ledger": dump.get("serve_ledger"),
        }
        events.append(_event(
            "flight", "flush", f"flight:{dump.get('reason', '')}",
            float(dump.get("flushed_at", 0.0) or 0.0), role=role, pid=pid,
            data={"reason": str(dump.get("reason", "")),
                  "file": str(dump.get("_file", "")),
                  "events_n": len(dump.get("events") or [])}))
        for evt in dump.get("events") or []:
            kind = str(evt.get("kind", ""))
            wall = anchored_wall(dump, evt)
            if kind == "span":
                rec = evt.get("data") or {}
                key = (rec.get("trace_id", ""), rec.get("span_id", ""))
                if key in seen_spans:
                    continue
                seen_spans.add(key)
                events.append(_event(
                    "flight", "span", str(rec.get("name", "")), wall,
                    role=str(rec.get("role", role)),
                    pid=int(rec.get("pid", pid) or 0),
                    trace_id=str(rec.get("trace_id", "")),
                    span_id=str(rec.get("span_id", "")),
                    dur_s=float(rec.get("dur_s", 0.0) or 0.0),
                    data={"parent_span": str(rec.get("parent_span", "")),
                          "status": str(rec.get("status", "ok")),
                          "attrs": _summary(rec.get("attrs") or {})}))
            else:
                key = (pid, kind, str(evt.get("name", "")),
                       repr(evt.get("t_wall")), repr(evt.get("t_mono")))
                if key in seen_other:
                    continue
                seen_other.add(key)
                events.append(_event(
                    "flight", kind, str(evt.get("name", "")), wall,
                    role=role, pid=pid,
                    data=_summary(evt.get("data") or {})))
    latest = [ledgers[k] for k in sorted(ledgers)]
    return events, latest


# ------------------------------------------------------------- assembly


def _merge(journal_events: List[Dict], flight_events: List[Dict]
           ) -> List[Dict]:
    """One stream: journal events keep (epoch, seq) order (their clamped
    t_wall already respects it), flight events interleave by anchored
    wall; ties break journal-first, then causally/by-process."""
    keyed = []
    for i, e in enumerate(journal_events):
        keyed.append(((e["t_wall"], 0, e["epoch"], e["seq"], 0, i), e))
    for i, e in enumerate(flight_events):
        keyed.append(((e["t_wall"], 1, 0, 0, e["pid"], i), e))
    keyed.sort(key=lambda kv: kv[0])
    return [e for _, e in keyed]


def _policy_decisions(journal_events: List[Dict]) -> List[Dict]:
    out = []
    for e in journal_events:
        if e["kind"] != "policy":
            continue
        d = e["data"].get("decision")
        out.append({"seq": e["seq"], "epoch": e["epoch"],
                    "t_wall": e["t_wall"],
                    "decision": d if isinstance(d, dict) else {}})
    return out


def build_narrative(journal_events: List[Dict], ledgers: List[Dict]
                    ) -> Dict:
    """Automated downtime attribution: which seconds were lost, to which
    ledger state, triggered by which journaled event, answered by which
    policy decision.

    Incident triggers are journal facts — an ``epoch`` frame beyond the
    first is a master restart (lost seconds attribute to ``degraded``:
    every second a verb burned blocked on the dead master), a ``recover``
    frame is a worker failure (lost seconds attribute to the restore_*
    + rework states).  The answering decision is the first journaled
    ``policy`` frame at or after the trigger in (epoch, seq) order.
    """
    states: Dict[str, float] = {}
    wall = 0.0
    productive = 0.0
    for entry in ledgers:
        led = entry.get("ledger") or {}
        wall += float(led.get("wall_s", 0.0) or 0.0)
        for k, v in (led.get("states") or {}).items():
            states[str(k)] = states.get(str(k), 0.0) + float(v)
    productive = states.get("productive", 0.0)
    lost = {k: round(v, 6) for k, v in sorted(states.items())
            if k != "productive" and v > 0}
    decisions = _policy_decisions(journal_events)

    def _answer(epoch: int, seq: int) -> Optional[Dict]:
        for d in decisions:
            if (d["epoch"], d["seq"]) >= (epoch, seq):
                dec = d["decision"]
                return {"decision_id": dec.get("decision_id"),
                        "seq": d["seq"], "reason": dec.get("reason", "")}
        return None

    # mesh_transition frames aggregate per transition id: one journaled
    # propose→fence→hydrate→cutover→release ladder narrates as ONE
    # incident (in-place hot-swap, master/mesh_transition.py), anchored
    # at its propose frame.  Downtime attributes to the two ledger
    # states the survivor credits (trainer/hotswap.py): restore_replica
    # for hydrate, rework for cutover.
    mesh: Dict[int, Dict] = {}
    for e in journal_events:
        if e["kind"] != "mesh_transition":
            continue
        d = e["data"]
        tid = int(d.get("tid", 0) or 0)
        if not tid:
            continue
        t = mesh.setdefault(tid, {"phases": [], "final": "propose",
                                  "acks": 0})
        ev = str(d.get("event", ""))
        if ev == "propose":
            survivors = d.get("survivors")
            t["dead_node_id"] = d.get("dead_node_id")
            t["fence_epoch"] = d.get("fence_epoch")
            t["survivors_n"] = (len(survivors)
                                if isinstance(survivors, list)
                                else int(d.get("survivors_n", 0) or 0))
        elif ev == "phase":
            ph = str(d.get("phase", ""))
            t["phases"].append(ph)
            t["final"] = ph
        elif ev == "abort":
            t["final"] = "aborted"
        elif ev == "ack":
            t["acks"] += 1

    # epochs a journaled ``failover`` frame announced: the matching
    # ``epoch`` frame is a fenced standby PROMOTION, not a restart of
    # the same process (warm-standby HA, master/standby.py)
    failover_epochs = {
        int(e["data"].get("new_epoch", 0) or 0)
        for e in journal_events if e["kind"] == "failover"}

    incidents: List[Dict] = []
    for e in journal_events:
        if e["kind"] == "epoch" and int(
                e["data"].get("epoch", 0) or 0) >= 2:
            opened = int(e["data"].get("epoch", 0) or 0)
            incidents.append({
                "kind": ("failover" if opened in failover_epochs
                         else "master_restart"),
                "epoch": e["epoch"], "seq": e["seq"],
                "t_wall": e["t_wall"],
                "attributed_state": "degraded",
                "lost_s": round(states.get("degraded", 0.0), 6),
                "trigger": {"kind": "epoch", "seq": e["seq"]},
                "policy_response": _answer(e["epoch"], e["seq"]),
            })
        elif e["kind"] == "recover":
            restore = sum(states.get(s, 0.0) for s in _RESTORE_STATES)
            incidents.append({
                "kind": "worker_failure",
                "epoch": e["epoch"], "seq": e["seq"],
                "t_wall": e["t_wall"],
                "attributed_state": "restore",
                "lost_s": round(restore, 6),
                "trigger": {"kind": "recover", "seq": e["seq"],
                            "node_id": e["data"].get("node_id")},
                "policy_response": _answer(e["epoch"], e["seq"]),
            })
        elif (e["kind"] == "mesh_transition"
              and str(e["data"].get("event", "")) == "propose"):
            tid = int(e["data"].get("tid", 0) or 0)
            t = mesh.get(tid, {})
            swap = sum(states.get(s, 0.0) for s in _HOTSWAP_STATES)
            incidents.append({
                "kind": "mesh_transition",
                "epoch": e["epoch"], "seq": e["seq"],
                "t_wall": e["t_wall"],
                "attributed_state": "hotswap",
                "lost_s": round(swap, 6),
                "trigger": {"kind": "mesh_transition", "seq": e["seq"],
                            "node_id": t.get("dead_node_id"),
                            "transition_id": tid},
                "phase": str(t.get("final", "propose")),
                "phases": list(t.get("phases", [])),
                "acks": int(t.get("acks", 0)),
                "fence_epoch": t.get("fence_epoch"),
                "survivors_n": int(t.get("survivors_n", 0) or 0),
                "policy_response": _answer(e["epoch"], e["seq"]),
            })
    total = max(wall, sum(states.values()))
    return {
        "schema": TIMELINE_SCHEMA_VERSION,
        "wall_s": round(wall, 6),
        "productive_s": round(productive, 6),
        "goodput_fraction": round(
            (productive / total) if total > 0 else 0.0, 6),
        "lost_seconds": lost,
        "incidents": incidents,
        "policy_decisions": len(decisions),
    }


def assemble_incident(journal_dir: str = "", ckpt_dir: str = "",
                      journal_dirs: Optional[List[str]] = None) -> Dict:
    """The whole incident: merged event stream + narrative + counts.

    Pure function of the disk artifacts — the live TimelineQuery verb
    (master/master.py timeline_report) runs THIS on the master's own
    journal dir, so `tools/incident_report.py --journal/--flight` on the
    same artifacts reconstructs byte-equal canonical JSON.

    ``journal_dirs`` lists FURTHER journal dirs to merge after
    ``journal_dir`` (warm-standby failover post-mortems span the old
    primary's dir and the promoted standby's); with at most one
    effective dir the output is byte-identical to the single-journal
    path.  Live and offline must pass the SAME ordered dir list for
    byte-equality.
    """
    dirs: List[str] = []
    for d in [journal_dir, *(journal_dirs or [])]:
        if d and d not in dirs:
            dirs.append(d)
    journal_events = read_journal_events_multi(dirs)
    flight_events, ledgers = read_flight_events(ckpt_dir)
    events = _merge(journal_events, flight_events)
    traces = sorted({e["trace_id"] for e in events if e["trace_id"]})
    epochs = sorted({e["epoch"] for e in journal_events if e["epoch"] > 0})
    return {
        "schema": TIMELINE_SCHEMA_VERSION,
        "events": events,
        "narrative": build_narrative(journal_events, ledgers),
        "counts": {
            "events": len(events),
            "journal_events": len(journal_events),
            "flight_events": len(flight_events),
            "spans": sum(1 for e in events if e["kind"] == "span"),
            "traces": len(traces),
            "epochs": epochs,
            "processes": sorted({(e["role"], e["pid"])
                                 for e in flight_events}),
        },
    }


def incident_json(report: Dict) -> str:
    """Canonical serialization — the byte-equality unit the drills and
    `timeline_sha256` hash over (sorted keys, fixed separators)."""
    return json.dumps(report, sort_keys=True, separators=(",", ":"))


def incident_sha256(content: str) -> str:
    return hashlib.sha256(content.encode("utf-8")).hexdigest()


def trace_tree(events: List[Dict], trace_id: str) -> List[Dict]:
    """Span forest for one trace across processes/generations: roots
    (parent absent from the trace) with nested ``children``, each node
    ordered by t_wall — one request admitted by generation 1 and
    finished by generation 2 reads as ONE tree."""
    spans = [e for e in events
             if e["kind"] == "span" and e["trace_id"] == trace_id]
    nodes = {e["span_id"]: {**e, "children": []} for e in spans}
    roots = []
    for e in sorted(spans, key=lambda s: (s["t_wall"], s["span_id"])):
        parent = e["data"].get("parent_span", "")
        node = nodes[e["span_id"]]
        if parent and parent in nodes:
            nodes[parent]["children"].append(node)
        else:
            roots.append(node)
    return roots


# ------------------------------------------------------ Perfetto export


def export_perfetto(report: Dict, path: str) -> int:
    """Whole-incident Chrome/Perfetto trace: span events become duration
    slices per (pid, role) process track, journal frames and flight
    flushes become instant marks on their process's track
    (spans.dump_chrome_trace grew multi-process metadata for this)."""
    from .spans import dump_chrome_trace

    events = report.get("events") or []
    spans = []
    instants = []
    names: Dict[int, str] = {}
    for e in events:
        if e["source"] == "journal":
            names.setdefault(0, "master(journal)")
            instants.append({
                "name": e["name"], "t_wall": e["t_wall"], "pid": 0,
                "args": {"epoch": e["epoch"], "seq": e["seq"],
                         "kind": e["kind"]}})
            continue
        names.setdefault(e["pid"], e["role"] or f"pid{e['pid']}")
        if e["kind"] == "span":
            spans.append({
                "name": e["name"], "t_wall": e["t_wall"],
                "dur_s": e["dur_s"], "pid": e["pid"], "role": e["role"],
                "trace_id": e["trace_id"], "span_id": e["span_id"],
                "parent_span": e["data"].get("parent_span", ""),
                "status": e["data"].get("status", "ok"),
                "attrs": e["data"].get("attrs", {})})
        else:
            instants.append({
                "name": e["name"], "t_wall": e["t_wall"], "pid": e["pid"],
                "args": {"kind": e["kind"]}})
    return dump_chrome_trace(path, extra_spans=spans,
                             instant_events=instants,
                             process_names=names, include_buffer=False)

"""Serving latency ledger: wall-time attribution + per-request latency.

Parity: the reference has no serving telemetry (serving is delegated to
vLLM, `atorch/atorch/rl/model_engine/model_engine.py:35`); the training
side's only signal is the speed monitor.  Here the serving plane gets
the same treatment the trainer got in telemetry/ledger.py: every second
of a decode worker's wall time lands in exactly one SERVE_STATES bucket,
and request lifecycle marks (admit → first token → finish) feed bounded
reservoirs from which p50/p99 total latency and time-to-first-token are
computed without storing unbounded history.

Accounting rules (mirroring GoodputLedger):

- Credits happen at WINDOW BOUNDARIES only — the engine credits one
  ``decode`` window per fused K-token scan and one ``prefill`` window per
  admission; never per token, never via a new device readback.
- Durations are ``time.monotonic`` intervals; ``started_wall`` is the
  only wall-clock field.
- Counters are the recovery-attribution surface: the serve-drain chaos
  drill asserts ``requeued`` > 0 on the ledger a re-admitted worker
  reports, proving the recovery was *accounted*, not silent.

Snapshot keys, ``SERVE_STATES`` and ``SERVE_COUNTERS`` are ADD-ONLY
schemas pinned by tests/test_serving.py — extend, never rename.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Deque, Dict, Optional

#: One entry per attributable worker state, in export order.  ADD-ONLY.
SERVE_STATES = (
    "prefill",       # admission prefill scans (cache hydration)
    "decode",        # fused decode windows producing tokens
    "admission",     # host-side scheduling/slot bookkeeping
    "weight_sync",   # pulling refreshed weights from a live trainer
    "idle",          # no active slots, waiting for work
    "degraded",      # blocked on master RPCs during an outage
)

#: Monotonic request-lifecycle counters.  ADD-ONLY.
SERVE_COUNTERS = (
    "submitted",     # requests handed to this worker (leased)
    "admitted",      # requests that reached a KV slot
    "finished",      # requests fully decoded + result reported
    "requeued",      # in-flight requests re-admitted after a fault
    "tokens_out",    # generated tokens (excludes prompt)
)

SERVE_SCHEMA_VERSION = 1

#: Bounded latency reservoirs: enough for stable tails at drill/bench
#: scale without unbounded growth under production traffic.
_MAX_SAMPLES = 4096


def _percentile(samples, q: float) -> float:
    """Nearest-rank percentile of a sequence (0 when empty)."""
    if not samples:
        return 0.0
    xs = sorted(samples)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


class ServeLedger:
    """Thread-safe serving-plane wall-time + latency accumulator."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._states: Dict[str, float] = {s: 0.0 for s in SERVE_STATES}
        self._counters: Dict[str, int] = {c: 0 for c in SERVE_COUNTERS}
        self._t_start: Optional[float] = None
        self._started_wall = 0.0
        # request_id -> (admit_t, first_token_t or None)
        self._inflight: Dict[str, list] = {}
        self._ttft_s: Deque[float] = collections.deque(maxlen=_MAX_SAMPLES)
        self._total_s: Deque[float] = collections.deque(maxlen=_MAX_SAMPLES)

    # ------------------------------------------------------------ lifecycle

    def start(self):
        """Open the wall-time window; idempotent (first call wins)."""
        with self._lock:
            if self._t_start is None:
                self._t_start = self._clock()
                self._started_wall = time.time()

    def started(self) -> bool:
        """True once `start()` opened the window (mirrors GoodputLedger —
        the flight recorder embeds a snapshot only from a started
        ledger, so an idle process dumps null, not an all-zero split)."""
        with self._lock:
            return self._t_start is not None

    # ------------------------------------------------------------ credits

    def account(self, state: str, seconds: float):
        if state not in self._states:
            raise ValueError(f"unknown serve state {state!r}; "
                             f"SERVE_STATES is add-only")
        if seconds <= 0:
            return
        self.start()
        with self._lock:
            self._states[state] += seconds

    @contextlib.contextmanager
    def window(self, state: str):
        """Credit the wall time of the with-block to `state`."""
        self.start()
        t0 = self._clock()
        try:
            yield
        finally:
            self.account(state, self._clock() - t0)

    def count(self, counter: str, n: int = 1):
        if counter not in self._counters:
            raise ValueError(f"unknown serve counter {counter!r}; "
                             f"SERVE_COUNTERS is add-only")
        self.start()
        with self._lock:
            self._counters[counter] += n

    # ------------------------------------------------------------ requests

    def note_admit(self, request_id: str):
        """Request reached a KV slot; latency clock starts here."""
        self.start()
        with self._lock:
            self._inflight[request_id] = [self._clock(), None]
            self._counters["admitted"] += 1

    def note_first_token(self, request_id: str):
        with self._lock:
            rec = self._inflight.get(request_id)
            if rec is not None and rec[1] is None:
                rec[1] = self._clock()
                self._ttft_s.append(rec[1] - rec[0])

    def note_finish(self, request_id: str, tokens: int = 0):
        now = self._clock()
        with self._lock:
            rec = self._inflight.pop(request_id, None)
            if rec is not None:
                self._total_s.append(now - rec[0])
            self._counters["finished"] += 1
            if tokens > 0:
                self._counters["tokens_out"] += tokens

    def note_requeued(self, n: int = 1):
        """A fault put `n` in-flight requests back on the queue."""
        self.count("requeued", n)

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> Dict:
        """Cumulative totals — safe to resend (receiver keeps latest)."""
        with self._lock:
            wall = (self._clock() - self._t_start
                    if self._t_start is not None else 0.0)
            states = dict(self._states)
            counters = dict(self._counters)
            ttft = list(self._ttft_s)
            total = list(self._total_s)
            active = len(self._inflight)
        credited = sum(states.values())
        return {
            "schema": SERVE_SCHEMA_VERSION,
            "wall_s": wall,
            "states": states,
            "other_s": max(0.0, wall - credited),
            "counters": counters,
            "active_requests": active,
            "latency": {
                "samples": len(total),
                "p50_ms": _percentile(total, 0.50) * 1e3,
                "p99_ms": _percentile(total, 0.99) * 1e3,
                "ttft_p50_ms": _percentile(ttft, 0.50) * 1e3,
                "ttft_p99_ms": _percentile(ttft, 0.99) * 1e3,
            },
            "started_wall": self._started_wall,
        }


_SERVE_LEDGER: Optional[ServeLedger] = None
_SERVE_LEDGER_LOCK = threading.Lock()


def get_serve_ledger() -> ServeLedger:
    """Process-global serving ledger (engine, worker, bench share it)."""
    global _SERVE_LEDGER
    with _SERVE_LEDGER_LOCK:
        if _SERVE_LEDGER is None:
            _SERVE_LEDGER = ServeLedger()
        return _SERVE_LEDGER


def reset_serve_ledger() -> ServeLedger:
    """Fresh ledger (tests / bench runs); returns the new instance."""
    global _SERVE_LEDGER
    with _SERVE_LEDGER_LOCK:
        _SERVE_LEDGER = ServeLedger()
        return _SERVE_LEDGER

"""Unified runtime telemetry: goodput ledger, trace spans, flight recorder.

Parity: reference `dlrover/python/master/monitor/speed_monitor.py` (the
master's only live training signal) + the xpu_timer always-on timing
intent (`atorch/dev/xpu_timer/common/manager.cc` — runtime metrics
exported continuously, not just inside benchmarks).

TPU redesign: the reference stack measures speed from reported steps and
leaves downtime attribution to offline log spelunking.  Here every second
of trainer wall time lands in exactly one ledger state (telemetry/
ledger.py), control-plane and checkpoint work is traced with
cross-process spans riding the typed JSON frames (telemetry/spans.py),
and each process keeps a bounded flight-recorder ring flushed to
``$ckpt_dir/flight/`` on faults (telemetry/recorder.py) — the measurement
substrate the Brain's adaptive policies read from instead of chaos-drill
ad-hoc timers.

The incident timeline (telemetry/timeline.py) merges all of the above
plus the master journal into ONE causally-ordered event stream — live
via the TimelineQuery verb, offline via tools/incident_report.py,
byte-equal either way.

The perf observatory (telemetry/perf.py) adds the device-side signal the
ledger cannot see: sampled in-train profiling windows keyed by
executable identity, a median+MAD baseline store under
``$ckpt_dir/perf/``, and a regression/retrace sentinel feeding node
events, the policy loop and tools/perf_report.py.

Schemas are ADD-ONLY: ``LEDGER_STATES``, the ledger snapshot keys, the
flight-dump envelope keys (tests/test_telemetry.py), the timeline
event envelope (tests/test_timeline.py) and the PerfSnapshot /
perf-event keys (tests/test_perf.py) — extend, never rename.
"""

from .ledger import (  # noqa: F401
    LEDGER_SCHEMA_VERSION,
    LEDGER_STATES,
    GoodputLedger,
    get_ledger,
    reset_ledger,
)
from .serving import (  # noqa: F401
    SERVE_COUNTERS,
    SERVE_SCHEMA_VERSION,
    SERVE_STATES,
    ServeLedger,
    get_serve_ledger,
    reset_serve_ledger,
)
from .perf import (  # noqa: F401
    PERF_EVENT_KEYS,
    PERF_SCHEMA,
    PERF_SNAPSHOT_KEYS,
    BaselineStore,
    PerfObservatory,
    RegressionSentinel,
    executable_key,
    get_observatory,
    latest_snapshot,
    reset_observatory,
    set_observatory,
)
from .recorder import (  # noqa: F401
    FLIGHT_SCHEMA_VERSION,
    FlightRecorder,
    flight_dir,
    get_recorder,
    load_flight_dumps,
    reset_recorder,
)
from .timeline import (  # noqa: F401
    TIMELINE_EVENT_KEYS,
    TIMELINE_SCHEMA_VERSION,
    assemble_incident,
    build_narrative,
    export_perfetto,
    incident_json,
    incident_sha256,
    trace_tree,
)
from .spans import (  # noqa: F401
    SPAN_SCHEMA_VERSION,
    clear_spans,
    current_trace,
    dump_chrome_trace,
    env_context,
    extract,
    inject,
    set_process_role,
    span,
    span_event,
    spans_snapshot,
)

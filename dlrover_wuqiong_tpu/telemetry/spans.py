"""Cross-process trace spans riding the typed JSON control-plane frames.

Parity: reference `dlrover/python/common/grpc.py` (the envelope every
agent-master exchange rides) + the xpu_timer timeline-dump intent
(`atorch/dev/xpu_timer/common/manager.cc` — host-side timing exported for
offline viewing).  The reference has no distributed tracing: a restore or
re-mesh is reconstructed by grepping three processes' logs.

TPU redesign: the frame envelope (common/comm.py) carries
``trace_id``/``span_id``/``parent_span``; `retry_call`, RpcClient verb
calls, servicer handling, checkpoint save/restore tiers, rendezvous
rounds and warm-pool hydration open spans into a process-local bounded
buffer.  One restore then reconstructs end-to-end across
agent → master → saver processes from the flight dumps (recorder.py) or
a Chrome trace-event JSON (`dump_chrome_trace`, chrome://tracing /
Perfetto format).

Clocks: span *durations* are ``time.monotonic`` intervals; span *start
timestamps* are ``time.time`` so spans from different processes align on
one timeline (the one sanctioned cross-process use of wall clock).

Child processes spawned mid-span inherit the active context through
``DWT_TRACE_ID`` / ``DWT_TRACE_PARENT`` (see `env_context`); the spawned
side picks them up lazily on its first span.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional

from .recorder import get_recorder

SPAN_SCHEMA_VERSION = 1

#: bounded process-local span buffer (drop-oldest)
_MAX_SPANS = 2048

_BUFFER: "deque[Dict]" = deque(maxlen=_MAX_SPANS)
_BUFFER_LOCK = threading.Lock()

_TLS = threading.local()

_ROLE = os.getenv("DWT_PROC_ROLE", "")


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


def set_process_role(role: str):
    """Name this process in span/flight dumps (agent/master/saver/...)."""
    global _ROLE
    _ROLE = role


def process_role() -> str:
    return _ROLE or "proc"


def _stack() -> List[Dict]:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = []
        # a spawned child joins the parent's trace lazily: the env
        # context seeds the root of this thread's stack once
        tid = os.getenv("DWT_TRACE_ID", "")
        if tid:
            stack.append({"trace_id": tid,
                          "span_id": os.getenv("DWT_TRACE_PARENT", "")})
        _TLS.stack = stack
    return stack


def current_trace() -> Optional[Dict[str, str]]:
    """Active {"trace_id", "span_id"} or None outside any span."""
    stack = _stack()
    if not stack:
        return None
    top = stack[-1]
    return {"trace_id": top["trace_id"], "span_id": top.get("span_id", "")}


def inject() -> Optional[Dict[str, str]]:
    """Trace fields for an outgoing frame envelope (None = untraced)."""
    return current_trace()


@contextlib.contextmanager
def extract(trace: Optional[Dict]):
    """Adopt an incoming frame's trace context for the handling scope."""
    if not trace or not trace.get("trace_id"):
        yield
        return
    stack = _stack()
    stack.append({"trace_id": str(trace["trace_id"]),
                  "span_id": str(trace.get("span_id", ""))})
    try:
        yield
    finally:
        stack.pop()


@contextlib.contextmanager
def env_context():
    """Env vars propagating the active context to a spawned child."""
    ctx = current_trace()
    env = {}
    if ctx:
        env["DWT_TRACE_ID"] = ctx["trace_id"]
        env["DWT_TRACE_PARENT"] = ctx["span_id"]
    yield env


def _record(rec: Dict):
    with _BUFFER_LOCK:
        _BUFFER.append(rec)
    # spans are flight-recorder events too: a fault dump carries the
    # recent trace tree without a separate flush path
    get_recorder().record("span", rec["name"], rec)


@contextlib.contextmanager
def span(name: str, attrs: Optional[Dict] = None):
    """Open a span; nests under the active one, propagates via frames."""
    stack = _stack()
    parent = stack[-1] if stack else None
    rec = {
        "schema": SPAN_SCHEMA_VERSION,
        "name": name,
        "trace_id": parent["trace_id"] if parent else _new_id(),
        "span_id": _new_id(),
        "parent_span": parent.get("span_id", "") if parent else "",
        "role": process_role(),
        "pid": os.getpid(),
        "t_wall": time.time(),
        "dur_s": 0.0,
        "attrs": dict(attrs or {}),
        "status": "ok",
    }
    stack.append({"trace_id": rec["trace_id"], "span_id": rec["span_id"]})
    t0 = time.monotonic()
    try:
        yield rec
    except BaseException:
        rec["status"] = "error"
        raise
    finally:
        rec["dur_s"] = time.monotonic() - t0
        stack.pop()
        _record(rec)


def span_event(name: str, attrs: Optional[Dict] = None):
    """Zero-duration span for point-in-time marks (world formed, ...)."""
    with span(name, attrs):
        pass


def spans_snapshot() -> List[Dict]:
    """Copy of the bounded buffer, oldest first."""
    with _BUFFER_LOCK:
        return list(_BUFFER)


def clear_spans():
    with _BUFFER_LOCK:
        _BUFFER.clear()


def dump_chrome_trace(path: str, extra_spans: Optional[List[Dict]] = None,
                      instant_events: Optional[List[Dict]] = None,
                      process_names: Optional[Dict[int, str]] = None,
                      include_buffer: bool = True):
    """Write the buffer (plus `extra_spans`, e.g. merged flight dumps) as
    Chrome trace-event JSON — load in chrome://tracing or Perfetto.

    Multi-process (add-only, telemetry/timeline.py export_perfetto):
    `process_names` emits one process_name metadata row per pid so each
    process gets a labelled track; `instant_events`
    (``{"name", "t_wall", "pid", "args"}``) become instant marks (journal
    frames, flight flushes); `include_buffer=False` exports ONLY the
    supplied events — a whole-incident export must not mix in whatever
    the exporting process's own span buffer happens to hold."""
    import json

    events = []
    for pid, pname in sorted((process_names or {}).items()):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": pid, "args": {"name": str(pname)}})
    for inst in instant_events or []:
        events.append({
            "name": inst.get("name", ""),
            "cat": "instant",
            "ph": "i", "s": "p",
            "ts": float(inst.get("t_wall", 0.0)) * 1e6,
            "pid": inst.get("pid", 0),
            "tid": inst.get("pid", 0),
            "args": dict(inst.get("args") or {}),
        })
    buffered = spans_snapshot() if include_buffer else []
    for rec in (extra_spans or []) + buffered:
        events.append({
            "name": rec["name"],
            "cat": rec.get("role", "proc"),
            "ph": "X",
            "ts": rec["t_wall"] * 1e6,
            "dur": max(rec.get("dur_s", 0.0), 0.0) * 1e6,
            "pid": rec.get("pid", 0),
            "tid": rec.get("pid", 0),
            "args": {
                "trace_id": rec.get("trace_id", ""),
                "span_id": rec.get("span_id", ""),
                "parent_span": rec.get("parent_span", ""),
                "status": rec.get("status", "ok"),
                **rec.get("attrs", {}),
            },
        })
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": events}, f)
    os.replace(tmp, path)
    return len(events)

"""Goodput ledger: every second of trainer wall time in exactly one state.

Parity: reference `dlrover/python/master/monitor/speed_monitor.py:24`
(SpeedMonitor derives a single global speed number from reported steps)
— the ledger is its attribution-complete counterpart: instead of one
rate, the trainer accounts *where* wall time went (productive fused
window, dispatch overhead, data stall, checkpoint stage/persist,
per-tier restore, compile, rework after rollback, master-outage
degraded), so downtime splits that previously only existed as chaos
drill artifacts (chaos.py timing_r*.json) are live runtime telemetry.

Accounting rules (enforced by call sites, asserted by tests):

- Credits happen at FUSION BOUNDARIES only (trainer/trainer.py) — never
  inside the jitted step, and never via a new device readback; the
  dispatch-overhead share of a fused window is estimated from the
  measured per-dispatch overhead (auto engine / DWT_DISPATCH_OVERHEAD_S),
  not from extra syncs.
- Durations are ``time.monotonic`` intervals; the snapshot's
  ``started_wall`` is the only wall-clock field (a human-facing
  timestamp).
- ``other`` is the residual: wall − sum(credited states).  It is
  computed, never credited, which is what makes the attribution
  total: states + other == wall by construction.

The snapshot dict is an ADD-ONLY schema pinned by tests/test_telemetry.py.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional

#: One entry per attributable state, in export order.  ADD-ONLY: the
#: master aggregation, /metrics export, goodput_report CLI and chaos
#: drill assertions all key on these names.
LEDGER_STATES = (
    "productive",        # fused-window device time doing real steps
    "dispatch_overhead",  # per-dispatch tunnel/runtime overhead share
    "data_stall",        # blocked on next(stager) / host input pipeline
    "ckpt_stage",        # blocked on D2H staging into shm
    "ckpt_persist",      # blocked waiting on a prior async persist
    "restore_shm",       # restore served from the local shm tier
    "restore_replica",   # restore served from a peer replica fetch
    "restore_storage",   # restore served from durable storage
    "compile",           # first dispatch of a fused program (trace+XLA)
    "rework",            # re-executing steps already done pre-rollback
    "degraded",          # blocked on master RPCs during an outage
    "profile",           # perf-observatory window overhead (trace
                         # start/stop + xplane parse — telemetry/perf.py)
)

LEDGER_SCHEMA_VERSION = 1


class GoodputLedger:
    """Thread-safe accumulator of wall seconds per ledger state."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._states: Dict[str, float] = {s: 0.0 for s in LEDGER_STATES}
        self._t_start: Optional[float] = None
        self._started_wall = 0.0

    # ------------------------------------------------------------ lifecycle

    def start(self):
        """Open the wall-time window; idempotent (first call wins)."""
        with self._lock:
            if self._t_start is None:
                self._t_start = self._clock()
                self._started_wall = time.time()

    def started(self) -> bool:
        with self._lock:
            return self._t_start is not None

    # ------------------------------------------------------------ credits

    def account(self, state: str, seconds: float):
        """Credit `seconds` to `state` (unknown states raise — the state
        list is the schema)."""
        if state not in self._states:
            raise ValueError(f"unknown ledger state {state!r}; "
                             f"LEDGER_STATES is add-only")
        if seconds <= 0:
            return
        self.start()
        with self._lock:
            self._states[state] += seconds

    @contextlib.contextmanager
    def window(self, state: str):
        """Credit the wall time of the with-block to `state`."""
        self.start()
        t0 = self._clock()
        try:
            yield
        finally:
            self.account(state, self._clock() - t0)

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> Dict:
        """Cumulative totals — safe to resend (receiver keeps latest)."""
        with self._lock:
            wall = (self._clock() - self._t_start
                    if self._t_start is not None else 0.0)
            states = dict(self._states)
        credited = sum(states.values())
        # clamp: concurrent windows (saver thread vs train loop) can
        # credit more than wall; residual is never negative
        other = max(0.0, wall - credited)
        productive = states.get("productive", 0.0)
        total = max(wall, credited)
        return {
            "schema": LEDGER_SCHEMA_VERSION,
            "wall_s": wall,
            "states": states,
            "other_s": other,
            "goodput_fraction": (productive / total) if total > 0 else 0.0,
            "started_wall": self._started_wall,
        }

    def goodput_fraction(self) -> float:
        return self.snapshot()["goodput_fraction"]


_LEDGER: Optional[GoodputLedger] = None
_LEDGER_LOCK = threading.Lock()


def get_ledger() -> GoodputLedger:
    """Process-global ledger (trainer, checkpoint engine, master client
    and bench all credit the same instance)."""
    global _LEDGER
    with _LEDGER_LOCK:
        if _LEDGER is None:
            _LEDGER = GoodputLedger()
        return _LEDGER


def reset_ledger() -> GoodputLedger:
    """Fresh ledger (tests / bench runs); returns the new instance."""
    global _LEDGER
    with _LEDGER_LOCK:
        _LEDGER = GoodputLedger()
        return _LEDGER

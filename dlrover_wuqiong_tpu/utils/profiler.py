"""Training-side profiling orchestration.

Parity: reference `atorch/atorch/utils/prof.py` (torch.profiler window
orchestration + timeline dump) and the xpu_timer runtime-timing intent
(`atorch/dev/xpu_timer/common/manager.cc` — always-on step timings exported
to Prometheus).

TPU redesign: heavyweight tracing is `jax.profiler` (XPlane/TensorBoard
format) started for a bounded step window; lightweight always-on timing is
a host-side per-step stopwatch feeding the shared MetricRegistry (the
device timeline inside a jit step is XLA's domain — per-op host hooks like
LD_PRELOAD shims don't exist on TPU, the trace viewer covers that instead).
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

from ..common.log import get_logger

logger = get_logger("profiler")


class StepProfiler:
    """Windowed jax.profiler trace + always-on step timing.

    Usage:
        prof = StepProfiler(trace_dir="/tmp/trace", start_step=10,
                            end_step=12)
        for step in ...:
            with prof.step(step):
                state, m = train_step(state, batch)
    """

    def __init__(self, trace_dir: Optional[str] = None,
                 start_step: int = -1, end_step: int = -1,
                 registry=None, job_name: str = "dwt"):
        self.trace_dir = trace_dir
        self.start_step = start_step
        self.end_step = end_step
        self._tracing = False
        self._job = job_name
        self.last_profile = None  # OpProfile of the latest closed window
        if registry is None:
            from ..master.metrics import get_registry

            registry = get_registry()
        self._reg = registry

    @contextlib.contextmanager
    def step(self, step: int):
        self._maybe_start_trace(step)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._reg.observe("dwt_train_step_seconds", dt,
                              {"job": self._job},
                              help="host-observed train step wall time")
            self._reg.gauge("dwt_train_last_step", step, {"job": self._job})
            self._maybe_stop_trace(step)

    def _maybe_start_trace(self, step: int):
        if (self.trace_dir and not self._tracing
                and step == self.start_step):
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True
            logger.info("jax.profiler trace started at step %d → %s",
                        step, self.trace_dir)

    def _maybe_stop_trace(self, step: int):
        if self._tracing and step >= self.end_step:
            import jax

            jax.profiler.stop_trace()
            self._tracing = False
            logger.info("jax.profiler trace stopped at step %d", step)
            self._publish_op_profile()

    def _publish_op_profile(self):
        """xpu_timer parity: per-op-category latencies from the XPlane →
        MetricRegistry (→ Prometheus) + diagnosis evidence."""
        from .xplane import parse_trace_dir

        try:
            prof = parse_trace_dir(self.trace_dir)
        except Exception:  # noqa: BLE001 — observability must not kill train
            logger.warning("xplane parse failed", exc_info=True)
            return
        if prof is None:
            return
        self.last_profile = prof
        # fresh window: drop last window's series (op names churn between
        # windows; stale top-10 entries must not export forever)
        self._reg.drop_gauge("dwt_op_seconds")
        self._reg.drop_gauge("dwt_op_category_seconds")
        for cat, sec in sorted(prof.categories.items()):
            self._reg.gauge("dwt_op_category_seconds", sec,
                            {"job": self._job, "category": cat},
                            help="device time per op category in the last "
                                 "trace window (xplane)")
        for op in prof.top(k=10):
            self._reg.gauge("dwt_op_seconds", op.total_s,
                            {"job": self._job, "op": op.name,
                             "category": op.category},
                            help="device time of the hottest ops in the "
                                 "last trace window (xplane)")
        logger.info(
            "op profile: %s",
            " ".join(f"{c}={s * 1e3:.2f}ms"
                     for c, s in sorted(prof.categories.items())))

    def close(self):
        if self._tracing:
            import jax

            jax.profiler.stop_trace()
            self._tracing = False
            self._publish_op_profile()


@contextlib.contextmanager
def annotate(name: str):
    """Named region in the device trace (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield

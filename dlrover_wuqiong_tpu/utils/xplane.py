"""XPlane trace parsing → per-op-category runtime latencies.

Parity: reference xpu_timer (`atorch/dev/xpu_timer/common/manager.cc` +
`nvidia/hook.cc`) — an LD_PRELOAD shim that times every GEMM/NCCL launch and
exports per-op latency gauges to Prometheus.

TPU redesign: device kernels are not host-visible calls, so instead of
hooking launches we parse the XPlane protobuf that `jax.profiler` drops for
a traced step window and aggregate device-op durations by category (matmul,
collective, transfer, data-movement (on-device dynamic-slice/gather/...),
fused, sync, other).  The profile feeds the shared
MetricRegistry (→ PrometheusExporter) and the diagnosis evidence chain
(top-k slowest collectives), giving the same observability surface without
a preload shim.

The protobuf wire reader below is self-contained (stdlib only): XSpace is a
stable, public schema (tensorflow/tsl/profiler/protobuf/xplane.proto) and
we only need a thin slice of it — planes → lines → events + the two
metadata maps.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
from typing import Dict, Iterator, List, Optional, Tuple

from ..common.log import get_logger

logger = get_logger("xplane")


# ------------------------------------------------------- protobuf wire layer


def _varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over a message buffer."""
    pos, end = 0, len(buf)
    while pos < end:
        tag, pos = _varint(buf, pos)
        fnum, wt = tag >> 3, tag & 7
        if wt == 0:        # varint
            val, pos = _varint(buf, pos)
        elif wt == 2:      # length-delimited
            ln, pos = _varint(buf, pos)
            if pos + ln > end:  # slicing would silently return short
                raise ValueError("truncated length-delimited field")
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:      # fixed32
            if pos + 4 > end:
                raise ValueError("truncated fixed32")
            val = buf[pos:pos + 4]
            pos += 4
        elif wt == 1:      # fixed64
            if pos + 8 > end:
                raise ValueError("truncated fixed64")
            val = buf[pos:pos + 8]
            pos += 8
        else:              # groups — not used by xplane.proto
            raise ValueError(f"unsupported wire type {wt}")
        yield fnum, wt, val


@dataclasses.dataclass
class _Event:
    metadata_id: int = 0
    duration_ps: int = 0
    num_occurrences: int = 1
    stats: List[Tuple[int, object]] = dataclasses.field(default_factory=list)


def _parse_stat(buf: bytes) -> Tuple[int, object]:
    mid, val = 0, None
    for fnum, wt, v in _fields(buf):
        if fnum == 1:
            mid = v
        elif fnum == 5:            # str_value
            val = v.decode("utf-8", "replace")
        elif fnum in (3, 4, 7):    # uint64/int64/ref
            val = v
    return mid, val


def _parse_event(buf: bytes) -> _Event:
    ev = _Event()
    for fnum, wt, v in _fields(buf):
        if fnum == 1:
            ev.metadata_id = v
        elif fnum == 3:
            ev.duration_ps = v
        elif fnum == 5:
            ev.num_occurrences = v
        elif fnum == 4:
            ev.stats.append(_parse_stat(v))
    return ev


def _parse_map_entry(buf: bytes) -> Tuple[int, bytes]:
    key, val = 0, b""
    for fnum, wt, v in _fields(buf):
        if fnum == 1:
            key = v
        elif fnum == 2:
            val = v
    return key, val


def _metadata_name(buf: bytes) -> str:
    for fnum, wt, v in _fields(buf):
        if fnum == 2:
            return v.decode("utf-8", "replace")
    return ""


@dataclasses.dataclass
class _Line:
    name: str = ""
    events: List[_Event] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _Plane:
    name: str = ""
    lines: List[_Line] = dataclasses.field(default_factory=list)
    event_names: Dict[int, str] = dataclasses.field(default_factory=dict)
    stat_names: Dict[int, str] = dataclasses.field(default_factory=dict)


def _parse_line(buf: bytes) -> _Line:
    line = _Line()
    for fnum, wt, v in _fields(buf):
        if fnum == 2:
            line.name = v.decode("utf-8", "replace")
        elif fnum == 4:
            line.events.append(_parse_event(v))
    return line


def _parse_plane(buf: bytes) -> _Plane:
    plane = _Plane()
    for fnum, wt, v in _fields(buf):
        if fnum == 2:
            plane.name = v.decode("utf-8", "replace")
        elif fnum == 3:
            plane.lines.append(_parse_line(v))
        elif fnum == 4:
            k, mv = _parse_map_entry(v)
            plane.event_names[k] = _metadata_name(mv)
        elif fnum == 5:
            k, mv = _parse_map_entry(v)
            plane.stat_names[k] = _metadata_name(mv)
    return plane


def parse_xspace(path: str) -> List[_Plane]:
    with open(path, "rb") as f:
        buf = f.read()
    return [_parse_plane(v) for fnum, wt, v in _fields(buf) if fnum == 1]


# ------------------------------------------------------------- categorizer


# HLO-name prefixes → category (checked on the lowercased, wrapped_/suffix-
# stripped event name).  hlo_category stats, when present (TPU), win.
_PREFIX_CATEGORIES = (
    ("collective", ("all-reduce", "all-gather", "all-to-all",
                    "reduce-scatter", "collective-permute",
                    "collective-broadcast", "ragged-all-to-all")),
    ("matmul", ("dot", "convolution", "ragged-dot", "cublas", "gemm")),
    # dynamic-(update-)slice is ON-DEVICE data movement, heavily emitted by
    # the scan-based pipeline schedules — bucketing it under "transfer"
    # would inflate the host<->device gauge for every pipelined job
    ("transfer", ("copy", "infeed", "outfeed", "send", "recv")),
    ("data-movement", ("dynamic-update-slice", "dynamic-slice", "gather",
                       "scatter", "reshape", "transpose")),
    ("sync", ("rendezvous", "wait")),
    ("fused", ("fusion", "loop_", "input_", "output_")),
)

_HLO_CATEGORY_MAP = (
    ("collective", ("all-reduce", "all-gather", "all-to-all",
                    "reduce-scatter", "collective", "permute")),
    ("matmul", ("convolution", "dot", "gemm", "matmul")),
    ("transfer", ("copy", "infeed", "outfeed", "data formatting",
                  "host send", "host recv")),
)


def _normalize(name: str) -> str:
    n = name.lower()
    if n.startswith("wrapped_"):
        n = n[len("wrapped_"):]
    n = n.split(".")[0].split("%")[-1].strip()
    return n


def categorize(name: str, hlo_category: str = "") -> Optional[str]:
    """Category of a device op, or None for host noise."""
    if hlo_category:
        hc = hlo_category.lower()
        for cat, keys in _HLO_CATEGORY_MAP:
            if any(k in hc for k in keys):
                return cat
        return "fused" if "fusion" in hc else "other"
    if not name or name.startswith("$") or "(" in name or ":" in name:
        return None  # host-side python / runtime artifacts
    n = _normalize(name)
    for cat, prefixes in _PREFIX_CATEGORIES:
        if any(n.startswith(p) for p in prefixes):
            return cat
    # bare HLO instruction names are [a-z0-9-_]; anything else is host noise
    if not n or not all(c.isalnum() or c in "-_" for c in n):
        return None
    return "other"


# --------------------------------------------------------------- aggregation


@dataclasses.dataclass
class OpEntry:
    name: str
    category: str
    total_s: float
    count: int


@dataclasses.dataclass
class OpProfile:
    """Per-category and per-op device time for one trace window."""

    categories: Dict[str, float] = dataclasses.field(default_factory=dict)
    ops: List[OpEntry] = dataclasses.field(default_factory=list)

    def top(self, category: Optional[str] = None, k: int = 10
            ) -> List[OpEntry]:
        sel = [o for o in self.ops if category in (None, o.category)]
        return sel[:k]

    def collective_evidence(self, k: int = 5) -> str:
        """JSON evidence string for diagnosis: the k slowest collectives."""
        tops = self.top("collective", k)
        if not tops:
            return ""
        return json.dumps([
            {"op": o.name, "seconds": round(o.total_s, 6), "count": o.count}
            for o in tops])


def summarize_planes(planes: List[_Plane]) -> OpProfile:
    device_planes = [p for p in planes if "/device:" in p.name]
    use = device_planes or planes
    agg: Dict[Tuple[str, str], List[float]] = {}
    for plane in use:
        hlo_stat_ids = {i for i, n in plane.stat_names.items()
                        if n == "hlo_category"}
        for line in plane.lines:
            if line.name == "python":
                continue
            for ev in line.events:
                name = plane.event_names.get(ev.metadata_id, "")
                hlo_cat = next(
                    (str(v) for mid, v in ev.stats
                     if mid in hlo_stat_ids and isinstance(v, str)), "")
                cat = categorize(name, hlo_cat)
                if cat is None:
                    continue
                key = (_normalize(name), cat)
                tot = agg.setdefault(key, [0.0, 0])
                tot[0] += ev.duration_ps * 1e-12
                tot[1] += max(1, ev.num_occurrences)
    prof = OpProfile()
    for (name, cat), (sec, cnt) in agg.items():
        prof.categories[cat] = prof.categories.get(cat, 0.0) + sec
        prof.ops.append(OpEntry(name, cat, sec, cnt))
    prof.ops.sort(key=lambda o: -o.total_s)
    return prof


def parse_trace_dir(trace_dir: str) -> Optional[OpProfile]:
    """Parse the newest profiler run under `trace_dir` (all hosts merged)."""
    runs = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*")))
    if not runs:
        return None
    planes: List[_Plane] = []
    for pb in sorted(glob.glob(os.path.join(runs[-1], "*.xplane.pb"))):
        try:
            planes.extend(parse_xspace(pb))
        except Exception:  # noqa: BLE001 — torn/foreign file: skip, not fail
            logger.warning("unparseable xplane file %s", pb, exc_info=True)
    if not planes:
        return None
    return summarize_planes(planes)

"""Sharding-spec library: parameter/activation PartitionSpecs for transformers.

Parity: reference atorch TP modules — `RowParallelLinear`
(`modules/distributed_modules/layers.py:239`), `ColumnParallelLinear` (:392),
`VocabParallelEmbedding` (:549), the collective autograd functions
(`mappings.py:302-430`) and the operator-replacement registry
(`modules_registry.py`).

TPU redesign: Megatron-style row/column parallelism is *not* hand-written
collectives — it is a PartitionSpec per parameter plus GSPMD propagation.
A column-parallel linear is kernel P(None, "tp"); row-parallel is
P("tp", None) (XLA inserts the reduce-scatter/all-reduce the mappings.py
autograd functions implement by hand).  FSDP (ZeRO-3) adds sharding of every
param along "fsdp".  This module maps parameter *path patterns* → specs, the
single source of truth used by trainers and the checkpoint engine.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.log import get_logger

logger = get_logger("sharding")


Rule = Tuple[str, P]  # (path regex, spec)


# Default rules for transformer LMs (flax param-tree paths).  Order matters:
# first match wins.  Conventions: embedding tables (vocab, embed);
# attention/MLP kernels (in_features, out_features).
TRANSFORMER_RULES: List[Rule] = [
    # embeddings: vocab-parallel over tp (parity VocabParallelEmbedding :549)
    (r".*(wte|embed_tokens|token_embed|embedding)/embedding$",
     P("tp", "fsdp")),
    (r".*(wpe|pos_embed)/embedding$", P(None, "fsdp")),
    # attention qkv: column-parallel (heads split over tp)
    (r".*(attn|attention).*(q_proj|k_proj|v_proj|qkv|c_attn|query|key|value)"
     r"/kernel$", P("fsdp", "tp")),
    # attention out: row-parallel (parity RowParallelLinear :239)
    (r".*(attn|attention).*(o_proj|out_proj|c_proj|dense|out)/kernel$",
     P("tp", "fsdp")),
    # MLP up/gate: column-parallel
    (r".*(mlp|ffn|feed_forward).*(up_proj|gate_proj|c_fc|fc1|w1|w3)/kernel$",
     P("fsdp", "tp")),
    # MLP down: row-parallel
    (r".*(mlp|ffn|feed_forward).*(down_proj|c_proj|fc2|w2)/kernel$",
     P("tp", "fsdp")),
    # lm head: vocab-parallel
    (r".*(lm_head|output_proj)/kernel$", P("fsdp", "tp")),
    # biases follow their kernel's output dim
    (r".*(q_proj|k_proj|v_proj|qkv|c_attn|up_proj|gate_proj|c_fc|fc1|w1|w3)"
     r"/bias$", P("tp")),
    # norms, scalars: replicated (but fsdp-shard 1D when large? keep simple)
    (r".*(ln|norm|layernorm|rmsnorm).*", P()),
    (r".*/bias$", P()),
    (r".*scale$", P()),
]

MOE_RULES: List[Rule] = [
    # expert weights: (num_experts, in, out) — experts over ep
    (r".*experts.*(w_in|w_gate|w1|w3|up|gate).*", P("ep", "fsdp", "tp")),
    (r".*experts.*(w_down|w2|down).*", P("ep", "tp", "fsdp")),
    (r".*(router|gate)/kernel$", P("fsdp", None)),
]


def path_of(key_path) -> str:
    import jax

    parts = []
    for p in key_path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def spec_for_path(path: str, rules: Sequence[Rule],
                  ndim: Optional[int] = None) -> P:
    for pattern, spec in rules:
        if re.match(pattern, path, re.IGNORECASE):
            if ndim is not None:
                spec = _fit_spec(spec, ndim)
            return spec
    return P()  # default: replicated (fsdp handled by fsdp_wrap below)


def _fit_spec(spec: P, ndim: int) -> P:
    """Trim/pad a spec to the array's rank."""
    parts = list(spec)
    if len(parts) > ndim:
        parts = [p for p in parts if p is not None][:ndim]
        parts += [None] * (ndim - len(parts))
    elif len(parts) < ndim:
        parts += [None] * (ndim - len(parts))
    return P(*parts)


def _add_fsdp(spec: P, shape: Tuple[int, ...], mesh: Mesh,
              min_size: int = 2 ** 16) -> P:
    """ZeRO-3: also shard large replicated-dim params along "fsdp".

    Picks the largest dim not already sharded and divisible by the fsdp size.
    Parity: reference FSDPOptimization (zero_optimization.py:240) auto-wrap —
    in GSPMD it's just one more mesh axis in the spec.
    """
    fsdp_size = mesh.shape.get("fsdp", 1)
    if fsdp_size <= 1:
        return spec
    if "fsdp" in [a for part in spec if part for a in
                  (part if isinstance(part, tuple) else (part,))]:
        return spec
    import math

    if math.prod(shape) < min_size:
        return spec
    parts = list(spec) + [None] * (len(shape) - len(spec))
    # choose largest unsharded, divisible dim
    best, best_size = -1, 0
    for i, (dim, cur) in enumerate(zip(shape, parts)):
        if cur is None and dim % fsdp_size == 0 and dim > best_size:
            best, best_size = i, dim
    if best < 0:
        return spec
    parts[best] = "fsdp"
    return P(*parts)


@dataclass
class ShardingPlanner:
    """Maps a param pytree to NamedShardings over a mesh."""

    mesh: Mesh
    rules: List[Rule] = field(default_factory=lambda:
                              list(TRANSFORMER_RULES))
    fsdp_min_size: int = 2 ** 16

    def with_moe(self) -> "ShardingPlanner":
        self.rules = list(MOE_RULES) + self.rules
        return self

    def param_specs(self, params: Any) -> Any:
        """Pytree of PartitionSpec matching `params` structure."""
        import jax

        def _spec(key_path, leaf):
            path = path_of(key_path)
            spec = spec_for_path(path, self.rules,
                                 ndim=getattr(leaf, "ndim", None))
            shape = getattr(leaf, "shape", ())
            spec = _add_fsdp(spec, tuple(shape), self.mesh,
                             self.fsdp_min_size)
            return spec

        return jax.tree_util.tree_map_with_path(_spec, params)

    def param_shardings(self, params: Any) -> Any:
        import jax

        return jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                            self.param_specs(params),
                            is_leaf=lambda x: isinstance(x, P))

    def shard_params(self, params: Any) -> Any:
        """Place a host/replicated param pytree onto the mesh."""
        import jax

        return jax.device_put(params, self.param_shardings(params))

    # ------------------------------------------------------------ activations

    def batch_spec(self, ndim: int = 2, seq_axis: Optional[int] = None,
                   batch_axis: int = 0) -> P:
        """Batch activations: batch dim over (dp, fsdp), optional seq over sp.

        `batch_axis` > 0 supports a leading grad-accum microbatch axis
        (replicated — each accumulation step runs on the whole mesh).
        """
        parts: List[Any] = [None] * ndim
        parts[batch_axis] = ("dp", "fsdp")
        sp = self.mesh.shape.get("sp", 1)
        if seq_axis is not None and sp > 1:
            parts[seq_axis] = "sp"
        return P(*parts)

    def batch_sharding(self, ndim: int = 2, seq_axis: Optional[int] = None,
                       batch_axis: int = 0) -> NamedSharding:
        return NamedSharding(self.mesh,
                             self.batch_spec(ndim, seq_axis, batch_axis))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


def constrain(x, mesh: Mesh, spec: P):
    """In-jit sharding hint (the GSPMD equivalent of mappings.py collectives)."""
    import jax

    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

"""Long-context sequence/context parallelism: ring attention + Ulysses SP.

Parity: the reference's two long-context mechanisms (SURVEY.md §5) —
(1) blockwise distributed attention with global softmax over the SP group
(atorch `modules/distributed_transformer/distributed_attention.py:21-312`,
`DistributedSoftmax`, `DistributedSelfAttention`), and (2) Ulysses-style
sequence parallelism via all-to-all head scatter (atorch
`distributed/distributed.py:435-502`, `_SeqAllToAll`).

TPU redesign:
- **Ring attention** (`ring_attention`): sequence sharded over the mesh's
  `sp` axis; KV shards rotate around the ring with `jax.lax.ppermute` (rides
  ICI neighbor links) while each device accumulates blockwise attention of
  its local Q against the visiting KV chunk with the Pallas flash kernel.
  Partial results merge with the standard logsumexp combine, so memory is
  O(seq/sp) per device and the full score matrix never exists.  This is the
  true ring version of the reference's blockwise attention (which all-reduces
  softmax stats instead of rotating KV).
- **Ulysses** (`ulysses_attention`): `jax.lax.all_to_all` scatters heads /
  gathers sequence so each device runs full-sequence attention on h/sp heads,
  then the inverse all-to-all restores the sequence sharding.  One collective
  pair per attention, best when h >= sp and sequence moderately long.

Both are written against `shard_map` (functional SPMD) so they compose with
the GSPMD-sharded rest of the model, and both differentiate (ppermute and
all_to_all have registered transposes; the flash kernel has a custom VJP).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.flash_attention import flash_attention

try:  # moved out of jax.experimental in newer versions
    from jax import shard_map as _raw_shard_map  # type: ignore

    def shard_map(f, mesh, in_specs, out_specs):
        return _raw_shard_map(f, mesh=_context_mesh(mesh),
                              in_specs=in_specs,
                              out_specs=out_specs, check_vma=True)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _raw_shard_map

    def shard_map(f, mesh, in_specs, out_specs):
        # legacy jax: no get_abstract_mesh, so pp-nesting cannot happen —
        # keep check_rep=False (True would reject the Pallas custom-VJP
        # kernels that lack replication rules on that version)
        return _raw_shard_map(f, mesh=_context_mesh(mesh),
                              in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)


def _context_mesh(mesh: "Mesh"):
    """Nested-shard_map mesh resolution — see parallel/mesh.py
    context_mesh (shared with the pipeline)."""
    from .mesh import context_mesh

    return context_mesh(mesh)


_BATCH_AXES = ("dp", "fsdp")  # mesh data axes (parallel/mesh.py AXIS_ORDER)


def _qkv_spec(mesh: Mesh, seq_axis: str, batch_size: int) -> P:
    """(b, h, S, d) spec: seq over `seq_axis`, batch over the mesh's data
    axes.  Leaving batch unsharded would all-gather the global batch to every
    device at the shard_map boundary and redundantly compute attention over
    it, breaking the O(S/sp) memory claim under dp/fsdp>1.  Axes that don't
    divide the batch are dropped (shard_map requires even division)."""
    batch = []
    div = 1
    for a in _BATCH_AXES:
        n = mesh.shape.get(a, 1) if a in mesh.axis_names else 1
        if n > 1 and batch_size % (div * n) == 0:
            batch.append(a)
            div *= n
    return P(tuple(batch) if batch else None, None, seq_axis, None)


# ------------------------------------------------------------- lse utilities


def _attention_with_lse(q, k, v, causal: bool, sm_scale: Optional[float]):
    """(b, h, sq, d) attention returning (o, lse (b, h, sq) f32) — jnp path
    usable on any backend (shared with ops.flash_attention's fallback)."""
    import math

    from ..ops.flash_attention import _reference_with_lse

    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    return _reference_with_lse(q, k, v, causal, scale)


def _merge_partials(o1, lse1, o2, lse2):
    """Combine two blockwise attention partials over disjoint key sets."""
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.where(jnp.isfinite(lse1), jnp.exp(lse1 - m_safe), 0.0)
    w2 = jnp.where(jnp.isfinite(lse2), jnp.exp(lse2 - m_safe), 0.0)
    tot = w1 + w2
    tot_safe = jnp.where(tot > 0, tot, 1.0)
    o = (o1.astype(jnp.float32) * (w1 / tot_safe)[..., None]
         + o2.astype(jnp.float32) * (w2 / tot_safe)[..., None])
    lse = jnp.where(tot > 0, m_safe + jnp.log(tot_safe), -jnp.inf)
    return o.astype(o1.dtype), lse


# -------------------------------------------------------------- ring attention


def _chunk_attention(q, k, v, causal: bool, sm_scale: Optional[float]):
    """(o, lse) for one KV chunk — the Pallas kernel on TPU (O(s_local) VMEM
    working set, no score matrix in HBM), jnp reference elsewhere."""
    from ..ops.flash_attention import _on_tpu, flash_attention_with_lse

    if _on_tpu():
        return flash_attention_with_lse(q, k, v, causal, sm_scale)
    return _attention_with_lse(q, k, v, causal, sm_scale)


def _ring_attention_local(q, k, v, *, axis_name: str, n: int, causal: bool,
                          sm_scale: Optional[float]):
    """Per-device body under shard_map: q/k/v are the local seq shards
    (b, h, s_local, d).  The ring is unrolled (n is the static sp size) so
    the whole loop differentiates through ppermute's transpose.

    Step 0 attends the local chunk (causal within); steps 1..n-1 receive
    rotated KV from chunk src=(my-t)%n — never the local chunk again — so
    they run the cheaper non-causal kernel, gated to earlier chunks only by
    zeroing the merge weight (lse=-inf) for src > my.  The accumulator stays
    f32 across merges (no per-step requantization)."""
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    o0, lse = _chunk_attention(q, k, v, causal, sm_scale)
    o = o0.astype(jnp.float32)
    k_cur, v_cur = k, v

    for t in range(1, n):
        # rotate KV to the next device (ICI neighbor ring)
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (my - t) % n  # which global seq chunk this KV shard holds
        oc, lc = _chunk_attention(q, k_cur, v_cur, False, sm_scale)
        if causal:
            lc = jnp.where(src < my, lc, -jnp.inf)
        o, lse = _merge_partials(o, lse, oc, lc)
    return o.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, causal: bool = True,
                   sm_scale: Optional[float] = None,
                   axis: str = "sp"):
    """Context-parallel attention; q/k/v (b, h, S, d) seq-sharded over `axis`.

    Returns (b, h, S, d) with the same sharding.  Memory per device is
    O(S/sp); the KV ring rides ICI neighbor links.
    """
    n = mesh.shape.get(axis, 1)
    if n == 1:
        return flash_attention(q, k, v, causal, sm_scale)

    spec = _qkv_spec(mesh, axis, q.shape[0])
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis, n=n,
                          causal=causal, sm_scale=sm_scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


# ------------------------------------------------------------------- Ulysses


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool,
                   sm_scale: Optional[float]):
    """Per-device body: q/k/v (b, h, s_local, d) → all-to-all to
    (b, h/sp, S, d), full-seq attention, inverse all-to-all."""
    # scatter heads (axis 1), gather sequence (axis 2)
    qh = jax.lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    kh = jax.lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    vh = jax.lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    o = flash_attention(qh, kh, vh, causal, sm_scale)
    # scatter sequence back, gather heads
    return jax.lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, causal: bool = True,
                      sm_scale: Optional[float] = None,
                      axis: str = "sp"):
    """Ulysses-style SP attention (parity `_SeqAllToAll` distributed.py:474).

    q/k/v (b, h, S, d) seq-sharded over `axis`; heads must divide the axis
    size.  Each device computes full-sequence attention for h/sp heads.
    """
    sp = mesh.shape.get(axis, 1)
    if sp == 1:
        return flash_attention(q, k, v, causal, sm_scale)
    if q.shape[1] % sp:
        raise ValueError(
            f"ulysses needs heads ({q.shape[1]}) divisible by {axis}={sp}")

    spec = _qkv_spec(mesh, axis, q.shape[0])
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=axis, causal=causal,
                          sm_scale=sm_scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)

"""Local SGD / HSDP — DiLoCo-style two-level optimization.

Parity: reference `atorch/atorch/local_sgd/` (`patch_local_sgd_to_fsdp`
HSDP/__init__.py:17 — FSDP patched so each replica group trains locally and
periodically syncs through an outer optimizer, with GTA-style reduction in
`reduce_methods/`).

TPU redesign: the `dp` mesh axis is the replica-group (multi-slice / DCN)
axis.  Instead of patching a wrapper module, the two-level scheme is a
train-step transform: inner params carry an explicit leading replica axis
sharded P("dp") so groups diverge legitimately; the whole step runs under
`shard_map(axis_names={"dp"})` (fsdp/tp stay GSPMD inside); every
`sync_every` steps the step all-reduces the outer-delta over `dp` (ONE DCN
collective per H steps instead of per step — the point of DiLoCo) and takes
a Nesterov outer step.  Reduction is mean or GTA (sign-agreement gated
tensor averaging, parity reduce_methods/gta.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.log import get_logger
from .sharding import ShardingPlanner

logger = get_logger("local_sgd")

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    _shard_map = None


@dataclasses.dataclass(frozen=True)
class LocalSGDConfig:
    sync_every: int = 16          # H: inner steps between outer syncs
    outer_lr: float = 0.7         # DiLoCo paper's SGD+Nesterov outer opt
    outer_momentum: float = 0.9
    nesterov: bool = True
    reduce: str = "mean"          # "mean" | "gta"
    gta_threshold: float = 0.0    # min sign-agreement fraction for gta


class DiLoCoState(NamedTuple):
    step: jax.Array
    inner_params: Any      # stacked (R, ...) leaves, sharded P("dp", ...)
    inner_opt_state: Any   # stacked likewise
    outer_params: Any      # the shared global params (replicated over dp)
    outer_momentum: Any    # outer optimizer momentum (like outer_params)


def _reduce_delta(delta, cfg: LocalSGDConfig):
    """All-reduce per-group deltas over dp: mean or GTA.

    GTA (gradient/tensor agreement averaging): elementwise, keep only
    components whose sign agrees across a majority of replicas, rescaled —
    parity with reference local_sgd reduce_methods.
    """
    if cfg.reduce == "mean":
        return jax.tree.map(lambda d: jax.lax.pmean(d, "dp"), delta)

    def _gta(d):
        mean = jax.lax.pmean(d, "dp")
        sign_agree = jax.lax.pmean(jnp.sign(d), "dp")  # in [-1, 1]
        gate = (jnp.abs(sign_agree) > cfg.gta_threshold).astype(d.dtype)
        return mean * gate * jnp.abs(sign_agree)

    return jax.tree.map(_gta, delta)


def make_diloco_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    inner_optimizer: optax.GradientTransformation,
    mesh: Mesh,
    planner: ShardingPlanner,
    cfg: LocalSGDConfig,
    accum_steps: int = 1,
    reset_opt_on_sync: Optional[Callable[[Any, Any], Any]] = None,
    opt_host_shardings: Any = None,
    opt_device_shardings: Any = None,
):
    """Returns jit'd `step(DiLoCoState, batch) -> (DiLoCoState, metrics)`.

    The batch is sharded over ("dp", "fsdp") as usual; each dp group trains
    its own inner replica on its batch shard and only the periodic outer
    sync crosses the dp (DCN) axis.  With `accum_steps > 1` the batch
    carries a leading microbatch axis (replicated over dp) and gradients
    accumulate INSIDE the inner step — the accumulation is entirely local
    to each replica group, so it composes with the two-level scheme (the
    round-3 local_sgd x grad_accum rejection, closed).

    `reset_opt_on_sync(opt_state, new_params) -> opt_state` re-anchors
    optimizer state whose contents DERIVE the params (stable_bf16's f32
    master / Kahan term) after the outer sync rewrites them — without it
    the stale master would undo the sync on the next inner update.
    `opt_host_shardings`/`opt_device_shardings` (both or neither): the
    STACKED inner optimizer state lives in pinned_host between steps
    (optimizer_offload x local_sgd) and hops to device for the update —
    same contract as trainer/train_step.py.
    """
    if _shard_map is None:  # pragma: no cover
        raise RuntimeError("local_sgd needs jax.shard_map")
    dp = mesh.shape.get("dp", 1)
    if dp < 2:
        raise ValueError("local_sgd needs a dp axis of size >= 2 "
                         "(the replica groups that train locally)")
    H = cfg.sync_every

    def _unstack(t):
        return jax.tree.map(lambda x: x[0], t)

    def _restack(t):
        return jax.tree.map(lambda x: x[None], t)

    def _body(step, inner_params, inner_opt, outer_params, outer_mom,
              batch):
        p = _unstack(inner_params)
        o = _unstack(inner_opt)
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(p, batch)
        else:
            from ..trainer.train_step import accumulate_grads

            loss, grads = accumulate_grads(
                lambda micro: jax.value_and_grad(loss_fn)(p, micro), p,
                batch, accum_steps)
        updates, o = inner_optimizer.update(grads, o, p)
        p = optax.apply_updates(p, updates)

        do_sync = ((step + 1) % H) == 0

        def _sync(args):
            p, o, w, mom = args
            # outer "gradient": how far this group moved away from w
            delta = jax.tree.map(lambda a, b: (a - b).astype(jnp.float32),
                                 w, p)
            delta = _reduce_delta(delta, cfg)
            mom = jax.tree.map(
                lambda m, d: cfg.outer_momentum * m + d, mom, delta)
            if cfg.nesterov:
                step_dir = jax.tree.map(
                    lambda m, d: cfg.outer_momentum * m + d, mom, delta)
            else:
                step_dir = mom
            w = jax.tree.map(
                lambda wl, s: (wl.astype(jnp.float32)
                               - cfg.outer_lr * s).astype(wl.dtype),
                w, step_dir)
            # every group restarts the next round from the synced params
            p = jax.tree.map(lambda wl: wl.astype(wl.dtype), w)
            if reset_opt_on_sync is not None:
                # params-deriving opt state (stable_bf16 master/Kahan)
                # must re-anchor on the synced tree or it undoes the sync
                o = reset_opt_on_sync(o, p)
            return p, o, w, mom

        def _nosync(args):
            return args

        p, o, outer_params, outer_mom = jax.lax.cond(
            do_sync, _sync, _nosync, (p, o, outer_params, outer_mom))
        loss_avg = jax.lax.pmean(loss, "dp")
        return (_restack(p), _restack(o), outer_params, outer_mom,
                loss_avg)

    # specs: stacked leaves map their leading axis to dp; the batch maps its
    # batch dim to dp so each group trains on ITS shard (fsdp stays auto
    # inside); outer params/momentum/step replicate over dp.  With accum the
    # leading microbatch axis is replicated and dim 1 carries the dp shard.
    stacked_spec = P("dp")
    batch_spec = P("dp") if accum_steps == 1 else P(None, "dp")
    body = _shard_map(
        _body, mesh=mesh,
        in_specs=(P(), stacked_spec, stacked_spec, P(), P(), batch_spec),
        out_specs=(stacked_spec, stacked_spec, P(), P(), P()),
        axis_names={"dp"}, check_vma=False)

    def train_step(state: DiLoCoState, batch):
        inner_o = state.inner_opt_state
        if opt_host_shardings is not None:
            inner_o = jax.device_put(inner_o, opt_device_shardings)
        inner_p, inner_o, outer_p, outer_m, loss = body(
            state.step, state.inner_params, inner_o,
            state.outer_params, state.outer_momentum, batch)
        if opt_host_shardings is not None:
            inner_o = jax.device_put(inner_o, opt_host_shardings)
        new_state = DiLoCoState(state.step + 1, inner_p, inner_o, outer_p,
                                outer_m)
        return new_state, {"loss": loss}

    # offloaded opt states: donation would alias a pinned_host input onto
    # a device output (trainer/train_step.py's documented exception)
    donate = (0,) if opt_host_shardings is None else ()
    return jax.jit(train_step, donate_argnums=donate)


def init_diloco_state(params: Any, inner_optimizer:
                      optax.GradientTransformation, mesh: Mesh,
                      planner: ShardingPlanner,
                      cfg: LocalSGDConfig,
                      offload_opt: bool = False) -> DiLoCoState:
    """Build + place the two-level state on the mesh.

    inner params/opt leaves gain a leading replica axis of size dp sharded
    P("dp", ...); outer params keep the planner's fsdp/tp specs.
    `offload_opt` places the stacked inner optimizer arrays in pinned_host
    (the optimizer_offload x local_sgd composition); the outer trees stay
    on device — they are touched every sync and are 1/3 the bytes.
    """
    dp = mesh.shape["dp"]
    param_specs = planner.param_specs(params)

    def _stack_sharding(spec):
        return NamedSharding(mesh, P("dp", *tuple(spec)))

    def _stack(x, spec):
        tiled = jnp.broadcast_to(x[None], (dp,) + x.shape)
        return jax.device_put(tiled, _stack_sharding(spec))

    inner_params = jax.tree.map(_stack, params, param_specs)
    opt_state = inner_optimizer.init(params)

    def _stack_opt(x):
        x = jnp.asarray(x)
        sh = NamedSharding(mesh, P(*(("dp",) + (None,) * x.ndim)))
        placed = jax.device_put(
            jnp.broadcast_to(x[None], (dp,) + x.shape), sh)
        if offload_opt and x.ndim > 0:  # scalars (counts) stay on device
            placed = jax.device_put(placed, NamedSharding(
                mesh, sh.spec, memory_kind="pinned_host"))
        return placed

    inner_opt = jax.tree.map(_stack_opt, opt_state)
    outer_params = planner.shard_params(params)
    outer_momentum = jax.tree.map(
        lambda x: jnp.zeros(x.shape, jnp.float32), params)
    outer_momentum = jax.device_put(
        outer_momentum, jax.tree.map(
            lambda s: NamedSharding(mesh, s), param_specs,
            is_leaf=lambda x: isinstance(x, P)))
    return DiLoCoState(
        step=jnp.zeros((), jnp.int32),
        inner_params=inner_params, inner_opt_state=inner_opt,
        outer_params=outer_params, outer_momentum=outer_momentum)

"""Pipeline parallelism over the mesh `pp` axis — GPipe-schedule SPMD.

Parity: reference pipe compiler (`atorch/atorch/modules/distributed_modules/
compilers/pipe_compiler/PipelineStage.py:115,922` — PiPPy stage split +
1F1B/interleaved schedule over torch RPC) and
`auto/opt_lib/pipeline_parallel_optimization.py:56`.

TPU redesign: no RPC driver and no stage processes.  The layer stack is
stacked into one pytree with a leading layer axis sharded `P("pp")`, and the
schedule is a `lax.scan` over pipeline ticks inside `shard_map` restricted to
the `pp` axis (`axis_names={"pp"}`): each tick every stage applies its local
layer slice and hands its activation to the next stage with
`jax.lax.ppermute` (ICI neighbor link).  All other mesh axes (dp/fsdp/tp/sp)
stay in GSPMD "auto" mode inside the body, so pipeline composes with the rest
of the strategy space.  Autodiff through scan+ppermute yields the reverse
pipeline (fill-drain backward), which is exactly the GPipe schedule; the
bubble fraction is (pp-1)/(M+pp-1) for M microbatches.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..common.log import get_logger

logger = get_logger("pipeline")

try:
    from jax import shard_map as _shard_map  # jax >= 0.8 style
except ImportError:  # pragma: no cover
    _shard_map = None


def _pp_shard_map(f, mesh, in_specs, out_specs):
    """shard_map manual over ONLY the pp axis; other axes stay GSPMD."""
    if _shard_map is None:  # pragma: no cover
        raise RuntimeError("pipeline parallelism needs jax.shard_map with "
                           "axis_names support (jax >= 0.6)")
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      axis_names={"pp"}, check_vma=False)


def pipeline_apply(block_fn: Callable[[Any, jax.Array], jax.Array],
                   stacked_params: Any, x: jax.Array, mesh: Mesh,
                   num_microbatches: int) -> jax.Array:
    """Run a stacked layer pytree as a `pp`-stage pipeline over `x`.

    Args:
        block_fn: (one_layer_params, x) -> x, applied per layer.
        stacked_params: pytree whose leaves have a leading layer axis L
            (sharded P("pp") — L must divide evenly by pp).
        x: (B, T, C) activations, replicated over pp.
        num_microbatches: M; must divide B.
    Returns (B, T, C), replicated over pp.
    """
    pp = mesh.shape.get("pp", 1)
    if pp == 1:
        def _layer(h, pl):
            return block_fn(pl, h), None
        return jax.lax.scan(_layer, x, stacked_params)[0]

    B = x.shape[0]
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    xm = x.reshape(M, B // M, *x.shape[1:])

    def _stage_body(sp_local, xm_full):
        # sp_local leaves: (L/pp, ...) — this stage's layer slice
        # xm_full: (M, b, T, C) — replicated over pp
        stage = jax.lax.axis_index("pp")
        n_ticks = M + pp - 1
        fwd_perm = [(i, i + 1) for i in range(pp - 1)]

        def _apply_stage(h):
            def _layer(h, pl):
                return block_fn(pl, h), None
            return jax.lax.scan(_layer, h, sp_local)[0]

        def _tick(carry, t):
            buf, outs = carry
            mb_in = jnp.clip(t, 0, M - 1)
            h_in = jnp.where(stage == 0, xm_full[mb_in], buf)
            y = _apply_stage(h_in)
            # hand activation to the next stage (no wraparound)
            buf_next = jax.lax.ppermute(y, "pp", fwd_perm)
            # last stage finished microbatch t-(pp-1) at this tick
            out_idx = t - (pp - 1)
            write = (stage == pp - 1) & (out_idx >= 0)
            outs_upd = outs.at[jnp.clip(out_idx, 0, M - 1)].set(y)
            outs = jnp.where(write, outs_upd, outs)
            return (buf_next, outs), None

        buf0 = jnp.zeros_like(xm_full[0])
        outs0 = jnp.zeros_like(xm_full)
        (_, outs), _ = jax.lax.scan(_tick, (buf0, outs0),
                                    jnp.arange(n_ticks))
        # only the last stage holds real outputs; broadcast over pp so the
        # head computes identically (and cheaply) on every stage
        outs = jax.lax.psum(
            jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs)), "pp")
        return outs

    out = _pp_shard_map(
        _stage_body, mesh,
        in_specs=(P("pp"), P()), out_specs=P())(stacked_params, xm)
    return out.reshape(B, *x.shape[1:])


# --------------------------------------------------------- model integration


_LAYER_RE = re.compile(r"^(h|layers)_(\d+)$")


def split_layer_params(params: Dict) -> Tuple[Dict, List[Dict], str]:
    """Split a flax param dict into (non_layer, [layer_0..layer_{L-1}], key
    prefix).  Layers are the `h_<i>` / `layers_<i>` subtrees."""
    non_layer, layers = {}, {}
    prefix = None
    for k, v in params.items():
        m = _LAYER_RE.match(k)
        if m:
            prefix = m.group(1)
            layers[int(m.group(2))] = v
        else:
            non_layer[k] = v
    ordered = [layers[i] for i in range(len(layers))]
    if not ordered:
        raise ValueError("model has no h_<i>/layers_<i> blocks to pipeline")
    return non_layer, ordered, prefix or "h"


def stack_layer_params(layers: List[Dict]) -> Dict:
    """[per-layer pytree] -> one pytree with leading layer axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layer_params(stacked: Dict, n: int) -> List[Dict]:
    return [jax.tree.map(lambda x: x[i], stacked) for i in range(n)]


@dataclasses.dataclass
class PipelinedLM:
    """Wraps a block-structured LM (GPT/Llama family) for pp execution.

    Looks like a model to the rest of the stack: has `.config`, `.apply`,
    `.init_params`.  Params restructure to {non-layer..., "blocks": stacked}.
    """

    inner: Any  # the wrapped flax module
    mesh: Mesh
    num_microbatches: int

    def __post_init__(self):
        self.config = self.inner.config
        self._n_layer = getattr(self.config, "n_layer",
                                getattr(self.config, "num_layers", 0))

    # -- param plumbing

    def init_params(self, rng, **kw):
        p = dict(self.inner.init_params(rng, **kw))
        non_layer, layers, self._prefix = split_layer_params(p)
        out = dict(non_layer)
        out["blocks"] = stack_layer_params(layers)
        return out

    def to_flat_params(self, params: Dict) -> Dict:
        """Pipelined layout -> the inner model's layout (for export)."""
        out = {k: v for k, v in params.items() if k != "blocks"}
        for i, lp in enumerate(unstack_layer_params(params["blocks"],
                                                    self._n_layer)):
            out[f"{getattr(self, '_prefix', 'h')}_{i}"] = lp
        return out

    # -- forward

    def apply(self, variables, idx, deterministic: bool = True,
              mutable: Any = None):
        params = variables["params"]
        cfg = self.config
        x = self._embed(params, idx)
        block_fn = self._block_fn(params, idx, deterministic)
        x = pipeline_apply(block_fn, params["blocks"], x, self.mesh,
                           self.num_microbatches)
        logits = self._head(params, x)
        if mutable:
            return logits, {}
        return logits

    def __call__(self, *a, **kw):  # pragma: no cover - convenience
        return self.apply(*a, **kw)

    # -- model-family adapters (embed / block / head built from the same
    #    flax modules the inner model uses, so numerics match exactly)

    def _embed(self, params, idx):
        import flax.linen as nn

        cfg = self.config
        T = idx.shape[1]
        if "wte" in params:  # GPT family (models/gpt.py)
            tok = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype).apply(
                {"params": params["wte"]}, idx)
            pos = nn.Embed(cfg.block_size, cfg.n_embd, dtype=cfg.dtype).apply(
                {"params": params["wpe"]}, jnp.arange(T)[None, :])
            return tok + pos
        # Llama family (models/llama.py)
        return nn.Embed(cfg.vocab_size, cfg.hidden_size,
                        dtype=cfg.dtype).apply(
            {"params": params["embed_tokens"]}, idx)

    def _block_fn(self, params, idx, deterministic):
        cfg = self.config
        if "wte" in params:
            from ..models.gpt import Block

            fn = lambda pl, h: Block(cfg).apply(  # noqa: E731
                {"params": pl}, h, deterministic)
        else:
            from ..models.llama import LlamaBlock, rope_freqs

            T = idx.shape[1]
            cos, sin = rope_freqs(cfg.head_dim, T, cfg.rope_theta)
            fn = lambda pl, h: LlamaBlock(cfg).apply(  # noqa: E731
                {"params": pl}, h, cos, sin)
        if getattr(cfg, "remat", False):
            fn = jax.checkpoint(fn, prevent_cse=False)
        return fn

    def _head(self, params, x):
        import flax.linen as nn

        cfg = self.config
        if "wte" in params:
            x = nn.LayerNorm(dtype=cfg.dtype).apply(
                {"params": params["ln_f"]}, x)
            wte = params["wte"]["embedding"]
            return jnp.einsum("bte,ve->btv", x, wte.astype(cfg.dtype))
        from ..models.llama import RMSNorm

        x = RMSNorm(cfg.rms_eps, cfg.dtype).apply(
            {"params": params["norm"]}, x)
        return nn.Dense(cfg.vocab_size, use_bias=False,
                        dtype=cfg.dtype).apply(
            {"params": params["lm_head"]}, x)


class PipelineShardingPlanner:
    """Decorates a ShardingPlanner: `blocks/...` leaves get P("pp", *inner).

    The stacked leading layer axis shards over pp; the remaining dims reuse
    the transformer TP/FSDP rules evaluated against the same path.
    """

    def __init__(self, base):
        self._base = base
        self.mesh = base.mesh
        self.rules = base.rules

    def __getattr__(self, name):
        return getattr(self._base, name)

    def param_specs(self, params: Any) -> Any:
        from .sharding import _add_fsdp, path_of, spec_for_path

        def _spec(key_path, leaf):
            path = path_of(key_path)
            if path.startswith("blocks/"):
                inner = spec_for_path(path, self.rules, ndim=leaf.ndim - 1)
                inner = _add_fsdp(inner, tuple(leaf.shape[1:]), self.mesh,
                                  self._base.fsdp_min_size)
                return P("pp", *tuple(inner) + (None,) * (
                    leaf.ndim - 1 - len(tuple(inner))))
            spec = spec_for_path(path, self.rules, ndim=leaf.ndim)
            return _add_fsdp(spec, tuple(leaf.shape), self.mesh,
                             self._base.fsdp_min_size)

        return jax.tree_util.tree_map_with_path(_spec, params)

    def param_shardings(self, params: Any) -> Any:
        from jax.sharding import NamedSharding

        return jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                            self.param_specs(params),
                            is_leaf=lambda x: isinstance(x, P))

    def batch_sharding(self, *a, **kw):
        return self._base.batch_sharding(*a, **kw)

    def replicated(self):
        return self._base.replicated()

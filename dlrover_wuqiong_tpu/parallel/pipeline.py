"""Pipeline parallelism over the mesh `pp` axis — GPipe / interleaved /
1F1B schedules, SPMD.

Parity: reference pipe compiler (`atorch/atorch/modules/distributed_modules/
compilers/pipe_compiler/PipelineStage.py:115,922` — PiPPy stage split +
1F1B/interleaved schedule over torch RPC) and `StageInterleaver.py`, plus
`auto/opt_lib/pipeline_parallel_optimization.py:56`.

TPU redesign: no RPC driver and no stage processes.  The layer stack is
stacked into one pytree with a leading layer axis sharded `P("pp")`, and the
schedule is a `lax.scan` over pipeline ticks inside `shard_map` restricted to
the `pp` axis (`axis_names={"pp"}`): each tick every stage applies its local
layer slice and hands its activation to the next stage with
`jax.lax.ppermute` (ICI neighbor link).  All other mesh axes (dp/fsdp/tp/sp)
stay in GSPMD "auto" mode inside the body, so pipeline composes with the rest
of the strategy space.

Three schedules (lockstep-SPMD analysis — all stages tick together, so the
torch 1F1B's *async* throughput win does not exist here; what transfers is):

- "gpipe": forward scan, autodiff replays it backward (fill-drain).  Bubble
  fraction (pp-1)/(M+pp-1).  Activation residuals: one per tick — O(M)
  stage-inputs live through the backward.
- "interleaved": Megatron-style interleaved virtual stages, expressed as the
  circular schedule — each device owns `v` non-contiguous layer chunks and
  microbatches wrap around the ring `v` times.  Bubble fraction shrinks to
  (pp-1)/(M*v+pp-1): the fill/drain cost is per *chunk* (1/v of a stage).
  Autodiff again yields the mirrored backward.
- "1f1b": manual one-forward-one-backward schedule.  Each tick a stage runs
  one microbatch forward AND one backward (with on-the-fly recompute from the
  stashed stage *input*), so the live stash is min(M, 2pp-1) microbatch
  inputs — O(pp), independent of M — vs GPipe's O(M).  Same-tick head
  coupling on the last stage starts each microbatch's backward immediately
  after its forward, exactly the 1F1B dependency pattern.  Tick count is
  M + 2(pp-1) combined fwd+bwd ticks (GPipe: M+pp-1 of each), so throughput
  is within (M+pp-1)/(M+2pp-2) of GPipe while memory scales with pp, not M —
  use it to raise M (and thereby shrink the bubble) under a fixed HBM budget.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..common.log import get_logger

logger = get_logger("pipeline")

try:
    from jax import shard_map as _shard_map  # jax >= 0.8 style
except ImportError:  # pragma: no cover
    _shard_map = None


def _pvary_pp(tree):
    """Mark a scan carry as pp-varying for VMA-tracked (nested) contexts.

    Under check_vma=True the scan carry must enter with the same varying-
    axes type it leaves with (ppermute/axis_index make it {V:pp}); outside
    VMA tracking pvary is a no-op."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        try:
            return jax.tree.map(
                lambda x: pcast(x, ("pp",), to="varying"), tree)
        except Exception:  # noqa: BLE001 — fall through to pvary
            pass
    try:
        return jax.tree.map(lambda x: jax.lax.pvary(x, ("pp",)), tree)
    except Exception:  # noqa: BLE001 — older jax without either
        return tree


def _pp_shard_map(f, mesh, in_specs, out_specs):
    """shard_map manual over ONLY the pp axis; other axes stay GSPMD.

    When NESTED inside another manual body (the DiLoCo dp step), the mesh
    must be the context AbstractMesh and VMA tracking must be ON — the
    pp x ring-SP closure showed that an inner shard_map's transpose
    silently corrupts gradients without it (tests pin grad exactness)."""
    if _shard_map is None:  # pragma: no cover
        raise RuntimeError("pipeline parallelism needs jax.shard_map with "
                           "axis_names support (jax >= 0.6)")
    from .mesh import context_mesh

    ctx = context_mesh(mesh)
    nested = ctx is not mesh
    return _shard_map(f, mesh=ctx, in_specs=in_specs, out_specs=out_specs,
                      axis_names={"pp"}, check_vma=nested)


def schedule_ticks(schedule: str, num_microbatches: int, pp: int,
                   virtual_stages: int = 1) -> Tuple[int, float]:
    """(tick count, bubble fraction) of a schedule's forward pass.

    Per-tick work is one layer-*chunk* (a full per-device stage for
    gpipe/1f1b, 1/v of it for interleaved), so bubble fractions are directly
    comparable across schedules."""
    M, v = num_microbatches, virtual_stages
    if schedule == "interleaved":
        ticks = M * v + pp - 1
        return ticks, (pp - 1) / ticks
    ticks = M + pp - 1
    return ticks, (pp - 1) / ticks


def default_pp_microbatches(accum_steps: int, pp: int) -> int:
    """The microbatch-count policy shared by auto_accelerate (what gets
    built) and the strategy engine's bubble estimate (what gets scored) —
    one definition so they cannot silently diverge."""
    return max(accum_steps, 2 * pp)


def circular_layer_order(n_layer: int, pp: int, v: int) -> List[int]:
    """Layer permutation for the interleaved (circular) schedule.

    Chunk c (layers [c*Lc, (c+1)*Lc)) lives on device `c % pp` at local
    position `c // pp`; this order makes each device's `P("pp")` slice of the
    stacked layer axis exactly its v chunks, concatenated."""
    if n_layer % (pp * v):
        raise ValueError(f"layers ({n_layer}) must divide by pp*v="
                         f"{pp * v} for the interleaved schedule")
    lc = n_layer // (pp * v)
    order = []
    for d in range(pp):
        for j in range(v):
            c = d + j * pp
            order.extend(range(c * lc, (c + 1) * lc))
    return order


def _apply_block(block_fn, pl, h):
    """block_fn may return h or (h, aux_scalar) — MoE blocks surface their
    load-balancing aux loss this way (sown intermediates cannot cross the
    shard_map/scan boundary)."""
    out = block_fn(pl, h)
    if isinstance(out, tuple):
        h2, aux = out
        return h2, aux.astype(jnp.float32)
    return out, jnp.zeros((), jnp.float32)


def _scan_blocks(block_fn, h, layer_params):
    """Sequentially apply stacked layers, accumulating aux: the ONE
    aux-carry implementation shared by every schedule."""
    def _layer(carry, pl):
        h, a = carry
        h2, a2 = _apply_block(block_fn, pl, h)
        return (h2, a + a2), None

    (h, aux), _ = jax.lax.scan(_layer, (h, jnp.zeros((), jnp.float32)),
                               layer_params)
    return h, aux


def pipeline_apply(block_fn: Callable[[Any, jax.Array], jax.Array],
                   stacked_params: Any, x: jax.Array, mesh: Mesh,
                   num_microbatches: int, schedule: str = "gpipe",
                   virtual_stages: int = 1, with_aux: bool = False):
    """Run a stacked layer pytree as a `pp`-stage pipeline over `x`.

    Args:
        block_fn: (one_layer_params, x) -> x  OR  -> (x, aux_scalar)
            (MoE load-balance loss), applied per layer.
        stacked_params: pytree whose leaves have a leading layer axis L
            (sharded P("pp") — L must divide evenly by pp).  For
            schedule="interleaved" the layer axis must already be in
            `circular_layer_order`.
        x: (B, T, C) activations, replicated over pp.
        num_microbatches: M; must divide B.
        schedule: "gpipe" | "interleaved" ("1f1b" is a training schedule —
            see `pipeline_1f1b`; its forward alone is gpipe).
        virtual_stages: v chunks per device for "interleaved".
        with_aux: also return the mean-over-microbatches aux loss
            (replicated over pp, differentiable).
    Returns (B, T, C) replicated over pp — or ((B, T, C), aux) with_aux.
    """
    pp = mesh.shape.get("pp", 1)
    if pp == 1:
        out, aux = _scan_blocks(block_fn, x, stacked_params)
        return (out, aux) if with_aux else out

    B = x.shape[0]
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    xm = x.reshape(M, B // M, *x.shape[1:])
    if schedule == "interleaved" and virtual_stages > 1:
        out, aux = _interleaved_apply(block_fn, stacked_params, xm, mesh,
                                      virtual_stages)
        out = out.reshape(B, *x.shape[1:])
        return (out, aux) if with_aux else out

    def _stage_body(sp_local, xm_full):
        # sp_local leaves: (L/pp, ...) — this stage's layer slice
        # xm_full: (M, b, T, C) — replicated over pp
        stage = jax.lax.axis_index("pp")
        n_ticks = M + pp - 1
        fwd_perm = [(i, i + 1) for i in range(pp - 1)]

        def _tick(carry, t):
            buf, outs, aux_acc = carry
            mb_in = jnp.clip(t, 0, M - 1)
            h_in = jnp.where(stage == 0, xm_full[mb_in], buf)
            y, aux_t = _scan_blocks(block_fn, h_in, sp_local)
            # fill/drain ticks compute on garbage: only count aux for this
            # stage's valid microbatch (m = t - stage)
            valid = (t - stage >= 0) & (t - stage < M)
            aux_acc = aux_acc + jnp.where(valid, aux_t, 0.0)
            # hand activation to the next stage (no wraparound)
            buf_next = jax.lax.ppermute(y, "pp", fwd_perm)
            # last stage finished microbatch t-(pp-1) at this tick
            out_idx = t - (pp - 1)
            write = (stage == pp - 1) & (out_idx >= 0)
            outs_upd = outs.at[jnp.clip(out_idx, 0, M - 1)].set(y)
            outs = jnp.where(write, outs_upd, outs)
            return (buf_next, outs, aux_acc), None

        buf0 = jnp.zeros_like(xm_full[0])
        outs0 = jnp.zeros_like(xm_full)
        (_, outs, aux_acc), _ = jax.lax.scan(
            _tick, _pvary_pp((buf0, outs0, jnp.zeros((), jnp.float32))),
            jnp.arange(n_ticks))
        # only the last stage holds real outputs; broadcast over pp so the
        # head computes identically (and cheaply) on every stage
        outs = jax.lax.psum(
            jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs)), "pp")
        # per-stage aux sums over pp; /M = mean over microbatches (matches
        # the dense model's single whole-batch aux)
        aux = jax.lax.psum(aux_acc, "pp") / M
        return outs, aux

    out, aux = _pp_shard_map(
        _stage_body, mesh,
        in_specs=(P("pp"), P()), out_specs=(P(), P()))(stacked_params, xm)
    out = out.reshape(B, *x.shape[1:])
    return (out, aux) if with_aux else out


def _interleaved_apply(block_fn, stacked_params, xm, mesh, v):
    """Circular (interleaved virtual-stage) schedule forward.

    Event (microbatch m, chunk c) runs at tick `c + (m % pp) + pp*v*(m // pp)`
    on device `c % pp` — gap-1 chains (activations hop exactly one tick via a
    wraparound ppermute), no per-device tick collisions, and M*v + pp - 1
    total ticks: the fill/drain bubble costs chunks (1/v stages), not stages.
    Requires M % pp == 0.
    """
    pp = mesh.shape["pp"]
    M = xm.shape[0]
    if M % pp:
        raise ValueError(f"interleaved schedule needs microbatches ({M}) "
                         f"divisible by pp={pp}")

    def _stage_body(sp_local, xm_full):
        stage = jax.lax.axis_index("pp")
        l_loc = jax.tree.leaves(sp_local)[0].shape[0]
        if l_loc % v:
            raise ValueError(f"per-device layers ({l_loc}) not divisible by "
                             f"virtual_stages={v}")
        lc = l_loc // v
        n_ticks = M * v + pp - 1
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def _apply_chunk(j, h):
            chunk = jax.tree.map(
                lambda a: jax.lax.dynamic_slice_in_dim(a, j * lc, lc, 0),
                sp_local)
            return _scan_blocks(block_fn, h, chunk)

        def _tick(carry, t):
            buf, outs, aux_acc = carry
            u = t - stage
            r = jnp.mod(u, pp)            # m % pp
            k = jnp.floor_divide(u, pp)   # j + v * (m // pp)
            j = jnp.clip(jnp.mod(k, v), 0, v - 1)
            q = jnp.floor_divide(k, v)    # m // pp
            valid = (u >= 0) & (q >= 0) & (q < M // pp)
            m = jnp.clip(r + pp * q, 0, M - 1)
            first = (stage == 0) & (j == 0)
            h_in = jnp.where(first, xm_full[m], buf)
            y, aux_t = _apply_chunk(j, h_in)
            aux_acc = aux_acc + jnp.where(valid, aux_t, 0.0)
            is_out = valid & (stage == pp - 1) & (j == v - 1)
            outs = jnp.where(is_out, outs.at[m].set(y), outs)
            return (jax.lax.ppermute(y, "pp", perm), outs, aux_acc), None

        buf0 = jnp.zeros_like(xm_full[0])
        outs0 = jnp.zeros_like(xm_full)
        (_, outs, aux_acc), _ = jax.lax.scan(
            _tick, _pvary_pp((buf0, outs0, jnp.zeros((), jnp.float32))),
            jnp.arange(n_ticks))
        outs = jax.lax.psum(
            jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs)), "pp")
        aux = jax.lax.psum(aux_acc, "pp") / M
        return outs, aux

    return _pp_shard_map(
        _stage_body, mesh,
        in_specs=(P("pp"), P()), out_specs=(P(), P()))(stacked_params, xm)


# ------------------------------------------------------------ 1F1B training


def _tree_zeros_like(t):
    return jax.tree.map(jnp.zeros_like, t)


def pipeline_1f1b(block_fn: Callable[[Any, jax.Array], jax.Array],
                  head_loss_fn: Callable[[Any, jax.Array, jax.Array],
                                         jax.Array],
                  stacked_params: Any, head_params: Any, xm: jax.Array,
                  aux: jax.Array, mesh: Mesh
                  ) -> Tuple[jax.Array, Any, Any, jax.Array]:
    """One-forward-one-backward pipeline training schedule.

    Per tick, every stage runs one microbatch forward and one backward.  The
    backward recomputes the stage from its stashed *input* (activation
    rematerialization), so the live stash is min(M, 2pp-1) microbatch inputs
    per stage — independent of M — where GPipe-through-autodiff keeps
    M + pp - 1 tick residuals alive.  The last stage folds the head+loss
    vjp into its forward slot, seeding each microbatch's backward in the same
    tick (the 1F1B dependency pattern; ref PipelineStage.py:922
    StageInterleaver's fwd/bwd queues).

    Schedule (device d, tick t): forward of microbatch `t - d`; backward of
    microbatch `t - 2(pp-1) + d`.  Both chains hop exactly one tick, so one
    forward ppermute and one backward ppermute per tick suffice.

    Args:
        block_fn: (layer_params, h) -> h OR (h, aux_scalar) — MoE blocks
            surface the router balance loss as aux; its value folds into
            the reported loss and its 1/M cotangent is seeded in each
            backward slot, so MoE composes with 1f1b.
        head_loss_fn: (head_params, h, aux_mb) -> scalar mean loss for one
            microbatch (runs on the last stage only).
        stacked_params: (L, ...) leaves, sharded P("pp").
        head_params: pytree, replicated over pp.
        xm: (M, b, T, C) embedded microbatches.
        aux: (M, b, ...) per-microbatch labels/extras for head_loss_fn.
    Returns:
        (loss, d_stacked, d_head, d_xm) — loss/d_head/d_xm replicated over
        pp, d_stacked sharded P("pp").  All grads are d(mean-over-M loss).
    """
    M = xm.shape[0]
    pp = mesh.shape.get("pp", 1)
    if pp == 1:
        def _total(sp, hp, xm_):
            def _mb(carry, mx):
                x_mb, aux_mb = mx
                h, a = _scan_blocks(block_fn, x_mb, sp)
                return carry + head_loss_fn(hp, h, aux_mb) + a, None
            total, _ = jax.lax.scan(_mb, jnp.zeros((), jnp.float32),
                                    (xm_, aux))
            return total / M
        loss, (d_sp, d_hp, d_xm) = jax.value_and_grad(
            _total, argnums=(0, 1, 2))(stacked_params, head_params, xm)
        return loss, d_sp, d_hp, d_xm

    S = min(M, 2 * pp - 1)          # stash ring size — the memory headline
    n_ticks = M + 2 * (pp - 1)
    fwd_perm = [(i, i + 1) for i in range(pp - 1)]
    bwd_perm = [(i, i - 1) for i in range(1, pp)]

    def _stage_body(sp_local, hp, xm_full, aux_full):
        stage = jax.lax.axis_index("pp")
        zero_h = jnp.zeros_like(xm_full[0])

        def _apply_stage(p, h):
            # (y, aux_scalar): MoE blocks surface the router balance loss;
            # dense blocks get aux = 0 and a zero cotangent — one uniform
            # code path instead of a rejected composition
            return _scan_blocks(block_fn, h, p)

        def _tick(carry, t):
            # Every slot computes unconditionally and masks its results:
            # tp/fsdp collectives live inside the stage/head bodies, and a
            # collective under a pp-varying `lax.cond` deadlocks the
            # cross-device rendezvous (different pp ranks would execute
            # different collective sequences).  Fill/drain waste is bounded:
            # per device the head runs (M+2pp-2)/M times GPipe's head work.
            fwd_buf, bwd_buf, stash, d_sp, d_hp, d_xm, loss = carry

            # ---- forward slot
            m_f = t - stage
            fwd_valid = (m_f >= 0) & (m_f < M)
            m_fc = jnp.clip(m_f, 0, M - 1)
            h_in = jnp.where(stage == 0, xm_full[m_fc], fwd_buf)
            y, aux_t = _apply_stage(sp_local, h_in)
            stash = jnp.where(fwd_valid, stash.at[m_fc % S].set(h_in),
                              stash)
            # the aux VALUE accumulates on the computing stage per valid
            # forward; its psum over pp lands in the reported loss below
            loss = loss + jnp.where(fwd_valid,
                                    aux_t.astype(jnp.float32) / M, 0.0)

            # head + loss, kept on the last stage by masking (cotangent 1/M
            # folds the mean-over-microbatches into every downstream grad)
            lm, head_vjp = jax.vjp(
                lambda hp_, h_: head_loss_fn(hp_, h_, aux_full[m_fc]),
                hp, y)
            d_hp_m, dh_seed = head_vjp(jnp.ones((), lm.dtype) / M)
            is_last_f = fwd_valid & (stage == pp - 1)
            loss = loss + jnp.where(is_last_f,
                                    lm.astype(jnp.float32) / M, 0.0)
            d_hp = jax.tree.map(
                lambda acc, g: acc + jnp.where(is_last_f, g,
                                               jnp.zeros_like(g)),
                d_hp, d_hp_m)

            # ---- backward slot (recompute-from-stash vjp)
            m_b = t - 2 * (pp - 1) + stage
            bwd_valid = (m_b >= 0) & (m_b < M)
            m_bc = jnp.clip(m_b, 0, M - 1)
            dy = jnp.where(stage == pp - 1, dh_seed, bwd_buf)
            h_s = stash[m_bc % S]
            _, stage_vjp = jax.vjp(_apply_stage, sp_local, h_s)
            # seed BOTH outputs: dL/dy from downstream, dL/daux = 1/M (the
            # mean-over-microbatches weight of the router balance loss) —
            # this is the cotangent whose absence forced the old
            # MoE x 1f1b rejection
            d_p_m, dh_prev = stage_vjp(
                (dy.astype(h_s.dtype),
                 jnp.ones((), jnp.float32) / M))
            d_sp = jax.tree.map(
                lambda acc, g: acc + jnp.where(bwd_valid, g,
                                               jnp.zeros_like(g)),
                d_sp, d_p_m)
            dh_prev = jnp.where(bwd_valid, dh_prev, zero_h)
            d_xm = jnp.where(bwd_valid & (stage == 0),
                             d_xm.at[m_bc].set(dh_prev), d_xm)

            # ---- ring hops (unconditional; invalid slots carry zeros that
            # land in equally-invalid slots next tick)
            fwd_buf = jax.lax.ppermute(y, "pp", fwd_perm)
            bwd_buf = jax.lax.ppermute(dh_prev, "pp", bwd_perm)
            return (fwd_buf, bwd_buf, stash, d_sp, d_hp, d_xm, loss), None

        carry0 = _pvary_pp(
            (zero_h, zero_h,
             jnp.zeros((S,) + xm_full[0].shape, xm_full.dtype),
             _tree_zeros_like(sp_local), _tree_zeros_like(hp),
             jnp.zeros_like(xm_full), jnp.zeros((), jnp.float32)))
        (_, _, _, d_sp, d_hp, d_xm, loss), _ = jax.lax.scan(
            _tick, carry0, jnp.arange(n_ticks))

        # loss: CE lives on the last stage only (masked at accumulation);
        # per-stage aux sums live everywhere — psum folds both
        loss = jax.lax.psum(loss, "pp")
        d_hp = jax.tree.map(
            lambda g: jax.lax.psum(
                jnp.where(stage == pp - 1, g, jnp.zeros_like(g)), "pp"),
            d_hp)
        d_xm = jax.lax.psum(
            jnp.where(stage == 0, d_xm, jnp.zeros_like(d_xm)), "pp")
        return loss, d_sp, d_hp, d_xm

    return _pp_shard_map(
        _stage_body, mesh,
        in_specs=(P("pp"), P(), P(), P()),
        out_specs=(P(), P("pp"), P(), P()))(
            stacked_params, head_params, xm, aux)


# --------------------------------------------------------- model integration


_LAYER_RE = re.compile(r"^(h|layers)_(\d+)$")


def split_layer_params(params: Dict) -> Tuple[Dict, List[Dict], str]:
    """Split a flax param dict into (non_layer, [layer_0..layer_{L-1}], key
    prefix).  Layers are the `h_<i>` / `layers_<i>` subtrees."""
    non_layer, layers = {}, {}
    prefix = None
    for k, v in params.items():
        m = _LAYER_RE.match(k)
        if m:
            prefix = m.group(1)
            layers[int(m.group(2))] = v
        else:
            non_layer[k] = v
    ordered = [layers[i] for i in range(len(layers))]
    if not ordered:
        raise ValueError("model has no h_<i>/layers_<i> blocks to pipeline")
    return non_layer, ordered, prefix or "h"


def stack_layer_params(layers: List[Dict]) -> Dict:
    """[per-layer pytree] -> one pytree with leading layer axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layer_params(stacked: Dict, n: int) -> List[Dict]:
    return [jax.tree.map(lambda x: x[i], stacked) for i in range(n)]


@dataclasses.dataclass
class PipelinedLM:
    """Wraps a block-structured LM (GPT/Llama family) for pp execution.

    Looks like a model to the rest of the stack: has `.config`, `.apply`,
    `.init_params`.  Params restructure to {non-layer..., "blocks": stacked}.

    `schedule`: "gpipe" | "interleaved" | "1f1b".  Interleaved stores the
    stacked layer axis in `circular_layer_order` (undone by
    `to_flat_params`).  "1f1b" applies to training via `value_and_grad`;
    its plain forward is gpipe.

    Arbitrary layer-stack models (anything `split_layer_params` can split)
    plug in via the `embed_fn` / `block_builder` / `head_fn` adapter hooks;
    the GPT/Llama adapters below are the defaults.
    """

    inner: Any  # the wrapped flax module
    mesh: Mesh
    num_microbatches: int
    schedule: str = "gpipe"
    virtual_stages: int = 1
    embed_fn: Optional[Callable] = None      # (params, idx) -> (B,T,C)
    block_builder: Optional[Callable] = None  # (params, idx, det) -> block_fn
    head_fn: Optional[Callable] = None       # (head_params, h) -> logits
    embed_keys: Optional[Tuple[str, ...]] = None
    head_keys: Optional[Tuple[str, ...]] = None
    # custom PER-MICROBATCH head loss for the 1f1b schedule:
    # (head_params, h (b,T,C), labels (b,T)) -> scalar mean loss.  This is
    # the shape 1f1b can honor (its backward seeds per-microbatch head
    # vjps in-schedule); a whole-batch (params, batch) loss_fn cannot be
    # decomposed that way and stays rejected.
    head_loss_fn: Optional[Callable] = None
    # does block_fn return (h, aux)?  None = derive: MoE configs using the
    # built-in adapters do; custom block_builders must say so explicitly
    # (a silent zero aux would hide a dropped balance loss)
    block_returns_aux: Optional[bool] = None

    def __post_init__(self):
        self.config = self.inner.config
        self._n_layer = getattr(self.config, "n_layer",
                                getattr(self.config, "num_layers", 0))
        if self.head_loss_fn is not None and self.schedule != "1f1b":
            raise ValueError(
                "head_loss_fn only applies to schedule='1f1b' — gpipe/"
                "interleaved train through a whole-batch loss_fn and "
                "would silently ignore it")
        if getattr(self.config, "moe_experts", 0) and \
                self.block_builder is not None and \
                self.block_returns_aux is None:
            # fail HERE, before any (possibly many-GB) param init —
            # guessing either way silently drops or fabricates the
            # router balance loss
            raise ValueError(
                "MoE config with a custom block_builder: set "
                "block_returns_aux=True if the builder's block_fn returns "
                "(h, aux), False if the aux loss is handled elsewhere")
        pp = self.mesh.shape.get("pp", 1)
        if self.schedule == "interleaved":
            self._order = circular_layer_order(self._n_layer, pp,
                                               self.virtual_stages)
        else:
            self._order = list(range(self._n_layer))

    # -- param plumbing

    def init_params(self, rng, **kw):
        return self.from_flat_params(self.inner.init_params(rng, **kw))

    def from_flat_params(self, flat: Dict) -> Dict:
        """The inner model's layout -> pipelined layout (ckpt import)."""
        non_layer, layers, self._prefix = split_layer_params(dict(flat))
        out = dict(non_layer)
        out["blocks"] = stack_layer_params([layers[i] for i in self._order])
        return out

    def to_flat_params(self, params: Dict) -> Dict:
        """Pipelined layout -> the inner model's layout (for export)."""
        out = {k: v for k, v in params.items() if k != "blocks"}
        stacked = unstack_layer_params(params["blocks"], self._n_layer)
        for pos, layer_idx in enumerate(self._order):
            out[f"{getattr(self, '_prefix', 'h')}_{layer_idx}"] = \
                stacked[pos]
        return out

    # -- forward

    def apply(self, variables, idx, deterministic: bool = True,
              mutable: Any = None):
        params = variables["params"]
        x = self._embed(params, idx)
        block_fn = self._block_fn(params, idx, deterministic)
        # MoE + custom builder without block_returns_aux was rejected in
        # __post_init__, so the derive below is unambiguous
        want_aux = (self.block_returns_aux
                    if self.block_returns_aux is not None
                    else bool(getattr(self.config, "moe_experts", 0)))
        res = pipeline_apply(block_fn, params["blocks"], x, self.mesh,
                             self.num_microbatches, schedule=self.schedule,
                             virtual_stages=self.virtual_stages,
                             with_aux=want_aux)
        if want_aux:
            x, aux = res
        else:
            x = res
        logits = self._head(params, x)
        if mutable:
            # surface the MoE aux loss the way flax sow would, so
            # make_lm_loss's collect_moe_aux_loss finds it
            inter = ({"intermediates": {"moe_aux_loss": (aux,)}}
                     if want_aux else {})
            return logits, inter
        return logits

    # -- 1F1B training path

    def _embed_head_keys(self, params) -> Tuple[Tuple[str, ...],
                                                Tuple[str, ...]]:
        if self.embed_keys or self.head_keys:
            if not (self.embed_keys and self.head_keys):
                raise ValueError("embed_keys and head_keys must be supplied "
                                 "together for adapter-hook models")
            return self.embed_keys, self.head_keys
        if "wte" in params:   # GPT: tied wte appears in BOTH (grads sum)
            return ("wte", "wpe"), ("ln_f", "wte")
        return ("embed_tokens",), ("norm", "lm_head")

    def value_and_grad(self, params: Dict, batch: Dict
                       ) -> Tuple[jax.Array, Dict]:
        """(loss, grads) via the 1F1B schedule — used by make_train_step in
        place of jax.value_and_grad when schedule == "1f1b".  The head
        loss is `head_loss_fn` when supplied, else token cross-entropy."""
        from ..models.gpt import cross_entropy_loss

        idx, labels = batch["input_ids"], batch["labels"]
        M = self.num_microbatches
        B, T = idx.shape
        if B % M:
            raise ValueError(f"batch {B} not divisible by microbatches {M}")
        e_keys, h_keys = self._embed_head_keys(params)
        ep = {k: params[k] for k in e_keys}
        hp = {k: params[k] for k in h_keys}
        x, embed_vjp = jax.vjp(lambda e: self._embed(e, idx), ep)
        xm = x.reshape(M, B // M, T, x.shape[-1])
        lm = labels.reshape(M, B // M, T)
        block_fn = self._block_fn(params, idx, True)

        if self.head_loss_fn is not None:
            head_loss = self.head_loss_fn
        else:
            def head_loss(hparams, h, lbl):
                return cross_entropy_loss(self._head(hparams, h), lbl)

        loss, d_blocks, d_head, d_xm = pipeline_1f1b(
            block_fn, head_loss, params["blocks"], hp, xm, lm, self.mesh)
        (d_embed,) = embed_vjp(d_xm.reshape(B, T, -1).astype(x.dtype))
        grads: Dict = {"blocks": d_blocks}
        for k in e_keys:
            grads[k] = d_embed[k]
        for k in h_keys:
            grads[k] = (jax.tree.map(jnp.add, grads[k], d_head[k])
                        if k in grads else d_head[k])
        return loss, grads

    def __call__(self, *a, **kw):  # pragma: no cover - convenience
        return self.apply(*a, **kw)

    # -- model-family adapters (embed / block / head built from the same
    #    flax modules the inner model uses, so numerics match exactly)

    def _embed(self, params, idx):
        if self.embed_fn is not None:
            return self.embed_fn(params, idx)
        import flax.linen as nn

        cfg = self.config
        T = idx.shape[1]
        if "wte" in params:  # GPT family (models/gpt.py)
            tok = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype).apply(
                {"params": params["wte"]}, idx)
            pos = nn.Embed(cfg.block_size, cfg.n_embd, dtype=cfg.dtype).apply(
                {"params": params["wpe"]}, jnp.arange(T)[None, :])
            return tok + pos
        # Llama family (models/llama.py)
        return nn.Embed(cfg.vocab_size, cfg.hidden_size,
                        dtype=cfg.dtype).apply(
            {"params": params["embed_tokens"]}, idx)

    def _block_fn(self, params, idx, deterministic):
        if self.block_builder is not None:
            return self.block_builder(params, idx, deterministic)
        cfg = self.config
        if getattr(cfg, "moe_experts", 0) and "wte" in params:
            # MoE blocks: capture the sown load-balance aux loss and carry
            # it through the pipeline as an explicit scalar
            from ..models.gpt import Block
            from ..models.moe import collect_moe_aux_loss

            def fn(pl, h):
                h2, upd = Block(cfg).apply(
                    {"params": pl}, h, deterministic,
                    mutable=["intermediates"])
                return h2, collect_moe_aux_loss(
                    upd.get("intermediates", {}))
        elif "wte" in params:
            from ..models.gpt import Block

            fn = lambda pl, h: Block(cfg).apply(  # noqa: E731
                {"params": pl}, h, deterministic)
        else:
            from ..models.llama import LlamaBlock, rope_freqs

            T = idx.shape[1]
            cos, sin = rope_freqs(cfg.head_dim, T, cfg.rope_theta)
            fn = lambda pl, h: LlamaBlock(cfg).apply(  # noqa: E731
                {"params": pl}, h, cos, sin)
        if getattr(cfg, "remat", False):
            fn = jax.checkpoint(fn, prevent_cse=False)
        return fn

    def _head(self, params, x):
        if self.head_fn is not None:
            return self.head_fn(params, x)
        import flax.linen as nn

        cfg = self.config
        if "wte" in params:
            x = nn.LayerNorm(dtype=cfg.dtype).apply(
                {"params": params["ln_f"]}, x)
            wte = params["wte"]["embedding"]
            return jnp.einsum("bte,ve->btv", x, wte.astype(cfg.dtype))
        from ..models.llama import RMSNorm

        x = RMSNorm(cfg.rms_eps, cfg.dtype).apply(
            {"params": params["norm"]}, x)
        return nn.Dense(cfg.vocab_size, use_bias=False,
                        dtype=cfg.dtype).apply(
            {"params": params["lm_head"]}, x)


class PipelineShardingPlanner:
    """Decorates a ShardingPlanner: `blocks/...` leaves get P("pp", *inner).

    The stacked leading layer axis shards over pp; the remaining dims reuse
    the transformer TP/FSDP rules evaluated against the same path.
    """

    def __init__(self, base):
        self._base = base
        self.mesh = base.mesh
        self.rules = base.rules

    def __getattr__(self, name):
        return getattr(self._base, name)

    def param_specs(self, params: Any) -> Any:
        from .sharding import _add_fsdp, path_of, spec_for_path

        def _spec(key_path, leaf):
            path = path_of(key_path)
            if path.startswith("blocks/"):
                inner = spec_for_path(path, self.rules, ndim=leaf.ndim - 1)
                inner = _add_fsdp(inner, tuple(leaf.shape[1:]), self.mesh,
                                  self._base.fsdp_min_size)
                return P("pp", *tuple(inner) + (None,) * (
                    leaf.ndim - 1 - len(tuple(inner))))
            spec = spec_for_path(path, self.rules, ndim=leaf.ndim)
            return _add_fsdp(spec, tuple(leaf.shape), self.mesh,
                             self._base.fsdp_min_size)

        return jax.tree_util.tree_map_with_path(_spec, params)

    def param_shardings(self, params: Any) -> Any:
        from jax.sharding import NamedSharding

        return jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                            self.param_specs(params),
                            is_leaf=lambda x: isinstance(x, P))

    def batch_sharding(self, *a, **kw):
        return self._base.batch_sharding(*a, **kw)

    def replicated(self):
        return self._base.replicated()

"""Device-mesh planning: the TPU analogue of atorch's parallel-group engine.

Parity: reference `atorch/atorch/distributed/distributed.py`
(`create_parallel_group` :323, `get_pg_ranks` :291 — NCCL groups per parallel
dim) and `auto/opt_lib/shard_planners/dim_planner.py` (DimPlanner, auto sizing
of {tensor, pipe, data} dims).

TPU redesign: parallel "groups" are axes of one `jax.sharding.Mesh`.  Axis
order follows the hardware: innermost axes (tp/sp) ride ICI with the highest
bandwidth; outer axes (dp over DCN for multi-slice) tolerate lower bandwidth.
All axes always exist (size-1 axes are free) so PartitionSpecs are stable
across plans.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common.log import get_logger

logger = get_logger("mesh")

# canonical axis order: outer (slow/DCN) → inner (fast/ICI)
AXIS_ORDER = ("dp", "pp", "fsdp", "ep", "sp", "tp")


@dataclass
class MeshPlan:
    """Sizes of every parallel dim; product must equal device count."""

    dp: int = 1    # pure data parallel (replicated params)
    pp: int = 1    # pipeline stages
    fsdp: int = 1  # data parallel with sharded params/opt-state (ZeRO-3)
    ep: int = 1    # expert parallel
    sp: int = 1    # sequence/context parallel
    tp: int = 1    # tensor parallel

    def sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    @property
    def num_devices(self) -> int:
        return math.prod(self.sizes().values())

    @property
    def data_axes(self) -> Tuple[str, ...]:
        """Axes over which the batch is split."""
        return ("dp", "fsdp")

    def validate(self, num_devices: int):
        if self.num_devices != num_devices:
            raise ValueError(
                f"mesh plan {self.sizes()} needs {self.num_devices} devices, "
                f"have {num_devices}")

    def describe(self) -> str:
        return "x".join(f"{a}{n}" for a, n in self.sizes().items() if n > 1) \
            or "single"


def build_mesh(plan: MeshPlan,
               devices: Optional[Sequence] = None) -> "jax.sharding.Mesh":
    """Build the global mesh. Multi-host: `devices` defaults to
    `jax.devices()` (all processes' devices — requires jax.distributed)."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    plan.validate(len(devices))
    shape = tuple(plan.sizes()[a] for a in AXIS_ORDER)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, AXIS_ORDER)


def auto_plan(num_devices: int, num_params: Optional[int] = None,
              hbm_per_device: int = 16 << 30,
              seq_len: int = 0, num_experts: int = 0,
              max_tp: int = 8) -> MeshPlan:
    """Heuristic dim planner (parity: DimPlanner dim_planner.py:238).

    Strategy: fit first (enough combined HBM for params+opt+activations),
    then throughput — prefer pure DP/FSDP (no per-layer collectives), add TP
    only when a single chip cannot hold a layer's working set, SP for very
    long sequences, EP sized to expert count.
    """
    plan = MeshPlan()
    remaining = num_devices

    if num_params:
        # bytes/param: bf16 params + f32 master+m+v ≈ 14; activations extra
        state_bytes = num_params * 14
        min_shards = max(1, math.ceil(state_bytes / (hbm_per_device * 0.7)))
        # TP when even sharded state per device is huge (very large models)
        if num_params > 30e9 and remaining >= 4:
            plan.tp = min(max_tp, _largest_pow2_leq(min(remaining, max_tp)))
            remaining //= plan.tp
    if seq_len >= 32768 and remaining >= 2:
        plan.sp = min(_largest_pow2_leq(remaining), max(2, seq_len // 32768))
        plan.sp = _largest_pow2_leq(plan.sp)
        remaining //= plan.sp
    if num_experts and remaining >= 2:
        plan.ep = min(_largest_pow2_leq(remaining), num_experts)
        remaining //= plan.ep
    # everything else: FSDP (sharded state costs nothing on TPU; allgather
    # weights overlap with compute under XLA latency hiding)
    plan.fsdp = remaining
    plan.validate(num_devices)
    if num_params:
        # enforce the fit: state must shard across enough devices.  sp/ep
        # don't shard the optimizer state, so only tp*fsdp counts.  Before
        # giving up, reclaim sp/ep devices for fsdp — fitting beats the
        # nice-to-have axes.
        while plan.tp * plan.fsdp < min_shards and (plan.sp > 1
                                                    or plan.ep > 1):
            if plan.sp > 1:
                plan.sp //= 2
            else:
                plan.ep //= 2
            plan.fsdp *= 2
            logger.info("reclaimed a device axis for state fit: %s",
                        plan.describe())
        if plan.tp * plan.fsdp < min_shards:
            raise ValueError(
                f"model state (~{num_params * 14 / 1e9:.0f} GB) does not fit: "
                f"needs ≥{min_shards} state shards but plan "
                f"{plan.describe()} provides {plan.tp * plan.fsdp} "
                f"(devices with ≥{hbm_per_device >> 30} GiB HBM)")
    logger.info("auto mesh plan for %d devices: %s", num_devices,
                plan.describe())
    return plan


def detect_hbm_per_device(devices: Optional[Sequence] = None) -> int:
    """Per-device accelerator memory, from the runtime when available."""
    try:
        import jax

        devices = devices or jax.devices()
        stats = devices[0].memory_stats()
        limit = int(stats.get("bytes_limit", 0)) if stats else 0
        if limit > 0:
            return limit
    except Exception:  # noqa: BLE001 — CPU/older runtimes have no stats
        pass
    return 16 << 30


def _largest_pow2_leq(n: int) -> int:
    return 1 << (max(1, n).bit_length() - 1)


def hybrid_slice_plan(num_slices: int, devices_per_slice: int,
                      tp: int = 1, sp: int = 1) -> MeshPlan:
    """Multi-slice (DCN-connected) plan: dp over slices, fsdp/tp within
    a slice so heavy collectives stay on ICI (SURVEY.md §2.5 TPU row)."""
    inner = devices_per_slice // (tp * sp)
    return MeshPlan(dp=num_slices, fsdp=inner, tp=tp, sp=sp)


def context_mesh(mesh):
    """The mesh a NESTED shard_map must target.

    Inside another shard_map (manual axes active), jax requires the inner
    shard_map's mesh to be the context AbstractMesh — whose already-manual
    axes are marked — not the original all-Auto concrete mesh.  Outside
    any manual context the concrete mesh passes through unchanged.  Used
    by parallel/long_context.py (ring/Ulysses inside the pipeline) and
    parallel/pipeline.py (pipeline inside the DiLoCo dp body).
    """
    try:
        from jax.sharding import get_abstract_mesh
    except ImportError:  # pragma: no cover — legacy jax: no nesting
        return mesh
    ctx = get_abstract_mesh()
    if ctx is not None and getattr(ctx, "axis_names", None) and \
            any("manual" in str(t).lower() for t in
                getattr(ctx, "axis_types", ())):
        return ctx
    return mesh


def in_manual_context() -> bool:
    """True when tracing inside a shard_map with manual axes."""
    return context_mesh(None) is not None

"""Synthetic-fleet RPC benchmark: spawned master + hundreds of clients.

Parity: the reference has no control-plane load harness — masters are
sized by running real jobs (`dlrover/python/master/dist_master.py:86`
composes managers with no benchmark hook; `master/servicer.py` RPC
handlers are exercised only by live agents).  Redesign: on TPU slices a
single journaled master fronts hundreds of hosts, so its RPC ceiling is
a first-class perf surface — this module is the proof harness for the
group-commit control plane (master/journal.py): one master SUBPROCESS
(the real ``python -m
dlrover_wuqiong_tpu.master`` entry, journal enabled) is hammered by
hundreds of threaded `MasterClient`s spread over several worker
PROCESSES — client processes, not threads, because a single python
process tops out near 4k rpc/s on the GIL and would measure itself, not
the master.  The workload mixes the three verb classes exactly as a
real fleet does (agent/master_client.py):

  journaled  kv_store_set / kv_store_add — durable frame before the ack
  buffered   goodput-ledger / custom-metric reports — never journaled
  polling    waiting-num / journal-stats gets — read-only

Two phases, same machinery: ``--group-commit-max-frames=1`` (the
historical per-frame-fsync baseline) vs the group-commit default.  The
headline evidence is journaled-verb throughput ratio + `rpc_p99_ms` +
`journal_batch_mean` (frames per fsync), reported as ADD-ONLY keys in
bench.py's single-line JSON and streamed per-round by
``tools/perf_probe.py rpc``.

CPU-only by construction: nothing here touches an accelerator (client
procs never import jax — verified by test_fleet_bench), so the numbers
are tunnel-independent and comparable across machines.
"""

from __future__ import annotations

import math
import multiprocessing as mp
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

VERB_CLASSES = ("journaled", "buffered", "polling")

#: workers sleep until this shared wall-clock instant so every client
#: thread measures the SAME window (spawn/import skew stays outside it)
_START_LEAD_S = 6.0


def _client_thread(addr: str, node_id: int, start_at: float,
                   duration_s: float, out: Dict):
    """One synthetic agent: a mixed verb cycle until the deadline.

    Latencies are wall milliseconds per completed RPC, bucketed by verb
    class.  The mix is journaled-HEAVY (4 journaled : 1 buffered :
    1 polling) — an elastic fleet's hot verbs (task results, kv
    barriers, serve submissions) are the journaled ones, and they are
    what per-frame fsync convoys.  RPC failures (a timed-out frame
    behind a convoyed journal) are COUNTED, not fatal: baseline stalls
    are evidence, not a bench crash.
    """
    from .agent.master_client import MasterClient
    from .common.comm import RpcError

    cli = MasterClient(addr, node_id, outage_grace_s=30.0)
    lat: Dict[str, List[float]] = {c: [] for c in VERB_CLASSES}
    done_in_window: Dict[str, int] = {c: 0 for c in VERB_CLASSES}
    errors = 0
    key = f"fleet-{node_id}"
    ledger = {"states": {"productive": 1.0}, "wall_s": 1.0,
              "other_s": 0.0, "goodput_fraction": 1.0}
    now = time.time()
    if start_at > now:
        time.sleep(start_at - now)
    deadline = time.monotonic() + duration_s
    step = 0

    def timed(cls, fn, *args):
        nonlocal errors
        t0 = time.perf_counter()
        try:
            fn(*args)
        except RpcError:  # includes MasterUnreachableError
            errors += 1
            return
        lat[cls].append((time.perf_counter() - t0) * 1e3)
        # throughput counts only IN-WINDOW completions — a per-frame
        # baseline stalling RPCs for seconds must not bank the late tail
        # as window throughput (latency keeps the tail for p99)
        if time.monotonic() <= deadline:
            done_in_window[cls] += 1

    try:
        while time.monotonic() < deadline:
            step += 1
            timed("journaled", cli.kv_store_set, key, b"x%d" % step)
            timed("journaled", cli.kv_store_add, "fleet-counter", 1)
            timed("journaled", cli.kv_store_set, key + "b", b"y%d" % step)
            timed("journaled", cli.kv_store_add, f"fc{node_id % 8}", 1)
            timed("buffered", cli.report_goodput_ledger, ledger)
            timed("polling", cli.num_nodes_waiting)
    finally:
        cli.close()
    out[node_id] = {"lat": lat, "done": done_in_window, "errors": errors}


def _fleet_worker(addr: str, proc_idx: int, threads: int, start_at: float,
                  duration_s: float, conn):
    """Spawn target (module-level: picklable): one client process."""
    results: Dict[int, Dict[str, List[float]]] = {}
    ts = []
    for t in range(threads):
        node_id = 1000 + proc_idx * threads + t
        th = threading.Thread(
            target=_client_thread,
            args=(addr, node_id, start_at, duration_s, results),
            daemon=True)
        th.start()
        ts.append(th)
    for th in ts:
        th.join(duration_s + _START_LEAD_S + 60.0)
    merged: Dict = {c: [] for c in VERB_CLASSES}
    merged["done"] = {c: 0 for c in VERB_CLASSES}
    merged["errors"] = 0
    for got in results.values():
        for c in VERB_CLASSES:
            merged[c] += got["lat"][c]
            merged["done"][c] += got["done"][c]
        merged["errors"] += got["errors"]
    conn.send(merged)
    conn.close()


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(math.ceil(q * len(sorted_vals))) - 1)
    return sorted_vals[max(0, idx)]


class FleetMaster:
    """A journal-enabled master subprocess for benchmark phases.

    Context manager: spawns ``python -m dlrover_wuqiong_tpu.master`` with
    the group-commit knob under test, waits until connectable, and
    SIGTERMs it on exit.  ``journal_stats()`` polls the read-only gauge
    verb from the parent process.
    """

    def __init__(self, group_commit: bool,
                 max_frames: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 fsync_floor_ms: float = 0.0,
                 standby: bool = False):
        self.group_commit = group_commit
        self.max_frames = 1 if not group_commit else (max_frames or 256)
        self.max_wait_ms = max_wait_ms
        self.fsync_floor_ms = fsync_floor_ms
        # attach a warm standby (master/standby.py) tailing this master's
        # journal with NO lease (pure mirror, never promotes): the bench
        # phase proving shipping stays off the commit path (ISSUE 20)
        self.standby = standby
        self.standby_addr = ""
        self.addr = ""
        self._proc: Optional[subprocess.Popen] = None
        self._standby_proc: Optional[subprocess.Popen] = None
        self._work = ""

    def __enter__(self) -> "FleetMaster":
        from .common.comm import addr_connectable, find_free_port

        self._work = tempfile.mkdtemp(prefix="dwt-fleet-")
        port = find_free_port()
        self.addr = f"127.0.0.1:{port}"
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            # steady-state commit throughput: keep compaction (which
            # fences the queue) out of the measured window
            DWT_CTX_JOURNAL_SNAPSHOT_EVERY="100000000",
            # slow-storage emulation (journal.py): local NVMe fsyncs in
            # ~0.1ms, production masters journal to PD-class disks
            DWT_JOURNAL_FSYNC_FLOOR_MS=str(int(self.fsync_floor_ms)),
            PYTHONPATH=repo_root + os.pathsep +
            os.environ.get("PYTHONPATH", ""))
        args = [sys.executable, "-m", "dlrover_wuqiong_tpu.master",
                f"--port={port}", "--min_nodes=1", "--max_nodes=1",
                f"--journal-dir={os.path.join(self._work, 'journal')}",
                "--poll-interval=1.0",
                f"--group-commit-max-frames={self.max_frames}"]
        if self.max_wait_ms is not None:
            args.append(f"--group-commit-max-wait-ms={self.max_wait_ms}")
        self._proc = subprocess.Popen(
            args, env=env, cwd=self._work, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and \
                not addr_connectable(self.addr):
            if self._proc.poll() is not None:
                raise RuntimeError(
                    "fleet master died on startup: "
                    + (self._proc.stdout.read() or "")[-2000:])
            time.sleep(0.1)
        if not addr_connectable(self.addr):
            raise RuntimeError("fleet master never came up")
        if self.standby:
            sb_port = find_free_port()
            self.standby_addr = f"127.0.0.1:{sb_port}"
            # a mirror does not need failover-grade 50ms polls: 0.2s
            # keeps lag to ~one pull of frames while the tailer's wakeup
            # + fetch cost stays off the same (possibly single) CPU the
            # measured master is on — the retention gauge compares
            # THROUGHPUT, and scheduler steal would masquerade as
            # shipping cost
            sb_env = dict(env, DWT_STANDBY_POLL_S="0.2")
            self._standby_proc = subprocess.Popen(
                [sys.executable, "-m", "dlrover_wuqiong_tpu.master",
                 f"--port={sb_port}", "--min_nodes=1", "--max_nodes=1",
                 f"--journal-dir={os.path.join(self._work, 'jrnl-sb')}",
                 "--poll-interval=1.0", f"--standby-of={self.addr}"],
                env=sb_env, cwd=self._work, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            # gate the phase on the mirror actually flowing: the
            # primary's lag gauge goes live on the standby's first fetch
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if self._standby_proc.poll() is not None:
                    raise RuntimeError(
                        "fleet standby died on startup: "
                        + (self._standby_proc.stdout.read() or "")[-2000:])
                if self.journal_stats()["standby_lag_frames"] >= 0:
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError("fleet standby never fetched")
        return self

    def journal_stats(self) -> Dict:
        from .agent.master_client import MasterClient

        cli = MasterClient(self.addr, node_id=-2, outage_grace_s=10.0)
        try:
            st = cli.get_journal_stats()
            return {"enabled": st.enabled, "group_commit": st.group_commit,
                    "max_frames": st.max_frames,
                    "max_wait_ms": st.max_wait_ms,
                    "fsync_floor_ms": st.fsync_floor_ms,
                    "batches": st.batches, "frames": st.frames,
                    "batch_mean": round(st.batch_mean, 2),
                    "batch_max": st.batch_max,
                    "durable_seq": st.durable_seq, "epoch": st.epoch,
                    "shipped_seq": st.shipped_seq,
                    "standby_lag_frames": st.standby_lag_frames}
        finally:
            cli.close()

    def __exit__(self, *exc):
        for proc in (self._standby_proc, self._proc):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10.0)
        return False


def run_fleet(addr: str, clients: int = 200, procs: int = 8,
              duration_s: float = 2.0) -> Dict:
    """Hammer `addr` with `clients` threads across `procs` processes.

    Returns per-class counts/rates/latency tails plus the aggregate
    ``rpc_per_s`` / ``rpc_p99_ms`` over one shared measurement window.
    """
    threads = max(1, math.ceil(clients / procs))
    ctx = mp.get_context("spawn")  # never fork a jax-initialized parent
    start_at = time.time() + _START_LEAD_S  # graftlint: disable=wall-clock-duration -- cross-process start barrier: spawn'd workers sleep until this shared wall-clock instant
    pipes, workers = [], []
    for p in range(procs):
        rx, tx = ctx.Pipe(duplex=False)
        w = ctx.Process(target=_fleet_worker,
                        args=(addr, p, threads, start_at, duration_s, tx),
                        daemon=True)
        w.start()
        tx.close()
        pipes.append(rx)
        workers.append(w)
    merged: Dict = {c: [] for c in VERB_CLASSES}
    done: Dict[str, int] = {c: 0 for c in VERB_CLASSES}
    errors = 0
    for rx in pipes:
        got = rx.recv()
        for c in VERB_CLASSES:
            merged[c] += got[c]
            done[c] += got["done"][c]
        errors += got["errors"]
    for w in workers:
        w.join(timeout=30.0)
        if w.is_alive():
            w.terminate()
    report: Dict = {"clients": procs * threads, "procs": procs,
                    "duration_s": duration_s}
    all_lat: List[float] = []
    for c in VERB_CLASSES:
        vals = sorted(merged[c])
        all_lat += vals
        report[c] = {
            "count": done[c],
            "rpc_per_s": round(done[c] / duration_s, 1),
            "p50_ms": round(_percentile(vals, 0.50), 3),
            "p99_ms": round(_percentile(vals, 0.99), 3),
        }
    all_lat.sort()
    report["rpc_total"] = sum(done.values())
    report["rpc_errors"] = errors
    report["rpc_per_s"] = round(sum(done.values()) / duration_s, 1)
    report["rpc_p99_ms"] = round(_percentile(all_lat, 0.99), 3)
    return report


#: bench phases, interleaved per round: per-frame-fsync baseline,
#: group-commit default, and group commit with a warm standby attached
#: (journal shipping must stay OFF the commit path — ISSUE 20)
_MODES = ("perframe", "grouped", "standby")


def fleet_bench(clients: int = 200, procs: int = 8,
                duration_s: float = 2.0, rounds: int = 2,
                fsync_floor_ms: float = 3.0) -> Dict:
    """A/B the per-frame-fsync baseline vs group commit, INTERLEAVED.

    Phases alternate per round (the same same-session interleave
    discipline as the kernel A/B probes — host load drifts), counts
    accumulate across rounds, and each phase gets a FRESH master so
    batch gauges attribute cleanly.  The headline ratio is
    journaled-verb throughput: grouped / per-frame.  The third phase
    re-runs the grouped shape with a warm STANDBY tailing the journal
    (no lease — pure mirror): acks gate on the local durable watermark
    only, so ``standby_retention`` must stay near 1.0 (shipping that
    re-serialized group commit would crater it) and the phase's journal
    gauges carry the shipped-seq/lag evidence.

    ``fsync_floor_ms`` pads each journal sync to the PRODUCTION storage
    regime (network-attached PD-class disks: 1-5ms per sync; this host's
    local NVMe fsyncs in ~0.1ms, which no real master journal rides).
    All phases pay the SAME floor per sync — group commit amortizes it,
    per-frame eats it per RPC — and the floor used is reported in every
    phase's journal gauges.  Pass 0 to measure bare local-disk fsync.
    """
    acc: Dict[str, Dict] = {}
    for mode in _MODES:
        acc[mode] = {c: {"count": 0} for c in VERB_CLASSES}
        acc[mode]["lat"] = {c: [] for c in VERB_CLASSES}
        acc[mode]["seconds"] = 0.0
        acc[mode]["errors"] = 0
        acc[mode]["journal"] = {}
    for _ in range(max(1, rounds)):
        for mode in _MODES:
            with FleetMaster(group_commit=(mode != "perframe"),
                             fsync_floor_ms=fsync_floor_ms,
                             standby=(mode == "standby")) as fm:
                got = run_fleet(fm.addr, clients=clients, procs=procs,
                                duration_s=duration_s)
                acc[mode]["seconds"] += duration_s
                for c in VERB_CLASSES:
                    acc[mode][c]["count"] += got[c]["count"]
                    acc[mode]["lat"][c].append(
                        (got[c]["p50_ms"], got[c]["p99_ms"]))
                acc[mode]["rpc_p99_ms"] = got["rpc_p99_ms"]
                acc[mode]["errors"] += got["rpc_errors"]
                acc[mode]["journal"] = fm.journal_stats()
    out: Dict = {"clients": clients, "procs": procs, "rounds": rounds,
                 "fsync_floor_ms": fsync_floor_ms}
    for mode in _MODES:
        secs = acc[mode]["seconds"] or 1.0
        summ = {"rpc_p99_ms": acc[mode]["rpc_p99_ms"],
                "rpc_errors": acc[mode]["errors"],
                "journal": acc[mode]["journal"]}
        total = 0
        for c in VERB_CLASSES:
            n = acc[mode][c]["count"]
            total += n
            tails = acc[mode]["lat"][c]
            summ[c] = {"rpc_per_s": round(n / secs, 1),
                       "p99_ms": round(max(t[1] for t in tails), 3)}
        summ["rpc_per_s"] = round(total / secs, 1)
        out[mode] = summ
    base = out["perframe"]["journaled"]["rpc_per_s"]
    grouped = out["grouped"]["journaled"]["rpc_per_s"]
    shipped = out["standby"]["journaled"]["rpc_per_s"]
    out["journaled_speedup"] = round(grouped / base, 2) if base else 0.0
    # the ISSUE 20 acceptance gauge: journaled rpc/s retained with a
    # standby attached (>= 0.9 of no-standby proves shipping is async)
    out["standby_retention"] = (round(shipped / grouped, 3)
                                if grouped else 0.0)
    out["standby_lag_frames"] = out["standby"]["journal"].get(
        "standby_lag_frames", -1)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m dlrover_wuqiong_tpu.fleet_bench`` — one JSON line.

    Runs in its own light process on purpose: the spawn'd client workers
    re-import THIS module's ``__main__``, which never touches jax — a
    heavy caller (bench.py) shells out here instead of spawning from its
    own jax-loaded interpreter.
    """
    import argparse
    import json

    p = argparse.ArgumentParser(
        prog="python -m dlrover_wuqiong_tpu.fleet_bench",
        description="synthetic-fleet control-plane RPC benchmark")
    p.add_argument("--clients", type=int, default=200)
    p.add_argument("--procs", type=int, default=8)
    p.add_argument("--duration-s", type=float, default=3.0)
    p.add_argument("--rounds", type=int, default=2)
    p.add_argument("--fsync-floor-ms", type=float, default=3.0,
                   help="per-sync storage-latency emulation (0 = bare "
                        "local fsync; default 3ms = PD-class disk)")
    args = p.parse_args(argv)
    out = fleet_bench(clients=args.clients, procs=args.procs,
                      duration_s=args.duration_s, rounds=args.rounds,
                      fsync_floor_ms=args.fsync_floor_ms)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Wire messages between agent and master.

Parity: reference `dlrover/python/common/grpc.py:129-468` message dataclasses
(`TaskRequest`, `Task`, `JoinRendezvousRequest`, `RendezvousState`, `NodeMeta`,
`HeartBeat`, `ParallelConfig`, ...) and `proto/elastic_training.proto:14-29`.
The TPU redesign replaces torch-elastic rank/world fields with the
`jax.distributed` contract: coordinator address + process id + device counts.
"""

from __future__ import annotations

from dataclasses import field
from typing import Dict, List, Optional

from .serialize import message


@message
class BaseRequest:
    node_id: int = -1
    node_type: str = ""


@message
class OkResponse:
    success: bool = True
    reason: str = ""


# ---------------------------------------------------------------- dataset / sharding


@message
class DatasetShardParams:
    batch_size: int = 0
    num_epochs: int = 1
    dataset_size: int = 0
    shuffle: bool = False
    num_minibatches_per_shard: int = 2
    dataset_name: str = ""
    task_type: str = "training"
    storage_type: str = ""


@message
class ShardConfig:
    start: int = 0
    end: int = 0
    indices: List[int] = field(default_factory=list)


@message
class TaskRequest:
    dataset_name: str = ""


@message
class Task:
    task_id: int = -1
    task_type: str = "none"
    shard: ShardConfig = field(default_factory=ShardConfig)
    dataset_name: str = ""


@message
class TaskResult:
    dataset_name: str = ""
    task_id: int = -1
    err_message: str = ""


@message
class DatasetTaskEnd:
    dataset_name: str = ""


@message
class ShardCheckpointRequest:
    dataset_name: str = ""


@message
class ShardCheckpoint:
    content: str = ""  # JSON state of the dataset splitter / task queues


# ---------------------------------------------------------------- rendezvous


@message
class JoinRendezvousRequest:
    node_id: int = -1
    node_rank: int = -1
    local_world_size: int = 1  # local accelerator/process count
    rdzv_name: str = ""
    node_ip: str = ""
    free_port: int = 0
    slice_id: str = ""  # TPU slice locality hint (DWT_SLICE_ID)


@message
class CommWorldRequest:
    node_id: int = -1
    rdzv_name: str = ""


@message
class RendezvousState:
    rdzv_round: int = 0
    group: int = 0
    # node_rank -> (node_id, local_world_size, node_ip, free_port)
    world: Dict[str, List] = field(default_factory=dict)
    coordinator_addr: str = ""
    complete: bool = False


@message
class WaitingNodeNumRequest:
    node_id: int = -1
    rdzv_name: str = ""


@message
class WaitingNodeNumResponse:
    waiting_num: int = 0


@message
class NetworkReadyRequest:
    pass


@message
class NetworkCheckResult:
    node_id: int = -1
    normal: bool = True
    elapsed_time: float = 0.0


@message
class StragglerExistRequest:
    pass


@message
class NetworkStatusResponse:
    nodes: List[int] = field(default_factory=list)
    reason: str = ""


# ---------------------------------------------------------------- node lifecycle


@message
class NodeMeta:
    node_type: str = "worker"
    node_id: int = -1
    node_rank: int = -1
    addr: str = ""
    cpu: float = 0.0
    memory_mb: float = 0.0
    accelerator_type: str = ""
    accelerator_num: int = 0


@message
class HeartBeat:
    node_id: int = -1
    timestamp: float = 0.0
    # piggyback diagnosis payloads (step progress, resource usage)
    global_step: int = 0
    resource: Dict[str, float] = field(default_factory=dict)


@message
class HeartbeatResponse:
    action: str = ""  # "", "restart", "stop"
    # for action="restart" fired by a loss-spike rollback: resume from the
    # newest committed checkpoint whose step PRECEDES this (-1 = latest)
    rollback_before_step: int = -1


@message
class NodeEventReport:
    node_id: int = -1
    node_type: str = "worker"
    event_type: str = ""
    reason: str = ""
    message: str = ""
    level: str = "info"


@message
class NodeFailure:
    node_id: int = -1
    restart_count: int = 0
    error_data: str = ""
    level: str = "process"


# ---------------------------------------------------------------- metrics


@message
class GlobalStep:
    step: int = 0
    timestamp: float = 0.0
    elapsed_time_per_step: float = 0.0


@message
class ResourceStats:
    node_id: int = -1
    cpu_percent: float = 0.0
    memory_mb: float = 0.0
    accelerator_stats: Dict[str, float] = field(default_factory=dict)


@message
class ModelInfo:
    num_params: int = 0
    num_layers: int = 0
    hidden_size: int = 0
    seq_len: int = 0
    flops_per_step: float = 0.0


@message
class CustomMetric:
    data: Dict[str, float] = field(default_factory=dict)


@message
class GoodputLedgerReport:
    """Cumulative per-node goodput ledger snapshot (telemetry/ledger.py).

    Totals are cumulative since trainer start, so the report is drop- and
    replay-safe over the BUFFERED verb class: the master keeps the latest
    snapshot per node and sums across nodes.  ``states`` keys come from
    ``LEDGER_STATES`` (add-only schema).
    """

    node_id: int = -1
    wall_s: float = 0.0
    states: Dict[str, float] = field(default_factory=dict)
    other_s: float = 0.0
    goodput_fraction: float = 0.0
    # send-time wall-clock stamp (cross-process — time.time()): the
    # degraded-mode buffer drains AFTER the frame that reconnected, so
    # without it a stale buffered snapshot would overwrite the fresh one
    # on the new master (latest-SENT must win, not latest-arrived)
    sent_at: float = 0.0


@message
class GoodputQuery:
    """Pull the job-level ledger aggregation (tools/goodput_report.py)."""

    pass


@message
class GoodputSummary:
    states: Dict[str, float] = field(default_factory=dict)
    wall_s: float = 0.0
    other_s: float = 0.0
    goodput_fraction: float = 0.0
    nodes: int = 0


@message
class PerfSnapshotReport:
    """Latest per-node perf-observatory snapshot (telemetry/perf.py).

    BUFFERED and NEVER journaled (pure telemetry — the goodput-report
    pattern): the ``snapshot`` dict carries cumulative counters plus the
    latest window, so drops and replays are harmless; the master keeps
    the latest-SENT per node.  ``snapshot`` keys are the ADD-ONLY
    ``PERF_SNAPSHOT_KEYS`` schema.
    """

    node_id: int = -1
    snapshot: Dict = field(default_factory=dict)
    # send-time wall stamp — same latest-SENT-wins hazard as
    # GoodputLedgerReport (the degraded buffer drains AFTER reconnect)
    sent_at: float = 0.0


@message
class PerfQuery:
    """Pull the job-level perf aggregation (tools/perf_report.py)."""

    pass


@message
class PerfSummary:
    """Per-node latest snapshots + job-level regression/retrace totals."""

    snapshots: Dict[str, Dict] = field(default_factory=dict)
    regressions: int = 0
    retraces: int = 0
    nodes: int = 0


@message
class JournalStatsQuery:
    """Pull the master's journal group-commit gauges (read-only, never
    journaled — the fleet bench and perf_probe poll it)."""

    pass


@message
class JournalStats:
    """Group-commit gauges for the master journal (master/journal.py).

    ``enabled`` is False on journal-less masters (standalone/test);
    ``group_commit`` is False when max_frames=1 (the per-frame-fsync
    baseline).  batch_mean/batch_max describe frames-per-fsync since
    the master started — the fleet bench's amortization evidence.
    """

    enabled: bool = False
    group_commit: bool = False
    max_frames: int = 0
    max_wait_ms: float = 0.0
    fsync_floor_ms: float = 0.0
    batches: int = 0
    frames: int = 0
    batch_mean: float = 0.0
    batch_max: int = 0
    durable_seq: int = 0
    epoch: int = 0
    # ADD-ONLY standby/failover gauges (ISSUE 20): shipped_seq is the
    # highest seq a standby holds or was served; standby_lag_frames is
    # durable_seq - shipped_seq (-1 = no standby ever fetched);
    # lease_epoch is the highest leadership-lease epoch this master has
    # journaled or observed — a revived primary compares it against its
    # own loaded epoch to self-fence instead of split-braining.
    shipped_seq: int = 0
    standby_lag_frames: int = -1
    lease_epoch: int = 0
    is_leader: bool = True


@message
class FetchJournalRequest:
    """Standby → primary: pull journal frames after ``from_seq``
    (POLLING class, read-only — NEVER journaled: shipping must not
    write to the log it ships).  The standby's own durable seq is the
    cursor, so a dropped response or torn batch tail is re-fetched
    idempotently — frames are immutable once durable."""

    node_id: int = -1
    from_seq: int = 0
    max_frames: int = 256


@message
class FetchJournalResponse:
    """One shipped batch, frames VERBATIM (raw encoded journal lines).

    ``snapshot`` is non-empty only when compaction truncated the
    requested range: the standby applies its state first, then the tail
    (which resumes at the compaction epoch marker).  ``durable_seq`` is
    the primary's watermark at serve time — the standby's lag signal;
    ``lease_epoch`` carries the primary's current leadership epoch so a
    tailing standby tracks it even between lease frames."""

    snapshot: bytes = b""
    snapshot_seq: int = 0
    frames: List[bytes] = field(default_factory=list)
    durable_seq: int = 0
    epoch: int = 0
    lease_epoch: int = 0


# ---------------------------------------------------------------- kv store


@message
class KVStoreSetRequest:
    key: str = ""
    value: bytes = b""


@message
class KVStoreGetRequest:
    key: str = ""


@message
class KVStoreMultiGetRequest:
    keys: List[str] = field(default_factory=list)


@message
class KVStoreAddRequest:
    key: str = ""
    amount: int = 1


@message
class KVStoreResponse:
    found: bool = False
    value: bytes = b""
    values: List[bytes] = field(default_factory=list)
    num: int = 0


# ---------------------------------------------------------------- parallelism config


@message
class ParallelConfig:
    """Tuned parallel/runtime config pushed master→agent→trainer.

    Parity: reference grpc.py ParallelConfig (dataloader + ckpt tuning); redesigned
    to carry mesh shape for the JAX strategy layer.
    """

    dataloader_batch_size: int = 0
    dataloader_num_workers: int = 0
    ckpt_interval_steps: int = 0
    mesh_shape: Dict[str, int] = field(default_factory=dict)
    restart_version: int = 0


@message
class ParallelConfigRequest:
    node_id: int = -1


# ---------------------------------------------------------------- brain


@message
class BrainPersistMetrics:
    """Parity: brain.proto persist_metrics."""

    job_name: str = ""
    node_type: str = "worker"
    cpu: float = 0.0
    memory_mb: float = 0.0


@message
class BrainOptimizeRequest:
    """Parity: brain.proto optimize."""

    job_name: str = ""
    node_type: str = "worker"
    event: str = ""  # "" | "oom" — selects the OOM-bump algorithm


@message
class BrainOptimizeResponse:
    cpu: float = 0.0
    memory_mb: float = 0.0
    stage: str = ""
    algorithm: str = ""  # which registered optalgorithm produced the plan


@message
class BrainJobMetricsRequest:
    """Parity: brain.proto get_job_metrics."""

    job_name: str = ""
    node_type: str = "worker"


@message
class BrainJobMetricsResponse:
    samples: str = ""  # JSON list of usage samples


# ---------------------------------------------------------------- adaptive policy


@message
class PolicyDecision:
    """One adaptive fault-tolerance decision (brain/policy.py).

    Four knobs per the Chameleon/PHOENIX loop: checkpoint cadence,
    replica count, fused-K, and recovery route/tier.  ADD-ONLY schema
    (tests/test_telemetry.py pins the field set).  ``issued_at`` is a
    persisted cross-process timestamp, hence wall clock.
    """

    decision_id: int = 0
    ckpt_interval_steps: int = 0   # 0 = no change
    replica_count: int = -1        # -1 = no change
    fused_steps: int = 0           # 0 = no change
    recovery_route: str = ""       # "" | "warm" | "cold"
    preferred_tier: str = ""       # "" | "shm" | "replica" | "storage"
    preempt_rate_per_hr: float = 0.0
    reason: str = ""
    issued_at: float = 0.0


@message
class PolicyDecisionReport:
    """Agent/operator-submitted decision (journaled + idem, like KV adds)."""

    node_id: int = -1
    decision: PolicyDecision = field(default_factory=PolicyDecision)


@message
class PolicyDecisionAck:
    decision_id: int = 0
    applied: bool = True
    reason: str = ""


@message
class PolicyStateRequest:
    """Pull the current (latest) decision for this job."""

    node_id: int = -1


@message
class PolicyHistoryRequest:
    """Pull the full decision history (JSON list, journal-backed)."""

    node_id: int = -1


@message
class PolicyHistory:
    content: str = ""  # JSON list of decision dicts, oldest first


# ---------------------------------------------------------------- diagnosis


@message
class DiagnosisReport:
    node_id: int = -1
    payload_type: str = ""  # "step", "stack", "chip_metrics"
    content: str = ""
    timestamp: float = 0.0


@message
class DiagnosisAction:
    action: str = ""  # "", "restart_worker", "relaunch_node", "rollback"
    reason: str = ""
    node_id: int = -1
    # spike-onset step for "rollback" (ADVICE r4: the latest committed
    # checkpoint can postdate spike onset — the restart must target the
    # newest committed step BEFORE this); -1 = unknown/latest
    step: int = -1


# ---------------------------------------------------------------- serving


@message
class ServeRequest:
    """One inference request (serving/).  ADD-ONLY schema, pinned by
    tests/test_serving.py.

    ``prompt`` is the token-id list (the control plane carries ids, not
    text — tokenization is a client concern).  ``seed`` feeds the
    per-request PRNG key, which makes sampled tokens independent of the
    batch the request happens to share slots with (the continuous-
    batching equivalence invariant).  ``submitted_at`` is a cross-process
    wall-clock stamp.
    """

    request_id: str = ""
    prompt: List[int] = field(default_factory=list)
    max_new_tokens: int = 16
    temperature: float = 1.0
    seed: int = 0
    deadline_s: float = 0.0      # 0 = no deadline
    submitted_at: float = 0.0


@message
class ServeSubmitRequest:
    """Client → master: enqueue requests (journaled + idem)."""

    node_id: int = -1
    requests: List[ServeRequest] = field(default_factory=list)


@message
class ServeSubmitAck:
    accepted: int = 0
    queue_depth: int = 0


@message
class ServeLeaseRequest:
    """Decode worker → master: lease up to ``max_requests`` pending
    requests (journaled + idem — a lease moves queue state, and replay
    must re-assign the same requests to the same worker)."""

    node_id: int = -1
    max_requests: int = 1


@message
class ServeLease:
    requests: List[ServeRequest] = field(default_factory=list)


@message
class ServeResult:
    """Completed request: generated token ids (prompt excluded)."""

    request_id: str = ""
    tokens: List[int] = field(default_factory=list)
    finish_reason: str = "length"  # "length" | "deadline" | "error"
    latency_s: float = 0.0
    ttft_s: float = 0.0


@message
class ServeResultReport:
    """Worker → master: durable result hand-off (journaled + idem)."""

    node_id: int = -1
    results: List[ServeResult] = field(default_factory=list)


@message
class ServeResultQuery:
    """Client → master: poll for finished results (removes returned
    entries — but the poll itself is idempotent per request_id set)."""

    request_ids: List[str] = field(default_factory=list)


@message
class ServeResultResponse:
    results: List[ServeResult] = field(default_factory=list)
    pending: int = 0


@message
class ServeStatsReport:
    """Cumulative per-worker serving ledger snapshot (BUFFERED, like
    GoodputLedgerReport: latest-SENT-wins per node via ``sent_at``)."""

    node_id: int = -1
    wall_s: float = 0.0
    states: Dict[str, float] = field(default_factory=dict)
    counters: Dict[str, int] = field(default_factory=dict)
    active_slots: int = 0
    p50_ms: float = 0.0
    p99_ms: float = 0.0
    ttft_p50_ms: float = 0.0
    ttft_p99_ms: float = 0.0
    sent_at: float = 0.0


@message
class ServeStatsQuery:
    """Pull the job-level serving summary (tools/serve_report.py)."""

    pass


@message
class ServeSummary:
    queue_depth: int = 0
    leased: int = 0
    done: int = 0
    submitted_total: int = 0
    requeued_total: int = 0
    done_total: int = 0
    workers: int = 0
    active_slots: int = 0
    counters: Dict[str, int] = field(default_factory=dict)
    states: Dict[str, float] = field(default_factory=dict)
    p50_ms: float = 0.0
    p99_ms: float = 0.0
    ttft_p50_ms: float = 0.0
    ttft_p99_ms: float = 0.0
    rps: float = 0.0


# ------------------------------------------------------------ incident timeline


@message
class TimelineQuery:
    """Client → master: assemble the incident timeline (POLLING class,
    read-only — never journaled).  The master answers from its own disk
    artifacts (journal dir + the optional ``ckpt_dir`` flight-dump root),
    so the offline `tools/incident_report.py` reconstruction from the
    same artifacts is byte-equal to ``TimelineResponse.content``.
    ADD-ONLY family, pinned by tests/test_timeline.py."""

    node_id: int = -1
    ckpt_dir: str = ""
    # extra journal dirs to merge in (epoch, seq) order — after a
    # failover the incident spans BOTH masters' journals; the answering
    # master puts its own dir first, then these, and the offline CLI
    # passing the same ordered list reproduces the bytes exactly
    journal_dirs: List[str] = field(default_factory=list)


@message
class TimelineResponse:
    """``content`` is the canonical incident JSON
    (telemetry/timeline.py incident_json: events + narrative + counts);
    ``events`` is the merged stream length for a cheap sanity check."""

    content: str = ""
    events: int = 0


# --------------------------------------------------------- mesh transition


@message
class MeshTransitionQuery:
    """Client → master: poll the active hot-swap mesh transition
    (POLLING class, read-only — never journaled).  Survivors drive their
    phase work off this state at FUSION BOUNDARIES only.  ADD-ONLY
    family, pinned by tests/test_mesh_transition.py."""

    node_id: int = -1


@message
class MeshTransitionState:
    """The journaled mesh_transition state machine, as clients see it.

    ``transition_id`` 0 is the no-transition sentinel.  ``phase`` walks
    propose → fence → hydrate → cutover → release → done (or aborted);
    every advance is a journal frame BEFORE it becomes visible here, so
    a master crash mid-transition replays to the same phase.
    ``fence_epoch`` is the bumped rendezvous round the post-cutover
    world carries — survivors adopt it at the fence phase and the
    rendezvous holds formation until release, so a replacement node
    joining mid-transition can never race the fenced cutover.
    ``started_at`` is a persisted cross-process timestamp (wall clock).
    """

    transition_id: int = 0
    phase: str = ""
    dead_node_id: int = -1
    dead_rank: int = -1
    survivors: List[int] = field(default_factory=list)
    rdzv_round: int = -1   # round of the world being transitioned FROM
    fence_epoch: int = 0   # bumped round the post-cutover world carries
    started_at: float = 0.0
    reason: str = ""


@message
class MeshTransitionPhaseReport:
    """Survivor → master: this node finished ``phase``'s worker-side
    work (journaled + idem — phase acks advance the fenced state
    machine, so a retry crossing a master restart must replay the
    recorded ack, never double-count).  ``ok=False`` aborts the
    transition (the job falls back to the classic restart route)."""

    node_id: int = -1
    transition_id: int = 0
    phase: str = ""
    ok: bool = True
    detail: str = ""

"""Checkpoint storage abstraction with a class registry.

Parity: reference `dlrover/python/common/storage.py` (CheckpointStorage,
PosixDiskStorage, get_checkpoint_storage, 328 LoC).
"""

from __future__ import annotations

import os
import shutil
import threading
from abc import ABC, abstractmethod
from typing import Dict, Optional, Type


class CheckpointStorage(ABC):
    """Byte/file-level storage interface used by the async checkpoint saver."""

    @abstractmethod
    def write(self, content, path: str):  # bytes or str
        ...

    @abstractmethod
    def read(self, path: str, mode: str = "rb"):
        ...

    @abstractmethod
    def safe_makedirs(self, path: str):
        ...

    @abstractmethod
    def safe_remove(self, path: str):
        ...

    @abstractmethod
    def exists(self, path: str) -> bool:
        ...

    @abstractmethod
    def listdir(self, path: str):
        ...

    def commit(self, step: int, success: bool):
        """Hook called after all shards of a step have been persisted."""

    def get_class_meta(self) -> Dict:
        return {"class_name": type(self).__name__, "kwargs": {}}


class PosixDiskStorage(CheckpointStorage):
    """Local disk / NFS-mounted POSIX storage."""

    def __init__(self, **kwargs):
        self._lock = threading.Lock()

    def write(self, content, path: str):
        mode = "wb" if isinstance(content, (bytes, bytearray, memoryview)) else "w"
        tmp = f"{path}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, mode) as f:
            f.write(content)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def write_fileobj(self, fileobj, path: str, length: int):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            remaining = length
            while remaining > 0:
                chunk = fileobj.read(min(remaining, 64 << 20))
                if not chunk:
                    break
                f.write(chunk)
                remaining -= len(chunk)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def read(self, path: str, mode: str = "rb"):
        if not os.path.exists(path):
            return None
        with open(path, mode) as f:
            return f.read()

    def safe_makedirs(self, path: str):
        os.makedirs(path, exist_ok=True)

    def safe_remove(self, path: str):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            try:
                os.remove(path)
            except OSError:
                pass

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str):
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))


class ObjectStoreStorage(CheckpointStorage):
    """Cloud object-store backend (gs:// / s3://) via `etils.epath`.

    A multi-host TPU job's checkpoints live in GCS, not on local disk —
    this backend gives the flash-ckpt saver the same interface there.
    epath routes to the appropriate filesystem implementation; hosts
    without the cloud filesystem deps fail at use-time with the
    underlying error (the posix paths keep working through epath too).
    """

    def __init__(self, **kwargs):
        from etils import epath  # lazy: orbax dependency, always present

        self._epath = epath

    def _p(self, path: str):
        return self._epath.Path(path)

    def write(self, content, path: str):
        """Atomic publish on every backend: object stores already commit
        whole objects atomically, but epath on a POSIX path writes in
        place — a crash mid-write would leave a torn file where the
        checkpoint trust boundary expects manifests/trackers to be
        whole-or-absent.  Write a sibling tmp then rename."""
        p = self._p(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = self._p(f"{path}.tmp.{os.getpid()}")
        if isinstance(content, str):
            tmp.write_text(content)
        else:
            tmp.write_bytes(bytes(content))
        try:
            tmp.rename(p)
        except OSError:
            # backends whose rename cannot replace: fall back to the
            # object store's own atomic whole-object write
            if isinstance(content, str):
                p.write_text(content)
            else:
                p.write_bytes(bytes(content))
            try:
                tmp.unlink()
            except OSError:
                pass

    def write_fileobj(self, fileobj, path: str, length: int):
        p = self._p(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        with p.open("wb") as f:
            remaining = length
            while remaining > 0:
                chunk = fileobj.read(min(1 << 20, remaining))
                if not chunk:
                    break
                f.write(chunk)
                remaining -= len(chunk)

    def read(self, path: str, mode: str = "rb"):
        p = self._p(path)
        try:
            return p.read_text() if "b" not in mode else p.read_bytes()
        except (FileNotFoundError, OSError):
            return None

    def safe_makedirs(self, path: str):
        self._p(path).mkdir(parents=True, exist_ok=True)

    def safe_remove(self, path: str):
        p = self._p(path)
        try:
            if p.is_dir():
                p.rmtree()
            elif p.exists():
                p.unlink()
        except OSError:
            pass

    def exists(self, path: str) -> bool:
        return self._p(path).exists()

    def listdir(self, path: str):
        p = self._p(path)
        if not p.exists():
            return []
        try:
            return sorted(c.name for c in p.iterdir())
        except (NotADirectoryError, OSError):
            return []

    def commit(self, step: int, success: bool):
        pass

    def get_class_meta(self) -> Dict:
        return {"class_name": type(self).__name__, "kwargs": {}}


_STORAGE_REGISTRY: Dict[str, Type[CheckpointStorage]] = {
    "PosixDiskStorage": PosixDiskStorage,
    "ObjectStoreStorage": ObjectStoreStorage,
}

_OBJECT_SCHEMES = ("gs://", "s3://", "az://")


def register_storage(cls: Type[CheckpointStorage]):
    _STORAGE_REGISTRY[cls.__name__] = cls
    return cls


def get_checkpoint_storage(meta: Optional[Dict] = None,
                           path_hint: str = "") -> CheckpointStorage:
    """Resolve a backend — by explicit meta, or by the target path's scheme
    (gs://... → object store)."""
    if not meta:
        if path_hint.startswith(_OBJECT_SCHEMES):
            return ObjectStoreStorage()
        return PosixDiskStorage()
    cls = _STORAGE_REGISTRY.get(meta.get("class_name", "PosixDiskStorage"),
                                PosixDiskStorage)
    return cls(**meta.get("kwargs", {}))

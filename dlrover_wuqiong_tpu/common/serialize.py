"""Typed message serialization.

Parity: reference `dlrover/python/common/grpc.py` serializes dataclasses with pickle
inside a 2-rpc gRPC envelope (insecure-by-design internal protocol).  Here messages
are dataclasses registered by name and encoded as JSON — same ergonomics, no
arbitrary-object deserialization.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Type

_MESSAGE_REGISTRY: Dict[str, Type] = {}


def message(cls):
    """Class decorator: make a dataclass a wire-serializable message."""
    cls = dataclasses.dataclass(cls)
    _MESSAGE_REGISTRY[cls.__name__] = cls
    return cls


def _encode_value(v: Any) -> Any:
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return {
            "__msg__": type(v).__name__,
            "fields": {
                f.name: _encode_value(getattr(v, f.name))
                for f in dataclasses.fields(v)
            },
        }
    if isinstance(v, dict):
        return {str(k): _encode_value(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_encode_value(x) for x in v]
    if isinstance(v, bytes):
        return {"__bytes__": v.hex()}
    return v


def _decode_value(v: Any) -> Any:
    if isinstance(v, dict):
        if "__msg__" in v:
            cls = _MESSAGE_REGISTRY.get(v["__msg__"])
            if cls is None:
                raise ValueError(f"unknown message type {v['__msg__']}")
            kwargs = {k: _decode_value(x) for k, x in v.get("fields", {}).items()}
            known = {f.name for f in dataclasses.fields(cls)}
            return cls(**{k: x for k, x in kwargs.items() if k in known})
        if "__bytes__" in v:
            return bytes.fromhex(v["__bytes__"])
        return {k: _decode_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode_value(x) for x in v]
    return v


def dumps(obj: Any) -> bytes:
    return json.dumps(_encode_value(obj), separators=(",", ":")).encode("utf-8")


def loads(data: bytes) -> Any:
    return _decode_value(json.loads(data.decode("utf-8")))

"""Shared plumbing for the one-line-JSON report CLIs under tools/.

Parity: no reference counterpart — the reference's operator surface is
`kubectl logs` + dashboards; this repo's contract (BASELINE.md / driver)
is ONE parseable JSON line per tool on stdout, ALWAYS.

Factored from the previously copy-pasted mains of
tools/goodput_report.py, tools/policy_report.py and
tools/serve_report.py (tools/incident_report.py builds on it directly).
The contract every tool shares:

- ``-h``/``--help`` prints the module docstring to STDERR, rc=0 (stdout
  stays machine-parseable);
- offline source flags (e.g. ``--flight``, ``--journal``) win over the
  live master RPC;
- a live query with no address (``--addr`` / $DWT_MASTER_ADDR) is rc=2
  with an ``error`` field;
- any failure is rc=1 with an ``error`` field — never a raw traceback
  on stdout;
- success prints exactly one ``json.dumps(report)`` line, rc=0.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Callable, Dict, Optional, Sequence


def parse_value_flags(argv: Sequence[str], value_flags: Sequence[str]
                      ) -> Dict[str, Optional[str]]:
    """``--flag VALUE`` pairs (unknown args are ignored, matching the
    historical tolerant manual loops); ``-h``/``--help`` maps to itself."""
    vals: Dict[str, Optional[str]] = {}
    it = iter(argv)
    for a in it:
        if a in ("-h", "--help"):
            vals["--help"] = a
        elif a in value_flags:
            vals[a] = next(it, None)
    return vals


def run_report(argv: Optional[Sequence[str]], doc: str,
               offline: Callable[[Dict[str, Optional[str]]],
                                 Optional[dict]],
               live: Callable[[str, Dict[str, Optional[str]]], dict],
               no_addr_error: str,
               value_flags: Sequence[str] = (),
               addr_env: str = "DWT_MASTER_ADDR") -> int:
    """One report CLI run under the shared rc/error contract.

    ``offline(vals)`` returns the report when its flags were given, or
    None to fall through to ``live(addr, vals)``.
    """
    argv = argv if argv is not None else sys.argv[1:]
    flags = tuple(value_flags) + ("--addr",)
    vals = parse_value_flags(argv, flags)
    if "--help" in vals:
        print(doc, file=sys.stderr)
        return 0
    try:
        report = offline(vals)
        if report is None:
            addr = vals.get("--addr") or os.getenv(addr_env, "")
            if not addr:
                print(json.dumps({"error": no_addr_error}))
                return 2
            report = live(addr, vals)
    except Exception as e:  # noqa: BLE001 — the JSON contract beats purity
        print(json.dumps({"error": repr(e)[:500]}))
        return 1
    print(json.dumps(report))
    return 0

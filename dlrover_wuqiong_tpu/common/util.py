"""Small shared helpers."""

from __future__ import annotations


def is_oom_error(exc: BaseException) -> bool:
    """True when `exc` is an accelerator out-of-memory failure.

    XLA surfaces OOM as XlaRuntimeError with a RESOURCE_EXHAUSTED status (or
    an "out of memory"-style message on some backends); there is no typed
    exception to catch, so callers that want a fallback path share this
    single string heuristic.
    """
    r = repr(exc)
    return "RESOURCE_EXHAUSTED" in r or "emory" in r

"""Small shared helpers."""

from __future__ import annotations


def is_oom_error(exc: BaseException) -> bool:
    """True when `exc` is an accelerator out-of-memory failure.

    XLA surfaces OOM as XlaRuntimeError with a RESOURCE_EXHAUSTED status;
    there is no typed exception to catch, so callers that want a fallback
    path share this heuristic.  Deliberately narrow: a host `MemoryError`
    or an arbitrary message containing "memory" is NOT a device OOM and
    must not trigger device-resource fallbacks (VERDICT r2 weak #7)."""
    name = type(exc).__name__
    if name != "XlaRuntimeError":
        return False
    text = str(exc)
    return "RESOURCE_EXHAUSTED" in text or "out of memory" in text.lower()

"""Small shared helpers."""

from __future__ import annotations

from typing import Any


def _first_sum(leaves):
    import jax.numpy as jnp

    total = jnp.float32(0.0)
    for a in leaves:
        total = total + jnp.float32(jnp.ravel(a)[0])
    return total


_sync_jit = None


def sync_tree(tree: Any) -> float:
    """Synchronize EVERY device-array leaf of `tree` with one host readback.

    `jax.block_until_ready` is a NO-OP over the axon TPU tunnel
    (CLAUDE.md), and reading back a single leaf only proves THAT leaf's
    transfer/compute finished — the round-4 verdict flagged two advertised
    metrics (`last_sync_s`, `restore_s`) as lower bounds for exactly this
    reason.  The sum over per-leaf first elements depends on every leaf;
    the single `float()` readback then waits for the whole tree.  The
    reduction runs as ONE jitted dispatch (per-leaf eager ops would pay
    the ~5-8ms tunnel dispatch cost hundreds of times and inflate the
    metric the caller is measuring).  The first call per tree structure
    compiles — callers timing a window should warm the helper on a
    same-structure tree first (bench.py does).

    Returns the (meaningless) sum so callers can assert it is finite if
    they want an extra liveness check.
    """
    global _sync_jit
    import jax
    import numpy as np

    leaves = [x for x in jax.tree.leaves(tree) if np.size(x) > 0]
    if not leaves:
        return 0.0
    if _sync_jit is None:
        _sync_jit = jax.jit(_first_sum)
    return float(_sync_jit(leaves))


def is_oom_error(exc: BaseException) -> bool:
    """True when `exc` is an accelerator out-of-memory failure.

    XLA surfaces OOM as XlaRuntimeError with a RESOURCE_EXHAUSTED status;
    there is no typed exception to catch, so callers that want a fallback
    path share this heuristic.  Deliberately narrow: a host `MemoryError`
    or an arbitrary message containing "memory" is NOT a device OOM and
    must not trigger device-resource fallbacks (VERDICT r2 weak #7)."""
    name = type(exc).__name__
    if name != "XlaRuntimeError":
        return False
    text = str(exc)
    return "RESOURCE_EXHAUSTED" in text or "out of memory" in text.lower()

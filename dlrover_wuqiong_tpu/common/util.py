"""Small shared helpers for the axon-tunnel measurement rules.

Parity: no single reference counterpart — the reference assumes local
CUDA devices where `torch.cuda.synchronize()` is truthful; over the axon
TPU tunnel `block_until_ready()` is a NO-OP (CLAUDE.md), so every timing
or liveness probe in this repo funnels through these helpers instead:
`sync_tree` (one-dispatch whole-tree host readback, bench.py:1 and the
checkpoint timers), `measure_h2d_gbps` (the resolve-time slow-link probe
behind auto/accelerate.py:330 offload warnings), and `is_oom_error`
(typed RESOURCE_EXHAUSTED detection shared by bench.py fallbacks and
auto/engine.py candidate scoring).
"""

from __future__ import annotations

from typing import Any


def _first_sum(leaves):
    import jax.numpy as jnp

    total = jnp.float32(0.0)
    for a in leaves:
        total = total + jnp.float32(jnp.ravel(a)[0])
    return total


_sync_jit = None


def sync_tree(tree: Any) -> float:
    """Synchronize EVERY device-array leaf of `tree` with one host readback.

    `jax.block_until_ready` is a NO-OP over the axon TPU tunnel
    (CLAUDE.md), and reading back a single leaf only proves THAT leaf's
    transfer/compute finished — the round-4 verdict flagged two advertised
    metrics (`last_sync_s`, `restore_s`) as lower bounds for exactly this
    reason.  The sum over per-leaf first elements depends on every leaf;
    the single `float()` readback then waits for the whole tree.  The
    reduction runs as ONE jitted dispatch (per-leaf eager ops would pay
    the ~5-8ms tunnel dispatch cost hundreds of times and inflate the
    metric the caller is measuring).  The first call per tree structure
    compiles — callers timing a window should warm the helper on a
    same-structure tree first (bench.py does).

    Returns the (meaningless) sum so callers can assert it is finite if
    they want an extra liveness check.
    """
    global _sync_jit
    import jax
    import numpy as np

    leaves = [x for x in jax.tree.leaves(tree) if np.size(x) > 0]
    if not leaves:
        return 0.0
    if _sync_jit is None:
        _sync_jit = jax.jit(_first_sum)
    return float(_sync_jit(leaves))


_h2d_gbps_cache: dict = {}


def measure_h2d_gbps(device=None, size_mb: int = 32,
                     force: bool = False) -> float:
    """Measured host->device bandwidth in GB/s, cached per device kind.

    One ~32MB transfer, synced by host readback (block_until_ready is a
    no-op over the axon tunnel).  DWT_H2D_GBPS overrides the measurement
    (tests fake a slow link; operators can pin a known value to skip the
    probe).  Used by auto_accelerate to warn when an offload strategy is
    selected on a link too slow to hide the traffic (round-4 verdict
    weak #5: offload_dots silently delivered 3.4x step time through a
    21-73 MB/s tunnel)."""
    import os
    import time

    env = os.getenv("DWT_H2D_GBPS")
    if env:
        try:
            v = float(env)
            if v > 0:  # non-positive would crash downstream estimates
                return v
        except ValueError:
            pass
    import jax
    import jax.numpy as jnp
    import numpy as np

    device = device or jax.devices()[0]
    key = getattr(device, "device_kind", str(device))
    if not force and key in _h2d_gbps_cache:
        return _h2d_gbps_cache[key]
    nbytes = size_mb << 20
    host = np.ones(nbytes // 4, np.float32)
    # warm (allocator, tunnel setup), then measure
    x = jax.device_put(host, device)
    float(jnp.float32(x[0]))
    t0 = time.perf_counter()
    x = jax.device_put(host, device)
    float(jnp.float32(x[0]))
    dt = max(time.perf_counter() - t0, 1e-9)
    gbps = nbytes / dt / 1e9
    _h2d_gbps_cache[key] = gbps
    return gbps


def is_oom_error(exc: BaseException) -> bool:
    """True when `exc` is an accelerator out-of-memory failure.

    XLA surfaces OOM as XlaRuntimeError with a RESOURCE_EXHAUSTED status;
    there is no typed exception to catch, so callers that want a fallback
    path share this heuristic.  Deliberately narrow: a host `MemoryError`
    or an arbitrary message containing "memory" is NOT a device OOM and
    must not trigger device-resource fallbacks (VERDICT r2 weak #7)."""
    name = type(exc).__name__
    if name != "XlaRuntimeError":
        return False
    text = str(exc)
    return "RESOURCE_EXHAUSTED" in text or "out of memory" in text.lower()

"""Small shared helpers for the axon-tunnel measurement rules.

Parity: no single reference counterpart — the reference assumes local
CUDA devices where `torch.cuda.synchronize()` is truthful; over the axon
TPU tunnel `block_until_ready()` is a NO-OP (CLAUDE.md), so every timing
or liveness probe in this repo funnels through these helpers instead:
`sync_tree` (one-dispatch whole-tree host readback, bench.py:1 and the
checkpoint timers), `measure_h2d_gbps` (the resolve-time slow-link probe
behind auto/accelerate.py:330 offload warnings), and `is_oom_error`
(typed RESOURCE_EXHAUSTED detection shared by bench.py fallbacks and
auto/engine.py candidate scoring).
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Optional, Tuple, Type


def retry_call(fn: Callable[[], Any], *,
               attempts: Optional[int] = 3,
               deadline_s: Optional[float] = None,
               base_delay_s: float = 0.1,
               max_delay_s: float = 2.0,
               jitter: float = 0.25,
               retry_on: Tuple[Type[BaseException], ...] = (Exception,),
               on_retry: Optional[Callable] = None,
               label: Optional[str] = None,
               sleep: Callable[[float], None] = time.sleep) -> Any:
    """THE retry policy of this repo: bounded exponential backoff + jitter.

    Parity: reference `dlrover/python/common/grpc.py` `retry_grpc_request`
    decorator — generalized so every control-plane touch (RpcClient,
    MasterClient degraded-mode probes, kv_store_wait polling,
    multi_process IPC dials, checkpoint replica fetches, bench.py backend
    init) shares ONE policy instead of five hand-rolled loops.

    `fn` is called with no arguments.  A raised exception that is an
    instance of `retry_on` is retried until either `attempts` total calls
    were made (None = unbounded) or `deadline_s` wall-clock seconds have
    elapsed since entry (None = unbounded); the last exception is then
    re-raised.  Exceptions outside `retry_on` propagate immediately
    (e.g. RpcError from a master that ANSWERED with an error must never
    be retried — the verb may not be idempotent).

    Backoff for retry i (0-based) is `min(max_delay_s, base_delay_s*2**i)`
    scaled by a symmetric jitter factor in [1-jitter, 1+jitter] — jitter
    keeps a fleet of workers hammering a restarting master from
    synchronizing into retry storms.  The delay is additionally clipped
    to the remaining deadline.  `on_retry(n_retries, exc, delay_s)` fires
    before each sleep — callers use it for logging and for tearing down
    poisoned state (bench.py drops the dead backend client there).

    `label` (e.g. the rpc verb) opens a ``retry:<label>`` trace span
    covering the whole bounded loop, with the retry count in its attrs
    (telemetry/spans.py) — per-RPC attribution without a second timing
    path.  None (the default) keeps the call untraced and zero-cost.
    """
    if label is not None:
        from ..telemetry import spans as _spans

        with _spans.span(f"retry:{label}") as rec:
            return _retry_loop(fn, attempts, deadline_s, base_delay_s,
                               max_delay_s, jitter, retry_on, on_retry,
                               sleep, rec)
    return _retry_loop(fn, attempts, deadline_s, base_delay_s, max_delay_s,
                       jitter, retry_on, on_retry, sleep, None)


def _retry_loop(fn, attempts, deadline_s, base_delay_s, max_delay_s,
                jitter, retry_on, on_retry, sleep, span_rec) -> Any:
    if attempts is None and deadline_s is None:
        attempts = 3  # both unbounded would spin forever on a hard fault
    start = time.monotonic()
    i = 0
    while True:
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by design
            if attempts is not None and i + 1 >= attempts:
                raise
            delay = min(max_delay_s, base_delay_s * (2.0 ** i))
            if jitter > 0:
                delay *= 1.0 + jitter * (2.0 * random.random() - 1.0)
            if deadline_s is not None:
                remaining = deadline_s - (time.monotonic() - start)
                if remaining <= 0:
                    raise
                delay = min(delay, remaining)
            i += 1
            if span_rec is not None:
                span_rec["attrs"]["retries"] = i
            if on_retry is not None:
                on_retry(i, e, delay)
            if delay > 0:
                sleep(delay)


def _first_sum(leaves):
    import jax.numpy as jnp

    total = jnp.float32(0.0)
    for a in leaves:
        total = total + jnp.float32(jnp.ravel(a)[0])
    return total


_sync_jit = None


def sync_tree(tree: Any) -> float:
    """Synchronize EVERY device-array leaf of `tree` with one host readback.

    `jax.block_until_ready` is a NO-OP over the axon TPU tunnel
    (CLAUDE.md), and reading back a single leaf only proves THAT leaf's
    transfer/compute finished — the round-4 verdict flagged two advertised
    metrics (`last_sync_s`, `restore_s`) as lower bounds for exactly this
    reason.  The sum over per-leaf first elements depends on every leaf;
    the single `float()` readback then waits for the whole tree.  The
    reduction runs as ONE jitted dispatch (per-leaf eager ops would pay
    the ~5-8ms tunnel dispatch cost hundreds of times and inflate the
    metric the caller is measuring).  The first call per tree structure
    compiles — callers timing a window should warm the helper on a
    same-structure tree first (bench.py does).

    Returns the (meaningless) sum so callers can assert it is finite if
    they want an extra liveness check.
    """
    global _sync_jit
    import jax
    import numpy as np

    leaves = [x for x in jax.tree.leaves(tree) if np.size(x) > 0]
    if not leaves:
        return 0.0
    if _sync_jit is None:
        _sync_jit = jax.jit(_first_sum)
    return float(_sync_jit(leaves))


_h2d_gbps_cache: dict = {}


def measure_h2d_gbps(device=None, size_mb: int = 32,
                     force: bool = False) -> float:
    """Measured host->device bandwidth in GB/s, cached per device kind.

    One ~32MB transfer, synced by host readback (block_until_ready is a
    no-op over the axon tunnel).  DWT_H2D_GBPS overrides the measurement
    (tests fake a slow link; operators can pin a known value to skip the
    probe).  Used by auto_accelerate to warn when an offload strategy is
    selected on a link too slow to hide the traffic (round-4 verdict
    weak #5: offload_dots silently delivered 3.4x step time through a
    21-73 MB/s tunnel)."""
    import os
    import time

    env = os.getenv("DWT_H2D_GBPS")
    if env:
        try:
            v = float(env)
            if v > 0:  # non-positive would crash downstream estimates
                return v
        except ValueError:
            pass
    import jax
    import jax.numpy as jnp
    import numpy as np

    device = device or jax.devices()[0]
    key = getattr(device, "device_kind", str(device))
    if not force and key in _h2d_gbps_cache:
        return _h2d_gbps_cache[key]
    nbytes = size_mb << 20
    host = np.ones(nbytes // 4, np.float32)
    # warm (allocator, tunnel setup), then measure
    x = jax.device_put(host, device)
    float(jnp.float32(x[0]))
    t0 = time.perf_counter()
    x = jax.device_put(host, device)
    float(jnp.float32(x[0]))
    dt = max(time.perf_counter() - t0, 1e-9)
    gbps = nbytes / dt / 1e9
    _h2d_gbps_cache[key] = gbps
    return gbps


def has_pinned_host_memory() -> bool:
    """True when the default device can address `pinned_host` memory.

    jax 0.4.37's CPU backend only exposes `unpinned_host`, so the
    optimizer_offload strategy (moments parked in pinned_host,
    trainer/train_step.py) cannot even build its shardings there —
    its tests skip with a version reason instead of failing."""
    import jax

    try:
        return any(getattr(m, "kind", "") == "pinned_host"
                   for m in jax.devices()[0].addressable_memories())
    except Exception:  # noqa: BLE001 — older jax without memories API
        return False


def has_multiprocess_cpu() -> bool:
    """True when the CPU backend can run multi-process SPMD.

    jax 0.4.x raises `Multiprocess computations aren't implemented on
    the CPU backend` from any cross-process computation; the multi-host
    CPU path arrived with the 0.5+ proxy backend.  Gates the
    `jax.distributed` end-to-end drills on CPU-only containers."""
    import jax

    try:
        major, minor = (int(p) for p in jax.__version__.split(".")[:2])
    except ValueError:  # pragma: no cover — exotic version string
        return True
    return (major, minor) >= (0, 5)


def has_jax_shard_map() -> bool:
    """True when `jax.shard_map` with axis_names support exists
    (jax >= 0.6).  Pipeline parallelism, local_sgd/DiLoCo and the
    ring/ulysses context-parallel attention all build on the manual-axes
    shard_map API; on older jax (this container ships 0.4.37) those
    features raise RuntimeError at build time and their tests skip with
    a version reason instead of failing (tests/* skipif gates)."""
    try:
        from jax import shard_map  # noqa: F401

        return True
    except ImportError:
        return False


_dispatch_overhead_cache: dict = {}


def measure_dispatch_overhead_s(iters: int = 30,
                                force: bool = False) -> float:
    """Measured fixed cost of ONE jit dispatch on this backend (seconds).

    Chains a scalar increment `iters` times through one jitted call each
    and syncs ONCE with a host readback at the end (bench.py idiom:
    `block_until_ready` is a no-op over the axon tunnel), so the number
    is the per-dispatch pipeline overhead — ~5-8ms over the tunnel,
    O(100us) on a local CPU backend — not the round-trip latency.  Feeds
    the fused-step auto-tuner (trainer/train_step.py auto_fused_steps).
    DWT_DISPATCH_OVERHEAD_S pins/overrides the probe (deterministic
    tests, known deployments); cached per backend after first measure."""
    import os
    import time

    env = os.getenv("DWT_DISPATCH_OVERHEAD_S")
    if env:
        try:
            v = float(env)
            if v >= 0:
                return v
        except ValueError:
            pass
    import jax
    import jax.numpy as jnp

    key = jax.default_backend()
    if not force and key in _dispatch_overhead_cache:
        return _dispatch_overhead_cache[key]

    @jax.jit
    def _bump(x):
        return x + 1

    x = _bump(jnp.zeros((), jnp.float32))
    float(x)  # compile + sync
    t0 = time.perf_counter()
    for _ in range(iters):
        x = _bump(x)
    float(x)
    overhead = (time.perf_counter() - t0) / iters
    _dispatch_overhead_cache[key] = overhead
    return overhead


def is_oom_error(exc: BaseException) -> bool:
    """True when `exc` is an accelerator out-of-memory failure.

    XLA surfaces OOM as XlaRuntimeError with a RESOURCE_EXHAUSTED status;
    there is no typed exception to catch, so callers that want a fallback
    path share this heuristic.  Deliberately narrow: a host `MemoryError`
    or an arbitrary message containing "memory" is NOT a device OOM and
    must not trigger device-resource fallbacks (VERDICT r2 weak #7)."""
    name = type(exc).__name__
    if name != "XlaRuntimeError":
        return False
    text = str(exc)
    return "RESOURCE_EXHAUSTED" in text or "out of memory" in text.lower()

"""Global tunables singleton.

Parity: reference `dlrover/python/common/global_context.py` (Context singleton with
master-port, relaunch policy, timeouts, `set_params_from_brain`).  Values may be
overridden from env vars prefixed ``DWT_CTX_``.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field, fields


@dataclass
class Context:
    master_port: int = 0
    node_heartbeat_interval: float = 15.0
    node_heartbeat_timeout: float = 300.0
    relaunch_always: bool = False
    max_relaunch_count: int = 3
    relaunch_on_worker_failure: int = 3
    seconds_to_wait_pending_pod: float = 900.0
    seconds_interval_to_optimize: float = 300.0
    train_speed_record_num: int = 50
    hang_detection_seconds: float = 1800.0
    # master diagnosis cadence (loss-spike / hang / straggler sweep);
    # chaos drills and e2e tests override via DWT_CTX_DIAGNOSIS_INTERVAL
    diagnosis_interval: float = 60.0
    rdzv_join_timeout: float = 600.0
    network_check: bool = False
    auto_tunning: bool = False
    checkpoint_replica: int = 0
    # /metrics exporter port: -1 disables, 0 picks a free port
    metrics_port: int = -1
    # master journal compaction: snapshot + truncate after this many
    # event frames (master/journal.py); DWT_CTX_JOURNAL_SNAPSHOT_EVERY
    journal_snapshot_every: int = 1000
    # how long a MasterClient rides a master outage before giving up on a
    # critical verb (retry backoff caps at ~2s between attempts); the
    # fire-and-forget verbs buffer instead of waiting (master_client.py)
    master_outage_grace_s: float = 120.0
    # paths
    work_dir: str = "/tmp/dwt"
    extra: dict = field(default_factory=dict)

    _singleton = None
    _lock = threading.Lock()

    @classmethod
    def singleton_instance(cls) -> "Context":
        if cls._singleton is None:
            with cls._lock:
                if cls._singleton is None:
                    ctx = cls()
                    ctx._load_env()
                    cls._singleton = ctx
        return cls._singleton

    def _load_env(self):
        for f in fields(self):
            if f.name.startswith("_") or f.name == "extra":
                continue
            env_key = "DWT_CTX_" + f.name.upper()
            raw = os.getenv(env_key)
            if raw is None:
                continue
            if f.type in ("int", int):
                setattr(self, f.name, int(raw))
            elif f.type in ("float", float):
                setattr(self, f.name, float(raw))
            elif f.type in ("bool", bool):
                setattr(self, f.name, raw.lower() in ("1", "true", "yes"))
            else:
                setattr(self, f.name, raw)

    def set_params_from_optimizer(self, params: dict):
        """Accept tuned runtime params (reference: `set_params_from_brain`)."""
        for k, v in params.items():
            if hasattr(self, k) and not k.startswith("_"):
                setattr(self, k, v)
            else:
                self.extra[k] = v


def get_context() -> Context:
    return Context.singleton_instance()

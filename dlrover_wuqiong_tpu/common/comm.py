"""Framed-message TCP RPC used for the agent↔master control plane.

Parity: reference gRPC service with a generic ``get``/``report`` envelope
(`dlrover/proto/elastic_training.proto:26-28`, `master/servicer.py:71-296`,
`elastic_agent/master_client.py`).  The transport here is a length-prefixed
JSON protocol over TCP — dependency-free, testable in-process, and the payloads
are the typed messages from `messages.py`.

Master fault tolerance rides in the envelope:

- every response carries the master's **fencing epoch** (bumped each time a
  master restarts on its journal, master/journal.py) — clients watch it and
  re-register / re-sync when a new master takes over instead of trusting a
  stale world;
- mutating requests may carry an **idempotency key** (``idem``) so a retry
  that crosses a master restart is applied at most once (the servicer's
  journaled idem cache returns the recorded response for a replay);
- all socket IO retries through the repo-wide ``retry_call``
  (common/util.py) with exponential backoff + reconnect; exhaustion raises
  ``MasterUnreachableError`` so callers can tell "master answered with an
  error" (RpcError — never retried) from "master is gone" (degraded mode).

Distributed tracing rides the same envelope (telemetry/spans.py): a
client call opens an ``rpc:<verb>`` span and stamps its context into the
optional ``trace`` field; the servicer side adopts it and opens
``serve:<verb>`` under the caller's span, so one restore or re-mesh
reconstructs as a single trace tree across agent/master/saver processes.
Untraced peers (fakes, old frames) simply omit the field.

Wire format per frame: 4-byte big-endian length + JSON body
  request:  {"verb": "get"|"report", "node_id": int, "node_type": str,
             "payload": <encoded message>, "idem": str?,
             "trace": {"trace_id": str, "span_id": str}?}
  response: {"ok": bool, "error": str, "payload": <encoded message|null>,
             "epoch": int|null}
"""

from __future__ import annotations

import inspect
import socket
import socketserver
import struct
import threading
from typing import Any, Callable, Optional

from ..telemetry import spans as tspans
from . import serialize
from .log import get_logger
from .util import retry_call

logger = get_logger("comm")

_LEN = struct.Struct(">I")
MAX_FRAME = 512 * 1024 * 1024

#: exception classes that mean "the bytes did not make it" — safe to retry
#: (ValueError covers a torn frame: a length prefix read off a half-closed
#: stream)
TRANSPORT_ERRORS = (OSError, ConnectionError, ValueError)


def _send_frame(sock: socket.socket, data: bytes):
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(_recv_exact(sock, 4))
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    return _recv_exact(sock, length)


def find_free_port(host: str = "127.0.0.1") -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def addr_connectable(addr: str, timeout: float = 1.0) -> bool:
    """Reference `elastic_run.py:326 _check_to_use_dlrover_run` telnet probe.

    ``addr`` may be an ordered endpoint list ("primary,standby" — the
    warm-standby HA form MasterClient dials): connectable when ANY
    endpoint answers, since the client's failover rotation reaches it.
    """
    for one in addr.split(","):
        one = one.strip()
        if not one:
            continue
        try:
            host, port = one.rsplit(":", 1)
            with socket.create_connection((host, int(port)),
                                          timeout=timeout):
                return True
        except OSError:
            continue
    return False


class RpcServer:
    """Threaded RPC server dispatching to a handler.

    handler(verb: str, node_id: int, node_type: str, payload) -> response
    message.  A handler whose signature also accepts an ``idem`` keyword
    (MasterServicer.handle) receives the request's idempotency key; plain
    4-arg handlers (tests, fakes) keep working unchanged.

    `epoch_provider` (callable -> int) stamps the master's fencing epoch
    into every response envelope; None leaves the field null (fakes).
    """

    def __init__(self, handler: Callable, host: str = "0.0.0.0",
                 port: int = 0,
                 epoch_provider: Optional[Callable[[], int]] = None):
        self._handler = handler
        self._epoch_provider = epoch_provider
        try:
            params = inspect.signature(handler).parameters
            self._pass_idem = "idem" in params or any(
                p.kind == inspect.Parameter.VAR_KEYWORD
                for p in params.values())
        except (TypeError, ValueError):  # builtins / odd callables
            self._pass_idem = False

        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                while True:
                    try:
                        frame = _recv_frame(sock)
                    except (ConnectionError, OSError):
                        return
                    epoch = None
                    if outer._epoch_provider is not None:
                        try:
                            epoch = outer._epoch_provider()
                        except Exception:  # noqa: BLE001 — advisory field
                            epoch = None
                    try:
                        req = serialize.loads(frame)
                        args = (req.get("verb", "get"),
                                req.get("node_id", -1),
                                req.get("node_type", ""),
                                req.get("payload"))
                        payload_name = type(req.get("payload")).__name__
                        # adopt the caller's trace so serve:<verb> nests
                        # under the client's rpc:<verb> span
                        with tspans.extract(req.get("trace")), \
                                tspans.span(
                                    f"serve:{req.get('verb', 'get')}",
                                    {"node_id": req.get("node_id", -1),
                                     "msg": payload_name}):
                            if outer._pass_idem:
                                resp = outer._handler(
                                    *args, idem=req.get("idem"))
                            else:
                                resp = outer._handler(*args)
                        body = serialize.dumps(
                            {"ok": True, "error": "", "payload": resp,
                             "epoch": epoch}
                        )
                    except Exception as e:  # noqa: BLE001 — report to caller
                        logger.exception("rpc handler error")
                        body = serialize.dumps(
                            {"ok": False, "error": f"{type(e).__name__}: {e}",
                             "payload": None, "epoch": epoch}
                        )
                    try:
                        _send_frame(sock, body)
                    except OSError:
                        return

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="dwt-rpc-server"
        )
        self._thread.start()
        logger.info("RPC server listening on port %s", self.port)

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class RpcError(RuntimeError):
    """The master ANSWERED with an error — never retried blindly."""


class MasterUnreachableError(RpcError):
    """The retry budget ran out without a response frame making it back.

    Subclasses RpcError so legacy `except RpcError` sites still catch it;
    the distinct type is what the MasterClient's degraded mode keys on
    (buffer the message, keep training) vs a real handler error (raise)."""


class RpcClient:
    """Persistent-connection client; every call retries through retry_call.

    Parity: reference `elastic_agent/master_client.py` retry decorator
    semantics (`retry_grpc_request`), extended with the fencing-epoch watch:
    the first response from a RESTARTED master carries a higher epoch, and
    `on_epoch_change(old, new)` fires exactly once per bump (outside the
    socket lock, re-entrant calls suppressed) so the MasterClient can
    re-register and re-sync in-flight state.
    """

    def __init__(self, addr: str, node_id: int = -1, node_type: str = "worker",
                 timeout: float = 30.0, retries: int = 3,
                 base_delay_s: float = 0.1, max_delay_s: float = 2.0):
        self._addr = addr
        self._node_id = node_id
        self._node_type = node_type
        self._timeout = timeout
        self._retries = retries
        self._base_delay_s = base_delay_s
        self._max_delay_s = max_delay_s
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        # fencing epoch bookkeeping
        self.epoch: Optional[int] = None
        self.on_epoch_change: Optional[Callable[[int, int], None]] = None
        self._epoch_lock = threading.Lock()
        self._notifying = False

    def _connect(self):
        host, port = self._addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock

    def close(self):
        with self._lock:
            self._close_locked()

    def _close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _attempt(self, req: bytes) -> Any:
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                _send_frame(self._sock, req)
                body = _recv_frame(self._sock)
            except TRANSPORT_ERRORS:
                # half-open / mid-frame death poisons the stream — drop it
                # so the retry re-dials instead of reading a stale tail
                self._close_locked()
                raise
        return serialize.loads(body)

    def _call(self, verb: str, payload: Any, idem: Optional[str] = None,
              attempts: Optional[int] = None,
              deadline_s: Optional[float] = None) -> Any:
        with tspans.span(f"rpc:{verb}",
                         {"msg": type(payload).__name__,
                          "node_id": self._node_id}):
            envelope = {"verb": verb, "node_id": self._node_id,
                        "node_type": self._node_type, "payload": payload}
            trace = tspans.inject()
            if trace is not None:
                envelope["trace"] = trace
            if idem is not None:
                envelope["idem"] = idem
            req = serialize.dumps(envelope)
            if attempts is None and deadline_s is None:
                attempts = self._retries
            try:
                resp = retry_call(
                    lambda: self._attempt(req),
                    attempts=attempts, deadline_s=deadline_s,
                    base_delay_s=self._base_delay_s,
                    max_delay_s=self._max_delay_s,
                    retry_on=TRANSPORT_ERRORS, label=verb)
            except TRANSPORT_ERRORS as e:
                raise MasterUnreachableError(
                    f"rpc {verb} to {self._addr} failed after retries: "
                    f"{type(e).__name__}: {e}") from e
        self._observe_epoch(resp.get("epoch"))
        if not resp.get("ok"):
            raise RpcError(resp.get("error", "unknown rpc error"))
        return resp.get("payload")

    def _observe_epoch(self, new: Optional[int]):
        if new is None:
            return
        fire = None
        with self._epoch_lock:
            old = self.epoch
            self.epoch = new
            if old is not None and new != old and not self._notifying \
                    and self.on_epoch_change is not None:
                fire = (old, new)
                self._notifying = True
        if fire is None:
            return
        try:
            self.on_epoch_change(*fire)
        except Exception:  # noqa: BLE001 — resync is best-effort
            logger.exception("epoch-change callback failed")
        finally:
            with self._epoch_lock:
                self._notifying = False

    def get(self, payload: Any, **kw) -> Any:
        return self._call("get", payload, **kw)

    def report(self, payload: Any, **kw) -> Any:
        return self._call("report", payload, **kw)

"""Framed-message TCP RPC used for the agent↔master control plane.

Parity: reference gRPC service with a generic ``get``/``report`` envelope
(`dlrover/proto/elastic_training.proto:26-28`, `master/servicer.py:71-296`,
`elastic_agent/master_client.py`).  The transport here is a length-prefixed
JSON protocol over TCP — dependency-free, testable in-process, and the payloads
are the typed messages from `messages.py`.

Wire format per frame: 4-byte big-endian length + JSON body
  request:  {"verb": "get"|"report", "node_id": int, "node_type": str,
             "payload": <encoded message>}
  response: {"ok": bool, "error": str, "payload": <encoded message|null>}
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Optional

from . import serialize
from .log import get_logger

logger = get_logger("comm")

_LEN = struct.Struct(">I")
MAX_FRAME = 512 * 1024 * 1024


def _send_frame(sock: socket.socket, data: bytes):
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(_recv_exact(sock, 4))
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    return _recv_exact(sock, length)


def find_free_port(host: str = "127.0.0.1") -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def addr_connectable(addr: str, timeout: float = 1.0) -> bool:
    """Reference `elastic_run.py:326 _check_to_use_dlrover_run` telnet probe."""
    try:
        host, port = addr.rsplit(":", 1)
        with socket.create_connection((host, int(port)), timeout=timeout):
            return True
    except OSError:
        return False


class RpcServer:
    """Threaded RPC server dispatching to a handler.

    handler(verb: str, node_id: int, node_type: str, payload) -> response message
    """

    def __init__(self, handler: Callable, host: str = "0.0.0.0", port: int = 0):
        self._handler = handler

        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                while True:
                    try:
                        frame = _recv_frame(sock)
                    except (ConnectionError, OSError):
                        return
                    try:
                        req = serialize.loads(frame)
                        resp = outer._handler(
                            req.get("verb", "get"),
                            req.get("node_id", -1),
                            req.get("node_type", ""),
                            req.get("payload"),
                        )
                        body = serialize.dumps(
                            {"ok": True, "error": "", "payload": resp}
                        )
                    except Exception as e:  # noqa: BLE001 — report to caller
                        logger.exception("rpc handler error")
                        body = serialize.dumps(
                            {"ok": False, "error": f"{type(e).__name__}: {e}",
                             "payload": None}
                        )
                    try:
                        _send_frame(sock, body)
                    except OSError:
                        return

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="dwt-rpc-server"
        )
        self._thread.start()
        logger.info("RPC server listening on port %s", self.port)

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class RpcError(RuntimeError):
    pass


class RpcClient:
    """Persistent-connection client with retry.

    Parity: reference `elastic_agent/master_client.py` retry decorator semantics.
    """

    def __init__(self, addr: str, node_id: int = -1, node_type: str = "worker",
                 timeout: float = 30.0, retries: int = 3):
        self._addr = addr
        self._node_id = node_id
        self._node_type = node_type
        self._timeout = timeout
        self._retries = retries
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self):
        host, port = self._addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock

    def close(self):
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None

    def _call(self, verb: str, payload: Any) -> Any:
        req = serialize.dumps(
            {"verb": verb, "node_id": self._node_id,
             "node_type": self._node_type, "payload": payload}
        )
        last_err: Optional[Exception] = None
        for attempt in range(self._retries):
            try:
                with self._lock:
                    if self._sock is None:
                        self._connect()
                    _send_frame(self._sock, req)
                    body = _recv_frame(self._sock)
                resp = serialize.loads(body)
                if not resp.get("ok"):
                    raise RpcError(resp.get("error", "unknown rpc error"))
                return resp.get("payload")
            except RpcError:
                raise
            except (OSError, ConnectionError, ValueError) as e:
                last_err = e
                self.close()
                time.sleep(min(2.0 ** attempt * 0.1, 2.0))
        raise RpcError(f"rpc to {self._addr} failed after "
                       f"{self._retries} attempts: {last_err}")

    def get(self, payload: Any) -> Any:
        return self._call("get", payload)

    def report(self, payload: Any) -> Any:
        return self._call("report", payload)

"""Node model and status state machine.

Parity: reference `dlrover/python/common/node.py` (Node, 358 LoC) and
`dlrover/python/master/node/status_flow.py` (NodeStateFlow, 136 LoC).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from .constants import NodeEventType, NodeExitReason, NodeStatus


@dataclass
class NodeResource:
    cpu: float = 0.0
    memory_mb: float = 0.0
    accelerator_type: str = ""
    accelerator_num: int = 0

    def to_dict(self) -> Dict:
        return {
            "cpu": self.cpu,
            "memory_mb": self.memory_mb,
            "accelerator_type": self.accelerator_type,
            "accelerator_num": self.accelerator_num,
        }


@dataclass
class NodeGroupResource:
    count: int = 0
    node_resource: NodeResource = field(default_factory=NodeResource)


class Node:
    """A training node (pod / local process) tracked by the master."""

    def __init__(
        self,
        node_type: str,
        node_id: int,
        rank_index: Optional[int] = None,
        name: str = "",
        status: str = NodeStatus.INITIAL,
        config_resource: Optional[NodeResource] = None,
        max_relaunch_count: int = 3,
    ):
        self.type = node_type
        self.id = node_id
        self.rank_index = rank_index if rank_index is not None else node_id
        self.name = name or f"{node_type}-{node_id}"
        self.status = status
        self.config_resource = config_resource or NodeResource()
        self.used_resource = NodeResource()
        self.max_relaunch_count = max_relaunch_count

        self.relaunch_count = 0
        self.relaunchable = True
        self.is_released = False
        self.exit_reason = ""
        self.addr = ""
        self.create_time: Optional[float] = None
        self.start_time: Optional[float] = None
        self.finish_time: Optional[float] = None
        self.heartbeat_time: float = 0.0
        self.start_hang_time: float = 0.0
        self.hang = False
        self.reported_status = ""
        self.restart_training = False
        # set with restart_training by a loss-spike rollback: the restarted
        # worker must resume from a committed ckpt step BEFORE this
        self.rollback_before_step = -1
        self.paral_config_version = 0

    # ------------------------------------------------------------- transitions

    def update_status(self, status: str):
        if status and status != self.status:
            self.status = status
            if status == NodeStatus.RUNNING and self.start_time is None:
                self.start_time = time.time()
            if status in NodeStatus.terminal():
                self.finish_time = time.time()

    def update_info(self, name: str = "", addr: str = "",
                    create_time: Optional[float] = None):
        if name:
            self.name = name
        if addr:
            self.addr = addr
        if create_time:
            self.create_time = create_time

    def update_resource_usage(self, cpu: float, memory_mb: float,
                              accelerator_stats: Optional[Dict] = None):
        self.used_resource.cpu = cpu
        self.used_resource.memory_mb = memory_mb

    def inc_relaunch_count(self):
        self.relaunch_count += 1

    def exited(self) -> bool:
        return self.status in NodeStatus.terminal()

    def is_unrecoverable_failure(self) -> bool:
        if not self.relaunchable:
            return True
        if self.relaunch_count >= self.max_relaunch_count:
            return True
        if self.exit_reason == NodeExitReason.FATAL_ERROR:
            return True
        return False

    def get_relaunch_node_info(self, new_id: int) -> "Node":
        new_node = Node(
            self.type,
            new_id,
            rank_index=self.rank_index,
            config_resource=self.config_resource,
            max_relaunch_count=self.max_relaunch_count,
        )
        new_node.relaunch_count = self.relaunch_count + 1
        return new_node

    def __repr__(self):
        return (f"Node({self.type}-{self.id} rank={self.rank_index} "
                f"status={self.status})")


@dataclass
class NodeEvent:
    event_type: str  # NodeEventType
    node: Node


class NodeStateFlow:
    """Allowed status transitions and the relaunch decision they imply.

    Parity: reference `master/node/status_flow.py` transition table.
    """

    _FLOW = {
        (NodeStatus.INITIAL, NodeStatus.PENDING): False,
        (NodeStatus.INITIAL, NodeStatus.RUNNING): False,
        (NodeStatus.INITIAL, NodeStatus.FAILED): True,
        (NodeStatus.INITIAL, NodeStatus.DELETED): True,
        (NodeStatus.PENDING, NodeStatus.RUNNING): False,
        (NodeStatus.PENDING, NodeStatus.SUCCEEDED): False,
        (NodeStatus.PENDING, NodeStatus.FAILED): True,
        (NodeStatus.PENDING, NodeStatus.DELETED): True,
        (NodeStatus.RUNNING, NodeStatus.SUCCEEDED): False,
        (NodeStatus.RUNNING, NodeStatus.FAILED): True,
        (NodeStatus.RUNNING, NodeStatus.DELETED): True,
        (NodeStatus.RUNNING, NodeStatus.BREAKDOWN): True,
        (NodeStatus.UNKNOWN, NodeStatus.RUNNING): False,
        (NodeStatus.UNKNOWN, NodeStatus.FAILED): True,
        (NodeStatus.UNKNOWN, NodeStatus.DELETED): True,
    }

    @classmethod
    def can_transition(cls, from_status: str, to_status: str) -> bool:
        if from_status == to_status:
            return False
        if from_status in (NodeStatus.SUCCEEDED, NodeStatus.FAILED):
            # terminal except deletion bookkeeping
            return to_status == NodeStatus.DELETED
        return (from_status, to_status) in cls._FLOW or \
            from_status == NodeStatus.UNKNOWN

    @classmethod
    def should_relaunch(cls, from_status: str, to_status: str) -> bool:
        return cls._FLOW.get((from_status, to_status), False)

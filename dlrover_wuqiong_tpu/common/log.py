"""Single logging module. Parity: reference `dlrover/python/common/log.py`."""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"


def _build_logger(name: str = "dwt") -> logging.Logger:
    logger = logging.getLogger(name)
    if logger.handlers:
        return logger
    level = os.getenv("DWT_LOG_LEVEL", "INFO").upper()
    logger.setLevel(getattr(logging, level, logging.INFO))
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(handler)
    logger.propagate = False
    return logger


default_logger = _build_logger()


def get_logger(name: str) -> logging.Logger:
    logger = default_logger.getChild(name)
    return logger

"""Central constants and environment-variable names.

Parity: reference `dlrover/python/common/constants.py` (NodeEnv/NodeType/NodeStatus/
NodeEventType etc.).  Re-designed for a TPU/JAX stack: the worker processes form a
`jax.distributed` world instead of a torch-elastic NCCL group, so the env contract
exposes coordinator address + process ids rather than MASTER_ADDR/RANK.
"""

from __future__ import annotations

import os


class NodeEnv:
    """Environment variables that wire agents/workers to the master."""

    JOB_NAME = "DWT_JOB_NAME"
    MASTER_ADDR = "DWT_MASTER_ADDR"  # host:port of the job master RPC service
    NODE_ID = "DWT_NODE_ID"
    NODE_RANK = "DWT_NODE_RANK"
    NODE_NUM = "DWT_NODE_NUM"
    # JAX world contract (filled by the agent after rendezvous).
    COORDINATOR_ADDR = "DWT_COORDINATOR_ADDR"
    PROCESS_ID = "DWT_PROCESS_ID"
    NUM_PROCESSES = "DWT_NUM_PROCESSES"
    LOCAL_DEVICE_COUNT = "DWT_LOCAL_DEVICE_COUNT"
    # Restart bookkeeping
    RESTART_COUNT = "DWT_RESTART_COUNT"
    PARAL_CONFIG_PATH = "DWT_PARAL_CONFIG_PATH"
    # loss-spike rollback: resume from the newest committed ckpt whose
    # step precedes this value (set one-shot by the agent on relaunch)
    ROLLBACK_BEFORE_STEP = "DWT_ROLLBACK_BEFORE_STEP"
    # warm re-mesh: persistent XLA compile cache shared by the agent, its
    # workers across restarts, and the warm-pool children
    # (auto/compile_cache.py)
    COMPILE_CACHE_DIR = "DWT_COMPILE_CACHE_DIR"


class NodeType:
    MASTER = "master"
    WORKER = "worker"
    PS = "ps"  # kept for sparse-embedding (parameter-service) jobs
    EVALUATOR = "evaluator"


class NodeStatus:
    """Lifecycle states of a node (pod/process).

    Parity: reference `common/constants.py` NodeStatus + `master/node/status_flow.py`.
    """

    INITIAL = "Initial"
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    DELETED = "Deleted"
    UNKNOWN = "Unknown"
    BREAKDOWN = "Breakdown"  # failed hardware health-check

    @classmethod
    def terminal(cls) -> set:
        return {cls.SUCCEEDED, cls.FAILED, cls.DELETED}


class NodeEventType:
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


class NodeExitReason:
    SUCCEEDED = "Succeeded"
    KILLED = "Killed"  # e.g. preemption / eviction — relaunchable
    OOM = "OOM"
    FATAL_ERROR = "FatalError"  # user-code error — not relaunchable
    HARDWARE_ERROR = "HardwareError"  # chip/ICI failure — relaunch on new node
    HANG = "Hang"
    UNKNOWN_ERROR = "UnknownError"

    RELAUNCHABLE = {KILLED, OOM, HARDWARE_ERROR, HANG, UNKNOWN_ERROR}
    KNOWN = {SUCCEEDED, KILLED, OOM, FATAL_ERROR, HARDWARE_ERROR, HANG,
             UNKNOWN_ERROR}


class JobExitReason:
    SUCCEEDED = "Succeeded"
    CODE_ERROR = "CodeError"
    WORKER_ERROR = "WorkerError"
    UNCOMPLETED_TIMEOUT = "UncompletedTimeout"
    HANG_ERROR = "HangError"
    UNKNOWN_ERROR = "UnknownError"


class RendezvousName:
    ELASTIC_TRAINING = "elastic-training"
    NETWORK_CHECK = "network-check"


class NetworkFailureReason:
    NO_INIT = "Not initialized"
    NODE_FAILURE = "Node failure"
    WAITING_NODE = "Waiting node"


class TrainingExceptionLevel:
    PROCESS_ERROR = "process"
    NODE_ERROR = "node"
    RDZV_ERROR = "rdzv"
    WARNING = "warning"
    INFO = "info"


class PlatformType:
    LOCAL = "local"
    KUBERNETES = "kubernetes"
    RAY = "ray"


class DistributionStrategy:
    LOCAL = "Local"
    ALLREDUCE = "AllreduceStrategy"  # SPMD data/model parallel over a mesh
    PS = "ParameterServerStrategy"
    CUSTOM = "CustomStrategy"


class TaskType:
    """Dynamic-sharding task types. Parity: reference elastic_training.proto TaskType."""

    NONE = "none"
    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"
    WAIT = "wait"
    TRAIN_END_CALLBACK = "train_end_callback"


class CheckpointConstant:
    CKPT_NAME_PREFIX = "checkpoint-"
    TRACKER_FILE = "latest_checkpointed_iteration.txt"
    MODEL_STATES_NAME = "model_states"
    OPTIM_STATES_NAME = "optim_states"
    DONE_DIR = ".done"
    # written inside a step dir when the tracker publishes it — the durable
    # "all shards landed" witness (done-files alone can be a partial set)
    COMMIT_MARKER = ".commit"
    SAVE_TIMEOUT = 600


class JobConstant:
    RDZV_JOIN_TIMEOUT_DEFAULT = 600
    HEARTBEAT_INTERVAL_SECS = float(
        os.getenv("DWT_HEARTBEAT_INTERVAL_SECS", "15"))
    HEARTBEAT_TIMEOUT_SECS = 300
    MASTER_SERVICE_DEFAULT_PORT = 0  # 0 → pick a free port
    TRAINING_AGENT_LOOP_INTERVAL = 1
    NODE_CHECK_TIMEOUT_SECS = 300
    PENDING_NODE_TIMEOUT_SECS = 900
    # Min interval between two membership-driven restarts (env-overridable:
    # elasticity e2e tests need tighter loops than production)
    RESTART_DEBOUNCE_SECS = float(
        os.getenv("DWT_RESTART_DEBOUNCE_SECS", "30"))


class ConfigPath:
    ENV_PARAL_CONFIG = NodeEnv.PARAL_CONFIG_PATH
    PARAL_CONFIG_DEFAULT = "/tmp/dwt/paral_config.json"
    RUNTIME_METRICS_DEFAULT = "/tmp/dwt/runtime_metrics.json"

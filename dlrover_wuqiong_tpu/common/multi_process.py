"""Local IPC primitives shared between the agent and training processes.

Parity: reference `dlrover/python/common/multi_process.py` (SharedLock:225,
SharedQueue:346, SharedDict:453, POSIX SharedMemory wrapper) — a unix-domain-socket
server per named resource owned by the agent process, plus POSIX shared memory for
zero-copy tensor staging.  Used by the flash-checkpoint path (§3.3 of SURVEY.md):
training procs write `jax.Array` shard bytes into shm and enqueue events for the
agent-side async saver.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import socketserver
import struct
import threading
import time
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Dict, Optional

from .log import get_logger

logger = get_logger("multi_process")

SOCKET_DIR = os.getenv("DWT_SOCKET_DIR", "/tmp/dwt/sockets")

_LEN = struct.Struct(">I")


def _socket_path(name: str) -> str:
    os.makedirs(SOCKET_DIR, exist_ok=True)
    return os.path.join(SOCKET_DIR, f"{name}.sock")


def _send(sock: socket.socket, obj: Any):
    data = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv(sock: socket.socket) -> Any:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("closed")
        hdr += chunk
    (n,) = _LEN.unpack(hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("closed")
        buf += chunk
    return json.loads(buf.decode())


class LocalSocketComm:
    """A named resource reachable over a unix socket.

    The creating process (``master=True``) runs a server thread answering
    requests; other processes connect as clients.  Subclasses implement
    ``_handle(request) -> response``.
    """

    def __init__(self, name: str, master: bool = False):
        self._name = name
        self._path = _socket_path(name)
        self._master = master
        self._server = None
        self._client_lock = threading.Lock()
        self._client_sock: Optional[socket.socket] = None
        if master:
            self._start_server()

    # ------------------------------------------------------------------ server

    def _start_server(self):
        if os.path.exists(self._path):
            os.unlink(self._path)
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        req = _recv(self.request)
                    except (ConnectionError, OSError):
                        return
                    try:
                        resp = outer._handle(req)
                    except Exception as e:  # noqa: BLE001
                        resp = {"err": f"{type(e).__name__}: {e}"}
                    try:
                        _send(self.request, resp)
                    except OSError:
                        return

        class _Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True

        self._server = _Server(self._path, _Handler)
        t = threading.Thread(target=self._server.serve_forever, daemon=True,
                             name=f"dwt-ipc-{self._name}")
        t.start()

    def close(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            if os.path.exists(self._path):
                os.unlink(self._path)
        with self._client_lock:
            if self._client_sock is not None:
                self._client_sock.close()
                self._client_sock = None

    def _handle(self, request: Dict) -> Dict:
        raise NotImplementedError

    # ------------------------------------------------------------------ client

    class _DialBudgetExceeded(Exception):
        """Could not even CONNECT within the caller's dial budget."""

    def _request(self, req: Dict, timeout: float = 60.0,
                 dial_timeout: Optional[float] = None) -> Dict:
        """`timeout` bounds the whole exchange; `dial_timeout` (<= timeout)
        separately bounds the CONNECT phase — a socket path that never
        answers means the resource master does not exist, and callers with
        their own fallback (lock-free staging copy, replica backup) must
        not wait out the full exchange budget to learn that."""
        if self._master:
            return self._handle(req)
        from .util import retry_call

        start = time.monotonic()

        def attempt() -> Dict:
            # raw dial sanctioned here because the whole attempt runs
            # under retry_call (graftlint raw-rpc-call)
            if self._client_sock is None:
                if dial_timeout is not None and \
                        time.monotonic() - start > dial_timeout:
                    raise LocalSocketComm._DialBudgetExceeded()
                self._client_sock = socket.socket(socket.AF_UNIX,
                                                  socket.SOCK_STREAM)
                self._client_sock.connect(self._path)
            _send(self._client_sock, req)
            resp = _recv(self._client_sock)
            if "err" in resp:
                raise RuntimeError(resp["err"])
            return resp

        def drop_sock(_n, _exc, _delay):
            if self._client_sock is not None:
                self._client_sock.close()
                self._client_sock = None

        with self._client_lock:
            try:
                # flat 0.1s cadence preserved (jitterless, max=base): the
                # master side comes up once and stays — backoff would only
                # delay the first contact
                return retry_call(
                    attempt, attempts=None, deadline_s=timeout,
                    base_delay_s=0.1, max_delay_s=0.1, jitter=0.0,
                    retry_on=(ConnectionError, FileNotFoundError, OSError),
                    on_retry=drop_sock)
            except (LocalSocketComm._DialBudgetExceeded, ConnectionError,
                    FileNotFoundError, OSError) as e:
                drop_sock(0, e, 0.0)
                raise TimeoutError(
                    f"IPC resource {self._name} unreachable") from e


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        pass
    return True


class SharedLock(LocalSocketComm):
    """Cross-process lock. Parity: reference SharedLock (multi_process.py:225).

    Unlike the reference, the holder's PID is tracked and a waiter reaps
    the lock when the holder process no longer exists: a worker SIGKILLed
    mid-critical-section (shm staging, elastic_agent relaunch flow) must
    not wedge the NEXT worker generation for the full acquire timeout —
    the lock, like the shm segments and sockets around it, outlives hard
    kills (CLAUDE.md)."""

    def __init__(self, name: str, master: bool = False):
        self._lock = threading.Lock() if master else None
        self._meta = threading.Lock() if master else None
        self._holder_pid: Optional[int] = None
        super().__init__(f"lock-{name}", master)

    def _try_acquire(self, pid: int) -> bool:
        with self._meta:
            if self._lock.acquire(blocking=False):
                self._holder_pid = pid
                return True
            holder = self._holder_pid
            if holder is not None and not _pid_alive(holder):
                logger.warning(
                    "lock %s: holder pid %d is dead — reaping", self._name,
                    holder)
                try:
                    self._lock.release()
                except RuntimeError:
                    pass
                self._lock.acquire(blocking=False)
                self._holder_pid = pid
                return True
            return False

    def _handle(self, request):
        op = request["op"]
        if op == "acquire":
            pid = int(request.get("pid", 0))
            if not request.get("blocking", True):
                return {"ok": self._try_acquire(pid)}
            timeout = request.get("timeout", -1)
            deadline = (time.monotonic() + timeout) if timeout and timeout > 0 \
                else None
            # poll instead of a blocking Lock.acquire so a holder that
            # dies WHILE we wait is noticed within one poll interval
            while True:
                if self._try_acquire(pid):
                    return {"ok": True}
                if deadline is not None and time.monotonic() >= deadline:
                    return {"ok": False}
                time.sleep(0.05)
        if op == "release":
            with self._meta:
                try:
                    self._lock.release()
                except RuntimeError:
                    pass
                self._holder_pid = None
            return {"ok": True}
        if op == "locked":
            return {"ok": self._lock.locked()}
        raise ValueError(op)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # client timeout must outlast the server's poll loop, not cut the
        # socket mid-wait (the server would keep polling for a vanished
        # waiter and hand it a lock nobody releases) — but the CONNECT
        # phase is bounded by the caller's own timeout: when the lock
        # master does not exist at all, the caller learns it within its
        # budget instead of the 60s rpc floor
        rpc_timeout = max(60.0, timeout + 30.0) if timeout and timeout > 0 \
            else 7 * 24 * 3600.0
        dial = max(0.2, timeout) if timeout and timeout > 0 else None
        return self._request({"op": "acquire", "blocking": blocking,
                              "timeout": timeout, "pid": os.getpid()},
                             timeout=rpc_timeout, dial_timeout=dial)["ok"]

    def release(self):
        self._request({"op": "release"})

    def locked(self) -> bool:
        return self._request({"op": "locked"})["ok"]


class SharedQueue(LocalSocketComm):
    """Cross-process FIFO queue. Parity: reference SharedQueue (:346)."""

    def __init__(self, name: str, master: bool = False, maxsize: int = 0):
        self._queue = queue.Queue(maxsize) if master else None
        super().__init__(f"queue-{name}", master)

    def _handle(self, request):
        op = request["op"]
        if op == "put":
            self._queue.put(request["item"])
            return {"ok": True}
        if op == "get":
            try:
                item = self._queue.get(
                    block=request.get("block", True),
                    timeout=request.get("timeout"))
                return {"ok": True, "item": item}
            except queue.Empty:
                return {"ok": False, "item": None}
        if op == "qsize":
            return {"ok": True, "n": self._queue.qsize()}
        if op == "empty":
            return {"ok": True, "n": int(self._queue.empty())}
        raise ValueError(op)

    def put(self, item: Any):
        self._request({"op": "put", "item": item})

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        wait = timeout if timeout is not None else 3600.0
        resp = self._request({"op": "get", "block": block, "timeout": timeout},
                             timeout=wait + 60.0)
        if not resp["ok"]:
            raise queue.Empty
        return resp["item"]

    def qsize(self) -> int:
        return self._request({"op": "qsize"})["n"]

    def empty(self) -> bool:
        return bool(self._request({"op": "empty"})["n"])


class SharedDict(LocalSocketComm):
    """Cross-process dict. Parity: reference SharedDict (:453)."""

    def __init__(self, name: str, master: bool = False):
        self._dict: Dict = {} if master else None
        self._dict_lock = threading.Lock() if master else None
        super().__init__(f"dict-{name}", master)

    def _handle(self, request):
        op = request["op"]
        with self._dict_lock:
            if op == "set":
                self._dict.update(request["items"])
                return {"ok": True}
            if op == "get":
                return {"ok": True, "dict": self._dict}
            if op == "pop":
                return {"ok": True,
                        "item": self._dict.pop(request["key"], None)}
        raise ValueError(op)

    def set(self, items: Dict):
        self._request({"op": "set", "items": items})

    def get(self) -> Dict:
        return self._request({"op": "get"})["dict"]

    def pop(self, key: str) -> Any:
        return self._request({"op": "pop", "key": key})["item"]


class SharedMemoryBuffer:
    """POSIX shared-memory segment wrapper.

    Parity: reference's SharedMemory (unregistered from the resource tracker so a
    training-process exit doesn't tear down the agent's segment).
    """

    def __init__(self, name: str, create: bool = False, size: int = 0):
        self.name = name
        if create:
            try:
                existing = shared_memory.SharedMemory(name=name)
            except FileNotFoundError:
                existing = None
            if existing is not None:
                if existing.size >= size:
                    self._shm = existing
                    self._created = False
                    self._unregister()
                    return
                existing.close()
                existing.unlink()
            self._shm = shared_memory.SharedMemory(name=name, create=True,
                                                   size=size)
            self._created = True
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            self._created = False
        self._unregister()

    def _unregister(self):
        # Keep the segment alive independent of any single process's exit.
        try:
            resource_tracker.unregister(self._shm._name, "shared_memory")
        except Exception:  # noqa: BLE001 — best-effort; impl detail of CPython
            pass

    @property
    def buf(self) -> memoryview:
        return self._shm.buf

    @property
    def size(self) -> int:
        return self._shm.size

    def close(self):
        self._shm.close()

    def unlink(self):
        # CPython 3.12's SharedMemory.unlink() unconditionally UNregisters
        # the segment from the resource tracker — but __init__ already
        # unregistered it (by design, see _unregister), so the tracker
        # process would log a KeyError traceback.  Re-register first so the
        # pair balances.
        try:
            resource_tracker.register(self._shm._name, "shared_memory")
        except Exception:  # noqa: BLE001 — impl detail of CPython
            pass
        try:
            self._shm.unlink()
        except FileNotFoundError:
            # already unlinked by a peer: CPython skipped ITS unregister,
            # so balance the register above or the tracker warns at exit
            try:
                resource_tracker.unregister(self._shm._name, "shared_memory")
            except Exception:  # noqa: BLE001
                pass

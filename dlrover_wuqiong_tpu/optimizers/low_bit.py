"""Low-bit optimizer states: block-wise int8 Adam moments.

Parity: reference `atorch/atorch/optimizers/low_bit/` (4/8-bit optimizer
states backed by triton/CUDA quant kernels `atorch/ops/csrc/quantize.cu`,
`quantization_optimizer.cu`).

TPU redesign: the quantize/dequantize are plain jnp — blockwise absmax int8
with an f32 scale per block — and XLA fuses them into the surrounding
elementwise update, so no custom kernel is needed for the memory win: mu/nu
are stored int8 (+ 1/256 f32 scales), cutting Adam state from 8 to ~2.03
bytes/param.  Numerics: absmax blockwise quantization, deterministic
rounding; bias-corrected Adam update in f32.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

BLOCK = 256


@jax.tree_util.register_pytree_node_class
class QTensor:
    """Blockwise-int8 tensor: int8 payload + per-block f32 absmax scale."""

    def __init__(self, q, scale, size: int, shape: Tuple[int, ...]):
        self.q = q
        self.scale = scale
        self.size = size
        self.shape = shape

    def tree_flatten(self):
        return (self.q, self.scale), (self.size, self.shape)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])


def quantize_blockwise(x: jax.Array) -> QTensor:
    """Nonlinear (quadratic-map) signed int8: code = 127*sqrt(|x|/absmax).

    A linear absmax map starves small elements sharing a block with a large
    one (codes round to 0 and the moment dies); the sqrt code map gives
    ~relative precision near zero — the same reason the reference's CUDA
    kernels use a nonlinear dynamic map (quantize.cu)."""
    flat = x.astype(jnp.float32).reshape(-1)
    n = flat.size
    pad = (-n) % BLOCK
    flat = jnp.pad(flat, (0, pad)).reshape(-1, BLOCK)
    absmax = jnp.max(jnp.abs(flat), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax, 1.0)
    norm = jnp.sqrt(jnp.abs(flat) / scale)
    q = (jnp.sign(flat) * jnp.clip(jnp.round(norm * 127.0), 0, 127)
         ).astype(jnp.int8)
    return QTensor(q=q, scale=scale[:, 0], size=n, shape=tuple(x.shape))


def dequantize_blockwise(qv: QTensor) -> jax.Array:
    c = qv.q.astype(jnp.float32) / 127.0
    flat = jnp.sign(c) * c * c * qv.scale[:, None]
    return flat.reshape(-1)[:qv.size].reshape(qv.shape)


class ScaleByAdam8bitState(NamedTuple):
    count: jax.Array
    mu: optax.Updates   # tree of QTensor
    nu: optax.Updates   # tree of QTensor


def scale_by_adam8bit(b1: float = 0.9, b2: float = 0.999,
                      eps: float = 1e-8) -> optax.GradientTransformation:
    _is_q = lambda x: isinstance(x, QTensor)  # noqa: E731

    def init_fn(params):
        qzero = lambda p: quantize_blockwise(  # noqa: E731
            jnp.zeros(p.shape, jnp.float32))
        return ScaleByAdam8bitState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(qzero, params),
            nu=jax.tree.map(qzero, params))

    def update_fn(updates, state, params=None):
        del params
        t = state.count + 1
        tf = t.astype(jnp.float32)
        bc1 = 1.0 - b1 ** tf
        bc2 = 1.0 - b2 ** tf

        flat_g, treedef = jax.tree.flatten(updates)
        flat_mu = jax.tree.leaves(state.mu, is_leaf=_is_q)
        flat_nu = jax.tree.leaves(state.nu, is_leaf=_is_q)
        us, mus, nus = [], [], []
        for g, mq, nq in zip(flat_g, flat_mu, flat_nu):
            g = g.astype(jnp.float32)
            m = b1 * dequantize_blockwise(mq) + (1 - b1) * g
            v = b2 * dequantize_blockwise(nq) + (1 - b2) * g * g
            us.append((m / bc1) / (jnp.sqrt(v / bc2) + eps))
            mus.append(quantize_blockwise(m))
            nus.append(quantize_blockwise(v))
        return (jax.tree.unflatten(treedef, us),
                ScaleByAdam8bitState(count=t,
                                     mu=jax.tree.unflatten(treedef, mus),
                                     nu=jax.tree.unflatten(treedef, nus)))

    return optax.GradientTransformation(init_fn, update_fn)


def adamw8bit(learning_rate: float | optax.Schedule = 1e-3, b1: float = 0.9,
              b2: float = 0.999, eps: float = 1e-8,
              weight_decay: float = 0.0) -> optax.GradientTransformation:
    """AdamW with int8 blockwise moments (~2 bytes/param of optimizer state
    instead of 8)."""
    return optax.chain(
        scale_by_adam8bit(b1, b2, eps),
        optax.add_decayed_weights(weight_decay) if weight_decay
        else optax.identity(),
        optax.scale_by_learning_rate(learning_rate),
    )

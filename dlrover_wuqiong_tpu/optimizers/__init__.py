"""Optimizers: AGD (NeurIPS'23), WeightedSAM (KDD'23), low-bit Adam states.

Parity: reference `atorch/atorch/optimizers/` (agd.py, wsam.py, low_bit/).
"""

from .agd import agd, scale_by_agd
from .low_bit import adamw8bit, dequantize_blockwise, quantize_blockwise, \
    scale_by_adam8bit
from .wsam import make_wsam_train_step, wsam_gradients

__all__ = [
    "agd", "scale_by_agd",
    "adamw8bit", "scale_by_adam8bit",
    "quantize_blockwise", "dequantize_blockwise",
    "make_wsam_train_step", "wsam_gradients",
]

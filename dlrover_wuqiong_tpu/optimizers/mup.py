"""muP — maximal-update parametrization for width-transferable HPs.

Parity: reference `atorch/atorch/mup/` (module.py MupModule, optim.py
MuAdam/MuSGD, shape.py base-shape inference, init.py scaled initializers).

Optax-idiom redesign: no module wrappers.  Base shapes come from a small
"base" model's param tree; each target param gets a width multiplier and a
role (input / hidden / output / finite), and
  - `mup_init` rescales initial hidden/output weights by 1/sqrt(mult)
    (variance ∝ 1/fan_in as fan_in grows),
  - `mup_adam`/`mup_sgd` wrap optax with per-param lr scaling following
    the μP table (Adam: hidden & output lr ∝ 1/mult; SGD: hidden lr ∝
    const, output ∝ 1/mult, input ∝ mult),
  - attention uses 1/d scores instead of 1/sqrt(d) (pass
    `sm_scale=1/head_dim` to the attention op).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ..common.log import get_logger

logger = get_logger("mup")

_INPUT_RE = re.compile(
    r".*(wte|wpe|embed|embedding|input_proj)", re.IGNORECASE)
_OUTPUT_RE = re.compile(r".*(lm_head|output|head)/", re.IGNORECASE)


def _path_of(key_path) -> str:
    parts = []
    for p in key_path:
        parts.append(str(getattr(p, "key", getattr(p, "idx",
                                                   getattr(p, "name", p)))))
    return "/".join(parts)


def classify_param(path: str, base_shape: Tuple[int, ...],
                   shape: Tuple[int, ...]) -> str:
    """'input' | 'hidden' | 'output' | 'finite' (μP Table 8 roles)."""
    grown = [i for i, (b, s) in enumerate(zip(base_shape, shape)) if b != s]
    if not grown or len(shape) < 2:
        return "finite"  # biases, norms, scalars — width-independent
    if _INPUT_RE.match(path):
        return "input"
    if _OUTPUT_RE.match(path):
        return "output"
    return "hidden"


def width_mults(base_params: Any, params: Any) -> Any:
    """Per-leaf {mult, role}: mult = fan_in growth factor vs the base model.

    Parity: shape.py base-shape comparison — the "infinite" dims are the
    ones that differ between base and target.
    """
    flat_b = jax.tree_util.tree_flatten_with_path(base_params)[0]
    flat_t = jax.tree_util.tree_flatten_with_path(params)[0]
    if len(flat_b) != len(flat_t):
        raise ValueError("base and target models differ in structure")
    info = {}
    for (pb, lb), (pt, lt) in zip(flat_b, flat_t):
        path = _path_of(pt)
        bs, ts = tuple(lb.shape), tuple(lt.shape)
        role = classify_param(path, bs, ts)
        if len(ts) >= 2 and role != "finite":
            # fan_in is the second-to-last dim for kernels (in, out);
            # embeddings (vocab, emb) treat the feature dim as the width
            fan_idx = len(ts) - 2 if role != "input" else len(ts) - 1
            mult = ts[fan_idx] / max(1, bs[fan_idx])
        else:
            mult = 1.0
        info[path] = {"mult": float(mult), "role": role}
    treedef = jax.tree_util.tree_structure(params)
    leaves = [info[_path_of(p)] for p, _ in flat_t]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def mup_init(params: Any, mults: Any) -> Any:
    """Rescale initial weights per μP: hidden/output std ∝ 1/sqrt(mult).

    Parity: init.py scaled initializers — applied post-init so any flax
    initializer composes.
    """
    def _scale(x, m):
        if m["role"] in ("hidden", "output") and m["mult"] != 1.0:
            return x / jnp.sqrt(m["mult"]).astype(x.dtype)
        return x

    return jax.tree.map(_scale, params, mults,
                        is_leaf=lambda x: isinstance(x, dict)
                        and "mult" in x)


def _lr_factor(role: str, mult: float, adam: bool) -> float:
    if mult == 1.0 or role == "finite":
        return 1.0
    if adam:
        # μP Table 8 (Adam): hidden & output lr ∝ 1/mult; input const
        return 1.0 / mult if role in ("hidden", "output") else 1.0
    # SGD: input ∝ mult, hidden const, output ∝ 1/mult
    if role == "input":
        return mult
    if role == "output":
        return 1.0 / mult
    return 1.0


def scale_by_mup(mults: Any, adam: bool = True
                 ) -> optax.GradientTransformation:
    """Per-param update scaling implementing the μP lr table."""

    factors = jax.tree.map(
        lambda m: _lr_factor(m["role"], m["mult"], adam), mults,
        is_leaf=lambda x: isinstance(x, dict) and "mult" in x)

    def init_fn(params):
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        return jax.tree.map(lambda u, f: u * f, updates, factors), state

    return optax.GradientTransformation(init_fn, update_fn)


def mup_adam(learning_rate, mults: Any, b1: float = 0.9, b2: float = 0.999,
             eps: float = 1e-8, weight_decay: float = 0.0
             ) -> optax.GradientTransformation:
    """MuAdam (parity optim.py MuAdam): adam then μP per-param lr scale."""
    base = optax.adamw(learning_rate, b1=b1, b2=b2, eps=eps,
                       weight_decay=weight_decay) if weight_decay \
        else optax.adam(learning_rate, b1=b1, b2=b2, eps=eps)
    return optax.chain(base, scale_by_mup(mults, adam=True))


def mup_sgd(learning_rate, mults: Any, momentum: Optional[float] = None
            ) -> optax.GradientTransformation:
    """MuSGD (parity optim.py MuSGD)."""
    return optax.chain(optax.sgd(learning_rate, momentum=momentum),
                       scale_by_mup(mults, adam=False))


def mup_attn_scale(head_dim: int) -> float:
    """μP attention: 1/d scores instead of 1/sqrt(d)."""
    return 1.0 / head_dim

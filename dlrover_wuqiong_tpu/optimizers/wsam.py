"""WeightedSAM (KDD'23) — sharpness-aware minimization with weighted
sharpness as a regularization term.

Parity: reference `atorch/atorch/optimizers/wsam.py:11` (`WeightedSAM`,
first_step/second_step two-pass scheme).  Torch needs an optimizer wrapper +
closure; the JAX shape is a *gradient transform of the loss*: a function
that evaluates the loss gradient twice (at w and at the ascent point
w + rho * g/||g||) and returns the WSAM-combined gradient, usable with any
optax optimizer inside any jit'd train step.

    g1 = grad L(w)
    e  = rho * P g1 / ||sqrt(P) g1||      (P = diag(w^2) if adaptive else I)
    g2 = grad L(w + e)
    decouple:  base update uses g1, then w -= lr * alpha * (g2 - g1)
    coupled:   base update uses alpha*g2 + (1-alpha)*g1
with alpha = gamma / (1 - gamma).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax


def wsam_gradients(loss_fn: Callable, params, *args, rho: float = 0.05,
                   gamma: float = 0.9, sam_eps: float = 1e-12,
                   adaptive: bool = False, decouple: bool = True,
                   ) -> Tuple[jax.Array, Any, Optional[Any]]:
    """Returns (loss, grads_for_base_optimizer, sharpness_or_None).

    When `decouple` (the reference default), apply the base optimizer with
    the returned grads and then subtract `lr * alpha * sharpness` from the
    params — `wsam_extra_update` does this as an optax-style add-on.
    """
    alpha = gamma / (1.0 - gamma)
    loss, g1 = jax.value_and_grad(loss_fn)(params, *args)

    if adaptive:
        weighted = jax.tree.map(lambda p, g: p * p * g, params, g1)
        norm_sq = sum(jnp.sum((p * g) ** 2) for p, g in zip(
            jax.tree.leaves(params), jax.tree.leaves(g1)))
    else:
        weighted = g1
        norm_sq = sum(jnp.sum(g * g) for g in jax.tree.leaves(g1))
    scale = rho / (jnp.sqrt(norm_sq) + sam_eps)
    e_w = jax.tree.map(lambda w: w * scale, weighted)

    perturbed = jax.tree.map(jnp.add, params, e_w)
    g2 = jax.grad(loss_fn)(perturbed, *args)

    if decouple:
        sharpness = jax.tree.map(jnp.subtract, g2, g1)
        return loss, g1, sharpness
    combined = jax.tree.map(lambda a, b: alpha * a + (1 - alpha) * b, g2, g1)
    return loss, combined, None


def make_wsam_train_step(loss_fn: Callable,
                         optimizer: optax.GradientTransformation,
                         learning_rate: float, rho: float = 0.05,
                         gamma: float = 0.9, sam_eps: float = 1e-12,
                         adaptive: bool = False, decouple: bool = True):
    """jit-able `step((params, opt_state), batch)` with the WSAM scheme.

    `learning_rate` is needed explicitly for the decoupled sharpness term
    (the reference reads it off the param group).
    """
    alpha = gamma / (1.0 - gamma)

    @jax.jit
    def step(carry, batch):
        params, opt_state = carry
        loss, grads, sharp = wsam_gradients(
            loss_fn, params, batch, rho=rho, gamma=gamma, sam_eps=sam_eps,
            adaptive=adaptive, decouple=decouple)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if sharp is not None:
            params = jax.tree.map(
                lambda p, s: p - learning_rate * alpha * s, params, sharp)
        return (params, opt_state), loss

    return step

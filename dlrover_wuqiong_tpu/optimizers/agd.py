"""AGD optimizer (NeurIPS'23) in optax idiom.

Parity: reference `atorch/atorch/optimizers/agd.py:18` — an auto-switchable
optimizer preconditioning with the stepwise *gradient difference* of the
bias-corrected first moment.  The reference reports up to 1.5x faster
convergence than AdamW on nanoGPT (atorch/docs/README-AGD.md:29).

Math (per step t, decoupled weight decay handled by the enclosing chain):
    m_t   = b1 m_{t-1} + (1-b1) g_t
    d_t   = m_t / (1-b1^t) - m_{t-1} / (1-b1^{t-1})     (d_1 = m_1/(1-b1))
    v_t   = b2 v_{t-1} + (1-b2) d_t^2
    den   = max(sqrt(v_t), delta * sqrt(1-b2^t))        (amsgrad: running max)
    u_t   = clip(m_t / den) * sqrt(1-b2^t) / (1-b1^t)
    w_t   = w_{t-1} (1 - lr wd) - lr u_t
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax


class ScaleByAgdState(NamedTuple):
    count: jax.Array
    mu: optax.Updates
    nu: optax.Updates
    max_nu: Optional[optax.Updates]


def scale_by_agd(b1: float = 0.9, b2: float = 0.999, delta: float = 1e-5,
                 amsgrad: bool = False,
                 clip: Optional[float] = None) -> optax.GradientTransformation:
    def init_fn(params):
        zeros = jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params)
        return ScaleByAgdState(
            count=jnp.zeros((), jnp.int32), mu=zeros,
            nu=jax.tree.map(jnp.zeros_like, zeros),
            max_nu=jax.tree.map(jnp.zeros_like, zeros) if amsgrad else None)

    def update_fn(updates, state, params=None):
        del params
        t = state.count + 1
        tf = t.astype(jnp.float32)
        bc1 = 1.0 - b1 ** tf
        bc1_old = 1.0 - b1 ** (tf - 1.0)
        bc1_old_safe = jnp.where(t > 1, bc1_old, 1.0)
        bc2 = 1.0 - b2 ** tf

        mu = jax.tree.map(
            lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
            state.mu, updates)
        diff = jax.tree.map(
            lambda m_new, m_old: m_new / bc1 - jnp.where(
                t > 1, m_old / bc1_old_safe, 0.0),
            mu, state.mu)
        nu = jax.tree.map(lambda v, d: b2 * v + (1 - b2) * d * d,
                          state.nu, diff)
        if amsgrad:
            max_nu = jax.tree.map(jnp.maximum, state.max_nu, nu)
            den_src = max_nu
        else:
            max_nu = None
            den_src = nu

        floor = delta * jnp.sqrt(bc2)

        def _u(m, v):
            u = m / jnp.maximum(jnp.sqrt(v), floor)
            if clip is not None:
                u = jnp.clip(u, -clip, clip)
            return u * jnp.sqrt(bc2) / bc1

        out = jax.tree.map(_u, mu, den_src)
        return out, ScaleByAgdState(count=t, mu=mu, nu=nu, max_nu=max_nu)

    return optax.GradientTransformation(init_fn, update_fn)


def agd(learning_rate: float | optax.Schedule = 1e-3,
        betas: Tuple[float, float] = (0.9, 0.999), delta: float = 1e-5,
        weight_decay: float = 0.0, amsgrad: bool = False,
        clip: Optional[float] = None) -> optax.GradientTransformation:
    """AGD with decoupled weight decay (reference `weight_decouple=True`)."""
    return optax.chain(
        scale_by_agd(betas[0], betas[1], delta, amsgrad, clip),
        optax.add_decayed_weights(weight_decay) if weight_decay
        else optax.identity(),
        optax.scale_by_learning_rate(learning_rate),
    )

"""Stable bf16 parameter training: Kahan compensation or f32 masters.

Parity: reference `atorch/atorch/optimizers/bf16_optimizer.py:46`
(BF16Optimizer — bf16 model weights trained stably against f32 master
weights, so small updates are not lost to bf16's 8-bit mantissa).

TPU redesign: an optax wrapper instead of an optimizer subclass, so it
composes with every inner optimizer in the zoo (adamw, AGD, WSAM, 8-bit
states, muP).  Two modes:

- Kahan (default): params stay bf16; the state carries a bf16
  compensation term `e` per parameter.  Each step applies
  v = f32(p) + f32(e) + f32(u); p' = bf16(v); e' = bf16(v - f32(p')).
  p'+e' together behave like a ~16-bit-mantissa accumulator at HALF the
  f32-master memory (2+2 vs 2+4 bytes/param).  Without it, any update
  smaller than half a bf16 ulp of the weight (|u| < ~0.002|p|) is lost
  entirely — late-training lr regimes sit exactly there.
- master=True: classic f32 master weights in the optimizer state (exact
  reference parity); weight decay and the inner update see the master.

Exactness contract with `optax.apply_updates`: the wrapper emits f32
updates `f32(p') - f32(p)`.  Both operands are bf16-representable, so the
difference is exact in f32, `p + u` reconstructs exactly f32(p'), and
apply_updates' cast back to bf16 lands on p' bit-for-bit.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class StableBF16State(NamedTuple):
    inner: Any
    comp: Any  # kahan: bf16 error feedback; master: f32 master weights


def _is_float(p) -> bool:
    return jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating)


def stable_bf16(inner: optax.GradientTransformation,
                master: bool = False) -> optax.GradientTransformation:
    """Wrap `inner` so bf16 params train without losing small updates."""

    def init_fn(params):
        if master:
            comp = jax.tree.map(
                lambda p: p.astype(jnp.float32) if _is_float(p) else p,
                params)
        else:
            comp = jax.tree.map(
                lambda p: (jnp.zeros(p.shape, jnp.bfloat16)
                           if _is_float(p) else jnp.zeros_like(p)),
                params)
        # the inner state (adam mu/nu, ...) inits from an f32 view —
        # zeros_like(bf16 params) would silently carry 8-mantissa-bit
        # moments, the very accumulation loss this wrapper prevents
        f32_params = jax.tree.map(
            lambda p: p.astype(jnp.float32) if _is_float(p) else p, params)
        return StableBF16State(inner=inner.init(f32_params), comp=comp)

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("stable_bf16 requires params")
        # the inner rule (incl. adamw weight decay) sees the PRECISE
        # value: the f32 master, or the Kahan-compensated view
        if master:
            precise = state.comp
        else:
            precise = jax.tree.map(
                lambda p, e: (p.astype(jnp.float32) + e.astype(jnp.float32)
                              if _is_float(p) else p),
                params, state.comp)
        u, inner_s = inner.update(updates, state.inner, precise)

        def _apply(p, e_or_m, ui):
            if not _is_float(p):
                return jnp.zeros_like(p), e_or_m
            if master:
                new_m = e_or_m + ui.astype(jnp.float32)
                new_p = new_m.astype(p.dtype)
                return new_p.astype(jnp.float32) - p.astype(jnp.float32), \
                    new_m
            v = (p.astype(jnp.float32) + e_or_m.astype(jnp.float32)
                 + ui.astype(jnp.float32))
            new_p = v.astype(p.dtype)
            new_e = (v - new_p.astype(jnp.float32)).astype(e_or_m.dtype)
            return new_p.astype(jnp.float32) - p.astype(jnp.float32), new_e

        pairs = jax.tree.map(_apply, params, state.comp, u)
        out = jax.tree.map(lambda pr: pr[0], pairs,
                           is_leaf=lambda x: isinstance(x, tuple))
        comp = jax.tree.map(lambda pr: pr[1], pairs,
                            is_leaf=lambda x: isinstance(x, tuple))
        return out, StableBF16State(inner=inner_s, comp=comp)

    return optax.GradientTransformation(init_fn, update_fn)


def reset_compensation(state: StableBF16State, params: Any,
                       master: bool) -> StableBF16State:
    """Re-anchor the comp state after params were rewritten EXTERNALLY.

    DiLoCo's outer sync overwrites the inner params with the synced global
    tree; the stale f32 master (or Kahan term) would then silently UNDO
    the sync on the next update (master mode derives p from the master).
    Call this with the post-sync params: master := f32(new params);
    Kahan error := 0."""
    if master:
        comp = jax.tree.map(
            lambda p: p.astype(jnp.float32) if _is_float(p) else p, params)
    else:
        comp = jax.tree.map(
            lambda c: jnp.zeros_like(c) if _is_float(c) else c, state.comp)
    return StableBF16State(inner=state.inner, comp=comp)

"""Scripted chaos scenarios with goodput-style recovery invariants.

Parity: reference `docs/tech_report/fault_tolerance_exps.md:27-80` — the
chaosblade experiments (pod delete / CPU-stressed straggler / network
break / process corruption) run against a live job, checking that training
restores and the damaged node is excluded.

Here each scenario is a callable returning an invariant report (dict), so
it is equally a CI test body (tests/test_chaos.py) and an operator tool:

    python -m dlrover_wuqiong_tpu.chaos pod-kill
    python -m dlrover_wuqiong_tpu.chaos straggler
    python -m dlrover_wuqiong_tpu.chaos network-partition

pod-kill drives the REAL stack — `run` CLI → master → agent → worker with
flash checkpoints — and hard-SIGKILLs the worker process group externally
mid-save-window.  The other two exercise the master's detection machinery
directly (fake platform backend), mirroring how the reference report reads
its k8s experiments.

The pod-kill worker deliberately parallels (but is distinct from)
tests/test_elastic_e2e.py's crash worker: that one injects an IN-PROCESS
fault (`os._exit` at a fixed step, deterministic), this one takes an
EXTERNAL asynchronous SIGKILL — the chaosblade `kubectl delete pod`
equivalent, which can land mid-checkpoint-write and therefore also proves
the torn-state invariant.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Dict

from .common.log import get_logger

logger = get_logger("chaos")


# ------------------------------------------------------------------ pod kill


_POD_KILL_WORKER = r"""
import os, sys, time
import numpy as np

from dlrover_wuqiong_tpu.trainer.elastic import init_elastic
from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
    FlashCheckpointer, StorageType)

ckpt_dir, marker_dir, total_steps = sys.argv[1], sys.argv[2], int(sys.argv[3])
ctx = init_elastic()
restart = ctx.world.restart_count
ckpt = FlashCheckpointer(ckpt_dir, job_name=os.environ["DWT_JOB_NAME"])
template = {"w": np.zeros((64, 64), np.float32),
            "step": np.zeros((), np.int64)}
state = ckpt.load_checkpoint(template)
start = int(state["step"]) + 1 if state is not None else 0
with open(os.path.join(marker_dir, f"start_r{restart}"), "w") as f:
    f.write(str(start))
with open(os.path.join(marker_dir, f"pid_r{restart}"), "w") as f:
    f.write(str(os.getpid()))
step = start - 1  # loop may be empty when the kill landed after the
                  # final checkpoint committed
for step in range(start, total_steps):
    w = np.full((64, 64), float(step), np.float32)
    ckpt.save_checkpoint(step, {"w": w, "step": np.int64(step)},
                         storage_type=StorageType.DISK)
    ctx.report_step(step)
    with open(os.path.join(marker_dir, "progress"), "w") as f:
        f.write(str(step))
    time.sleep(0.05)
ok = ckpt.wait_latest_checkpoint(60)
with open(os.path.join(marker_dir, "done"), "w") as f:
    f.write(f"{ok} {step}")
"""


def pod_kill(kill_at_step: int = 8, total_steps: int = 20,
             timeout: float = 240.0) -> Dict:
    """External SIGKILL of the training process mid-save-window.

    Invariants: the job completes after an automatic restart; the resumed
    run starts at a checkpointed step (goodput: lost work is bounded by the
    save cadence); the final checkpoint is complete and consistent (the
    done-dir commit never exposes a torn state)."""
    import numpy as np

    from .checkpoint.checkpointer import FlashCheckpointer

    work = tempfile.mkdtemp(prefix="dwt-chaos-podkill-")
    ckpt_dir = os.path.join(work, "ckpt")
    marker = os.path.join(work, "markers")
    os.makedirs(marker)
    script = os.path.join(work, "worker.py")
    with open(script, "w") as f:
        f.write(_POD_KILL_WORKER)
    job = f"chaos{os.getpid()}"
    env = dict(
        os.environ, DWT_JOB_NAME=job, JAX_PLATFORMS="cpu",
        DWT_SOCKET_DIR=os.path.join(work, "sockets"),
        PYTHONPATH=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) + os.pathsep +
        os.environ.get("PYTHONPATH", ""))
    cli = subprocess.Popen(
        [sys.executable, "-m", "dlrover_wuqiong_tpu.run", "--standalone",
         "--nproc_per_node=1", "--max_restarts=2", script, ckpt_dir,
         marker, str(total_steps)],
        env=env, cwd=work, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)

    deadline = time.time() + timeout
    killed_pid = None
    killed_at = -1  # the step actually OBSERVED when the kill landed —
    # polling can overshoot kill_at_step on a loaded host, so invariants
    # bound against this, not the request
    progress = os.path.join(marker, "progress")
    while time.time() < deadline and killed_pid is None:
        try:
            seen = int(open(progress).read())
            if seen >= kill_at_step:
                killed_pid = int(open(os.path.join(marker, "pid_r0"))
                                 .read())
                os.kill(killed_pid, signal.SIGKILL)  # the chaosblade moment
                # TOCTOU: the worker can advance past `seen` (and
                # checkpoint) before the SIGKILL lands — the worker is dead
                # NOW, so the file holds the final authoritative step
                try:
                    killed_at = int(open(progress).read())
                except (OSError, ValueError):
                    killed_at = seen
                logger.info("pod-kill: SIGKILL worker pid=%d at step %d",
                            killed_pid, killed_at)
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    try:
        out, _ = cli.communicate(timeout=max(5.0, deadline - time.time()))
    except subprocess.TimeoutExpired:
        cli.kill()
        out, _ = cli.communicate()

    report: Dict = {"scenario": "pod-kill", "killed_pid": killed_pid,
                    "killed_at_step": killed_at, "cli_rc": cli.returncode}
    report["completed"] = os.path.exists(os.path.join(marker, "done"))
    report["restarts"] = sum(
        1 for f in os.listdir(marker) if f.startswith("start_r")) - 1
    resume_file = os.path.join(marker, "start_r1")
    report["resume_step"] = (int(open(resume_file).read())
                             if os.path.exists(resume_file) else -1)
    # torn-checkpoint check: the committed latest must load completely and
    # carry self-consistent contents
    ck = FlashCheckpointer(ckpt_dir, job_name=f"{job}-verify")
    state = ck.load_checkpoint({"w": np.zeros((64, 64), np.float32),
                                "step": np.zeros((), np.int64)})
    ck.close()
    report["ckpt_intact"] = bool(
        state is not None
        and int(state["step"]) == total_steps - 1
        and np.all(np.asarray(state["w"]) == float(int(state["step"]))))
    # goodput: steps not lost to the fault / total useful steps (zero
    # lost when the resume point is past the killed step)
    if report["resume_step"] >= 0 and killed_at >= 0:
        lost = max(0, killed_at - report["resume_step"] + 1)
        report["goodput"] = round(1.0 - lost / total_steps, 3)
    report["ok"] = bool(
        report["completed"] and report["restarts"] == 1
        and 0 < report["resume_step"] <= killed_at + 1
        and report["ckpt_intact"] and cli.returncode == 0)
    if report["ok"]:
        import shutil

        shutil.rmtree(work, ignore_errors=True)
    else:
        report["cli_tail"] = out[-2000:]
        report["workdir"] = work  # kept for debugging
    return report


# ----------------------------------------------------------------- straggler


def straggler(n_nodes: int = 4, slow_node: int = 3,
              slow_factor: float = 5.0) -> Dict:
    """A CPU-stressed node steps far slower than its peers.

    Mirrors the report's chaosblade CPU-load experiment: the network-check
    sweep must name the straggler (so `--exclude-straggler` can drop it)
    and the diagnosis chain must flag it from runtime step cadence too."""
    from .common import messages as msg
    from .diagnosis.manager import (
        CheckStragglerOperator,
        DiagnosisDataManager,
        InferenceChain,
    )
    from .master.rendezvous import NetworkCheckRendezvousManager

    # 1) pre-flight: pairwise network-check sweep
    rdzv = NetworkCheckRendezvousManager()
    rdzv.update_rdzv_params(n_nodes, n_nodes, waiting_timeout=0.0)
    for nid in range(n_nodes):
        rdzv.join_rendezvous(nid, nid, 1)
    for nid in range(n_nodes):
        elapsed = slow_factor if nid == slow_node else 1.0
        rdzv.report_network_check_result(nid, True, elapsed)
    stragglers, _ = rdzv.get_straggler(threshold=2.0)

    # 2) runtime: step cadence diagnosis
    data = DiagnosisDataManager()
    now = time.time()
    for nid in range(n_nodes):
        period = 1.0 * (slow_factor if nid == slow_node else 1.0)
        for k in range(8):
            data.store_report(msg.DiagnosisReport(
                node_id=nid, payload_type="step", content=str(k),
                timestamp=now - (8 - k) * period))
    chain = InferenceChain([CheckStragglerOperator(ratio=3.0,
                                                   min_reports=6)])
    flagged = [c.node_id for c in chain.run(data)
               if c.name == "straggler"]

    report = {"scenario": "straggler", "expected": slow_node,
              "network_check_stragglers": stragglers,
              "runtime_stragglers": flagged}
    report["ok"] = (stragglers == [slow_node] and flagged == [slow_node])
    return report


# --------------------------------------------------------- network partition


def network_partition(heartbeat_timeout: float = 1.5,
                      wait: float = 3.0) -> Dict:
    """A node's control-plane link drops: heartbeats stop arriving.

    The master's heartbeat monitor must declare the node dead and relaunch
    it through the scaler (reference: network-break chaosblade experiment —
    the pod is replaced even though the process may still be running)."""
    from .common.constants import NodeEventType, NodeStatus
    from .common.global_context import get_context
    from .common.node import Node, NodeEvent
    from .master.job_manager import LocalJobManager

    ctx = get_context()
    old_timeout = ctx.node_heartbeat_timeout
    ctx.node_heartbeat_timeout = heartbeat_timeout
    try:
        jm = LocalJobManager(max_relaunch_count=3)
        for nid in range(2):
            node = jm.register_node("worker", nid, rank_index=nid)
            node.update_status(NodeStatus.RUNNING)
            node.heartbeat_time = time.time()
        t0 = time.time()
        relaunched = []
        # node 1 goes silent; node 0 keeps beating — the master's dead-node
        # sweep (master.py run loop) is replayed here
        while time.time() - t0 < wait and not relaunched:
            jm.get_node(0).heartbeat_time = time.time()
            for n in jm.get_dead_nodes():
                relaunched.append(n.id)
                dead = Node(n.type, n.id, rank_index=n.rank_index)
                dead.status = NodeStatus.FAILED
                dead.exit_reason = "Hang"
                jm.process_event(NodeEvent(NodeEventType.MODIFIED, dead))
            time.sleep(0.1)
        n1 = jm.get_node(1)
        report = {"scenario": "network-partition",
                  "dead_detected": relaunched,
                  "node1_relaunch_count": n1.relaunch_count}
        report["ok"] = (relaunched == [1] and n1.relaunch_count == 1)
        return report
    finally:
        ctx.node_heartbeat_timeout = old_timeout


SCENARIOS = {"pod-kill": pod_kill, "straggler": straggler,
             "network-partition": network_partition}


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    names = argv or list(SCENARIOS)
    ok = True
    for name in names:
        fn = SCENARIOS.get(name)
        if fn is None:
            print(f"unknown scenario {name!r}; have {list(SCENARIOS)}",
                  file=sys.stderr)
            return 2
        report = fn()
        print(json.dumps(report))
        ok = ok and report.get("ok", False)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

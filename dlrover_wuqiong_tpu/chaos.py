"""Scripted chaos scenarios with goodput-style recovery invariants.

Parity: reference `docs/tech_report/fault_tolerance_exps.md:27-80` — the
chaosblade experiments (pod delete / CPU-stressed straggler / network
break / process corruption) run against a live job, checking that training
restores and the damaged node is excluded.

Here each scenario is a callable returning an invariant report (dict), so
it is equally a CI test body (tests/test_chaos.py) and an operator tool:

    python -m dlrover_wuqiong_tpu.chaos pod-kill
    python -m dlrover_wuqiong_tpu.chaos straggler
    python -m dlrover_wuqiong_tpu.chaos network-partition
    python -m dlrover_wuqiong_tpu.chaos preempt-warm   # re-mesh compile win
    python -m dlrover_wuqiong_tpu.chaos preempt-fused  # K-step boundaries
    python -m dlrover_wuqiong_tpu.chaos preempt-adaptive  # policy loop
    python -m dlrover_wuqiong_tpu.chaos serve-drain    # kill decode worker

pod-kill drives the REAL stack — `run` CLI → master → agent → worker with
flash checkpoints — and hard-SIGKILLs the worker process group externally
mid-save-window.  The other two exercise the master's detection machinery
directly (fake platform backend), mirroring how the reference report reads
its k8s experiments.

The pod-kill worker deliberately parallels (but is distinct from)
tests/test_elastic_e2e.py's crash worker: that one injects an IN-PROCESS
fault (`os._exit` at a fixed step, deterministic), this one takes an
EXTERNAL asynchronous SIGKILL — the chaosblade `kubectl delete pod`
equivalent, which can land mid-checkpoint-write and therefore also proves
the torn-state invariant.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Dict

from .common.log import get_logger

logger = get_logger("chaos")

_launch_seq = 0


def _launch_standalone(prefix: str, worker_src: str, args,
                       max_restarts: int, extra_env=None):
    """Shared scaffolding for scenarios that drive the REAL stack: fresh
    workdir + markers, fresh DWT_JOB_NAME / DWT_SOCKET_DIR (CLAUDE.md:
    shm segments and control sockets persist across hard kills), and the
    `run --standalone` CLI as a Popen.

    Returns (proc, workdir, ckpt_dir, marker_dir, job_name)."""
    work = tempfile.mkdtemp(prefix=f"dwt-chaos-{prefix}-")
    ckpt_dir = os.path.join(work, "ckpt")
    marker = os.path.join(work, "markers")
    os.makedirs(marker)
    script = os.path.join(work, "worker.py")
    with open(script, "w") as f:
        f.write(worker_src)
    # unique per INVOCATION, not just per process: preempt-warm runs two
    # drills back-to-back and a shared name would re-attach the second
    # run to the first's kill-surviving shm segments (CLAUDE.md)
    global _launch_seq
    _launch_seq += 1
    job = f"{prefix}{os.getpid()}n{_launch_seq}"
    env = dict(
        os.environ, DWT_JOB_NAME=job, JAX_PLATFORMS="cpu",
        DWT_SOCKET_DIR=os.path.join(work, "sockets"),
        PYTHONPATH=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) + os.pathsep +
        os.environ.get("PYTHONPATH", ""))
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "dlrover_wuqiong_tpu.run", "--standalone",
         "--nproc_per_node=1", f"--max_restarts={max_restarts}", script,
         ckpt_dir, marker] + [str(a) for a in args],
        env=env, cwd=work, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    return proc, work, ckpt_dir, marker, job


# ------------------------------------------------------------------ pod kill


_POD_KILL_WORKER = r"""
import os, sys, time
import numpy as np

from dlrover_wuqiong_tpu.trainer.elastic import init_elastic
from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
    FlashCheckpointer, StorageType)

ckpt_dir, marker_dir, total_steps = sys.argv[1], sys.argv[2], int(sys.argv[3])
ctx = init_elastic()
restart = ctx.world.restart_count
ckpt = FlashCheckpointer(ckpt_dir, job_name=os.environ["DWT_JOB_NAME"])
template = {"w": np.zeros((64, 64), np.float32),
            "step": np.zeros((), np.int64)}
state = ckpt.load_checkpoint(template)
start = int(state["step"]) + 1 if state is not None else 0
with open(os.path.join(marker_dir, f"start_r{restart}"), "w") as f:
    f.write(str(start))
with open(os.path.join(marker_dir, f"pid_r{restart}"), "w") as f:
    f.write(str(os.getpid()))
step = start - 1  # loop may be empty when the kill landed after the
                  # final checkpoint committed
for step in range(start, total_steps):
    w = np.full((64, 64), float(step), np.float32)
    ckpt.save_checkpoint(step, {"w": w, "step": np.int64(step)},
                         storage_type=StorageType.DISK)
    ctx.report_step(step)
    with open(os.path.join(marker_dir, "progress"), "w") as f:
        f.write(str(step))
    time.sleep(0.05)
ok = ckpt.wait_latest_checkpoint(60)
with open(os.path.join(marker_dir, "done"), "w") as f:
    f.write(f"{ok} {step}")
"""


def pod_kill(kill_at_step: int = 8, total_steps: int = 20,
             timeout: float = 240.0) -> Dict:
    """External SIGKILL of the training process mid-save-window.

    Invariants: the job completes after an automatic restart; the resumed
    run starts at a checkpointed step (goodput: lost work is bounded by the
    save cadence); the final checkpoint is complete and consistent (the
    done-dir commit never exposes a torn state)."""
    import numpy as np

    from .checkpoint.checkpointer import FlashCheckpointer

    cli, work, ckpt_dir, marker, job = _launch_standalone(
        "chaos", _POD_KILL_WORKER, [total_steps], max_restarts=2)

    deadline = time.monotonic() + timeout
    killed_pid = None
    killed_at = -1  # the step actually OBSERVED when the kill landed —
    # polling can overshoot kill_at_step on a loaded host, so invariants
    # bound against this, not the request
    progress = os.path.join(marker, "progress")
    while time.monotonic() < deadline and killed_pid is None:
        try:
            seen = int(open(progress).read())
            if seen >= kill_at_step:
                killed_pid = int(open(os.path.join(marker, "pid_r0"))
                                 .read())
                os.kill(killed_pid, signal.SIGKILL)  # the chaosblade moment
                # TOCTOU: the worker can advance past `seen` (and
                # checkpoint) before the SIGKILL lands — the worker is dead
                # NOW, so the file holds the final authoritative step
                try:
                    killed_at = int(open(progress).read())
                except (OSError, ValueError):
                    killed_at = seen
                logger.info("pod-kill: SIGKILL worker pid=%d at step %d",
                            killed_pid, killed_at)
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    try:
        out, _ = cli.communicate(
            timeout=max(5.0, deadline - time.monotonic()))
    except subprocess.TimeoutExpired:
        cli.kill()
        out, _ = cli.communicate()

    report: Dict = {"scenario": "pod-kill", "killed_pid": killed_pid,
                    "killed_at_step": killed_at, "cli_rc": cli.returncode}
    report["completed"] = os.path.exists(os.path.join(marker, "done"))
    report["restarts"] = sum(
        1 for f in os.listdir(marker) if f.startswith("start_r")) - 1
    resume_file = os.path.join(marker, "start_r1")
    report["resume_step"] = (int(open(resume_file).read())
                             if os.path.exists(resume_file) else -1)
    # torn-checkpoint check: the committed latest must load completely and
    # carry self-consistent contents
    ck = FlashCheckpointer(ckpt_dir, job_name=f"{job}-verify")
    state = ck.load_checkpoint({"w": np.zeros((64, 64), np.float32),
                                "step": np.zeros((), np.int64)})
    ck.close()
    report["ckpt_intact"] = bool(
        state is not None
        and int(state["step"]) == total_steps - 1
        and np.all(np.asarray(state["w"]) == float(int(state["step"]))))
    # goodput: steps not lost to the fault / total useful steps (zero
    # lost when the resume point is past the killed step)
    if report["resume_step"] >= 0 and killed_at >= 0:
        lost = max(0, killed_at - report["resume_step"] + 1)
        report["goodput"] = round(1.0 - lost / total_steps, 3)
    report["ok"] = bool(
        report["completed"] and report["restarts"] == 1
        and 0 < report["resume_step"] <= killed_at + 1
        and report["ckpt_intact"] and cli.returncode == 0)
    if report["ok"]:
        import shutil

        shutil.rmtree(work, ignore_errors=True)
    else:
        report["cli_tail"] = out[-2000:]
        report["workdir"] = work  # kept for debugging
    return report


# ----------------------------------------------------------------- straggler


def straggler(n_nodes: int = 4, slow_node: int = 3,
              slow_factor: float = 5.0) -> Dict:
    """A CPU-stressed node steps far slower than its peers.

    Mirrors the report's chaosblade CPU-load experiment: the network-check
    sweep must name the straggler (so `--exclude-straggler` can drop it)
    and the diagnosis chain must flag it from runtime step cadence too."""
    from .common import messages as msg
    from .diagnosis.manager import (
        CheckStragglerOperator,
        DiagnosisDataManager,
        InferenceChain,
    )
    from .master.rendezvous import NetworkCheckRendezvousManager

    # 1) pre-flight: pairwise network-check sweep
    rdzv = NetworkCheckRendezvousManager()
    rdzv.update_rdzv_params(n_nodes, n_nodes, waiting_timeout=0.0)
    for nid in range(n_nodes):
        rdzv.join_rendezvous(nid, nid, 1)
    for nid in range(n_nodes):
        elapsed = slow_factor if nid == slow_node else 1.0
        rdzv.report_network_check_result(nid, True, elapsed)
    stragglers, _ = rdzv.get_straggler(threshold=2.0)

    # 2) runtime: step cadence diagnosis
    data = DiagnosisDataManager()
    now = time.time()
    for nid in range(n_nodes):
        period = 1.0 * (slow_factor if nid == slow_node else 1.0)
        for k in range(8):
            data.store_report(msg.DiagnosisReport(
                node_id=nid, payload_type="step", content=str(k),
                timestamp=now - (8 - k) * period))
    chain = InferenceChain([CheckStragglerOperator(ratio=3.0,
                                                   min_reports=6)])
    flagged = [c.node_id for c in chain.run(data)
               if c.name == "straggler"]

    report = {"scenario": "straggler", "expected": slow_node,
              "network_check_stragglers": stragglers,
              "runtime_stragglers": flagged}
    report["ok"] = (stragglers == [slow_node] and flagged == [slow_node])
    return report


# --------------------------------------------------------- network partition


def network_partition(heartbeat_timeout: float = 1.5,
                      wait: float = 3.0) -> Dict:
    """A node's control-plane link drops: heartbeats stop arriving.

    The master's heartbeat monitor must declare the node dead and relaunch
    it through the scaler (reference: network-break chaosblade experiment —
    the pod is replaced even though the process may still be running)."""
    from .common.constants import NodeEventType, NodeStatus
    from .common.global_context import get_context
    from .common.node import Node, NodeEvent
    from .master.job_manager import LocalJobManager

    ctx = get_context()
    old_timeout = ctx.node_heartbeat_timeout
    ctx.node_heartbeat_timeout = heartbeat_timeout
    try:
        jm = LocalJobManager(max_relaunch_count=3)
        for nid in range(2):
            node = jm.register_node("worker", nid, rank_index=nid)
            node.update_status(NodeStatus.RUNNING)
            node.heartbeat_time = time.time()
        t0 = time.monotonic()
        relaunched = []
        # node 1 goes silent; node 0 keeps beating — the master's dead-node
        # sweep (master.py run loop) is replayed here
        while time.monotonic() - t0 < wait and not relaunched:
            jm.get_node(0).heartbeat_time = time.time()
            for n in jm.get_dead_nodes():
                relaunched.append(n.id)
                dead = Node(n.type, n.id, rank_index=n.rank_index)
                dead.status = NodeStatus.FAILED
                dead.exit_reason = "Hang"
                jm.process_event(NodeEvent(NodeEventType.MODIFIED, dead))
            time.sleep(0.1)
        n1 = jm.get_node(1)
        report = {"scenario": "network-partition",
                  "dead_detected": relaunched,
                  "node1_relaunch_count": n1.relaunch_count}
        report["ok"] = (relaunched == [1] and n1.relaunch_count == 1)
        return report
    finally:
        ctx.node_heartbeat_timeout = old_timeout


# ------------------------------------------------------------------ preempt


_PREEMPT_WORKER = r"""
import json, os, sys, time
import numpy as np

from dlrover_wuqiong_tpu.trainer.elastic import init_elastic
from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
    FlashCheckpointer, StorageType)
from dlrover_wuqiong_tpu.telemetry import get_ledger

(ckpt_dir, marker_dir, total_steps, dt, interval, flash, with_model,
 fused) = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), float(sys.argv[4]),
    int(sys.argv[5]), sys.argv[6] == "1", sys.argv[7] == "1",
    int(sys.argv[8]))
ctx = init_elastic()
restart = ctx.world.restart_count
# the downtime split comes from the GOODPUT LEDGER, not ad-hoc timers:
# compile / restore_* / productive / rework are credited by the same
# call sites production uses (telemetry/ledger.py); the drill only adds
# cache counters the ledger does not model
led = get_ledger()
led.start()
extra = {"restart": restart, "cache_warm": False,
         "step_hits": 0, "step_misses": 0}
ledger_path = os.path.join(marker_dir, f"ledger_r{restart}.json")


def dump_ledger():
    snap = dict(led.snapshot(), **extra)
    tmp = ledger_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f)
    os.replace(tmp, ledger_path)  # a SIGKILL mid-write must not tear it


if with_model:
    # the re-mesh cost under measurement: rebuild + compile the REAL
    # train step through the persistent cache (auto/compile_cache.py) —
    # a warm restart deserializes from disk instead of recompiling
    import dataclasses
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax
    from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
    from dlrover_wuqiong_tpu.auto.compile_cache import counters
    from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig

    cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                              use_flash_attention=False, remat=False)
    h0, m0 = counters.snapshot()
    with led.window("compile"):
        res = auto_accelerate(GPT(cfg), optimizer=optax.adam(1e-2),
                              devices=jax.devices(),
                              strategy=[("fsdp", {})])
        # batch sized by the inherited device count: under pytest the
        # worker sees the conftest's 8-device XLA_FLAGS and fsdp needs
        # B % n == 0
        bs = max(4, len(jax.devices()))
        data = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (bs, 33)).astype(np.int32)
        hb = {"input_ids": data[:, :-1], "labels": data[:, 1:]}
        if fused > 1:
            # the re-mesh cost a FUSED worker pays: K changes the HLO,
            # so this is its own cache entry (auto/compile_cache.py)
            from dlrover_wuqiong_tpu.data.elastic_dataset import (
                stack_batches)
            fb = res.place_fused_batch(stack_batches([hb] * fused))
            st, m = res.fused_train_step(fused)(res.state, fb)
        else:
            b = res.place_batch(dict(hb))
            st, m = res.train_step(res.state, b)
        float(m["loss"])  # force the compile + first dispatch
    h1, m1 = counters.snapshot()
    extra.update(cache_warm=res.cache_warm, step_hits=h1 - h0,
                 step_misses=m1 - m0)
ckpt = FlashCheckpointer(ckpt_dir, job_name=os.environ["DWT_JOB_NAME"])
template = {"w": np.zeros((8, 8), np.float32),
            "step": np.zeros((), np.int64)}
# restore_* tiers are credited INSIDE engine.load (the sanctioned
# verified-restore route) — nothing to time here
state = ckpt.load_checkpoint(template)
start = int(state["step"]) + 1 if state is not None else 0
extra["start_step"] = start
# steps a PRIOR generation already executed past the restore point are
# REWORK, not productive: the shared step log knows the global high-water
prev_max = -1
try:
    with open(os.path.join(marker_dir, "steps.log")) as f:
        for ln in f:
            prev_max = max(prev_max, int(ln.split()[1]))
except (OSError, ValueError, IndexError):
    pass
dump_ledger()
with open(os.path.join(marker_dir, f"pid_r{restart}"), "w") as f:
    f.write(str(os.getpid()))
log = open(os.path.join(marker_dir, "steps.log"), "a")
step = start - 1
s = start
while s < total_steps:
    # one fused K-step dispatch: the host observes NOTHING until the
    # boundary — staging, disk saves and step reports all fire there
    # (fused=1 degenerates to the per-step loop)
    k_eff = min(fused - s % fused, total_steps - s)
    n_rework = max(0, min(s + k_eff, prev_max + 1) - s)
    if n_rework:
        with led.window("rework"):
            time.sleep(dt * n_rework)
    if k_eff - n_rework:
        with led.window("productive"):
            time.sleep(dt * (k_eff - n_rework))
    step = s + k_eff - 1
    sd = {"w": np.full((8, 8), float(step), np.float32),
          "step": np.int64(step)}
    if flash:
        # stage every BOUNDARY to shm (~free); the agent's
        # save-on-failure persists the last staged boundary when the
        # worker is killed — loss per kill is bounded by K, not interval
        ckpt.save_checkpoint(step, sd, storage_type=StorageType.MEMORY)
    if any((s + i) % interval == 0 for i in range(k_eff)) or \
        step == total_steps - 1:
        ckpt.save_checkpoint(step, sd, storage_type=StorageType.DISK)
    for i in range(k_eff):
        log.write(f"{time.time()} {s + i} {restart}\n")
    log.flush()
    ctx.report_step(step)
    dump_ledger()  # boundary-cadence: the kill sees the latest split
    s += k_eff
ok = ckpt.wait_latest_checkpoint(60)
dump_ledger()
with open(os.path.join(marker_dir, "done"), "w") as f:
    f.write(f"{ok} {step}")
"""


def _read_last_step(steps_log: str) -> int:
    """Newest executed step in a drill worker's shared steps.log."""
    try:
        with open(steps_log) as f:
            lines = f.read().splitlines()
        return int(lines[-1].split()[1]) if lines else -1
    except (OSError, ValueError, IndexError):
        return -1


def preempt(total_steps: int = 600, dt: float = 0.1,
            ckpt_interval: int = 50, kills: int = 2, seed: int = 0,
            flash: bool = True, target: float = 0.95,
            timeout: float = 420.0, model: bool = False,
            cache_dir: str = "", compile_cache: bool = True,
            fused_steps: int = 1, kill_at_steps=None,
            relaunch_always: bool = False) -> Dict:
    """Randomized preemption drill against the goodput north star.

    N SIGKILLs land at seeded-random times over the run; goodput is
    computed from STEP ACCOUNTING against wall clock:

        goodput = total_steps * dt / wall_clock_seconds

    — re-executed steps, restart latency, and resume overhead all count
    as lost time, exactly like the reference's production goodput metric
    (README.md:55-56: 69% -> 95% at GLM-65B scale).  `ckpt_interval` is
    the lever the reference tuned (flash ckpt let them drop 250 -> 10
    steps, docs/blogs/flash_checkpoint.md:40); `flash=True` additionally
    stages EVERY step to shm, so the agent's save-on-failure persists
    the last step and the loss per kill becomes interval-INDEPENDENT.

    `model=True` makes every worker generation rebuild + compile the
    REAL train step, so the report's downtime split shows what each
    restart paid: `compile_s` (re-mesh XLA cost — near zero when the
    persistent cache serves it), `restore_s` (checkpoint load, summed
    over the ledger's restore tiers), and `rework_s` (re-executed
    steps).  Every number comes from per-generation GOODPUT LEDGER
    snapshots (telemetry/ledger.py) written at fusion boundaries — the
    same attribution the live runtime exports — not drill-local timers.  `compile_cache=False` runs the
    cold-compile control (DWT_COMPILE_CACHE=0); `cache_dir` pins the
    cache location (fresh dir → first generation cold, restarts warm).

    `fused_steps=K > 1` runs the worker as the fused K-step driver
    (trainer/train_step.py): the host observes only fusion BOUNDARIES, so
    shm staging, disk saves and preemption recovery all quantize to K —
    the drill proves the boundary-only elastic contract still meets the
    goodput target (loss per kill bounded by K + restart latency, not by
    the disk interval).

    `kill_at_steps=[s1, s2, ...]` replaces the seeded wall-clock schedule
    with STEP-triggered kills: each SIGKILL lands once the worker's
    shared step log crosses the threshold.  Two runs (e.g. adaptive vs
    static cadence in `preempt_adaptive`) then take faults at identical
    step positions, so their goodput difference isolates the cadence
    policy from restart-latency jitter.

    `relaunch_always=True` disables the master's repeated-error-class
    cutoff for the drill: a SIGKILL burst classifies as `host_oom`
    (exit_code=137 is ambiguous), and three consecutive kills would
    otherwise stop relaunching — but a drill kill IS the preemption
    storm the cutoff's TRANSIENT_CLASSES carve-out exists for.
    """
    import random

    extra_env = {}
    if relaunch_always:
        extra_env["DWT_CTX_RELAUNCH_ALWAYS"] = "1"
    if model:
        extra_env["DWT_COMPILE_CACHE"] = "1" if compile_cache else "0"
        if cache_dir:
            extra_env["DWT_COMPILE_CACHE_DIR"] = cache_dir
    t_start = time.monotonic()
    cli, work, ckpt_dir, marker, job = _launch_standalone(
        "preempt", _PREEMPT_WORKER,
        [total_steps, dt, ckpt_interval, "1" if flash else "0",
         "1" if model else "0", max(1, fused_steps)],
        max_restarts=kills + 1, extra_env=extra_env)

    # kill schedule: seeded wall-clock times over the productive middle,
    # or explicit step thresholds when kill_at_steps pins the positions
    ideal = total_steps * dt
    steps_log = os.path.join(marker, "steps.log")
    if kill_at_steps is not None:
        schedule = [("step", int(s)) for s in sorted(kill_at_steps)]
        kills = len(schedule)
    else:
        rng = random.Random(seed)
        schedule = [("time", t) for t in
                    sorted(rng.uniform(0.15, 0.75) * ideal
                           for _ in range(kills))]
    killed = []
    for mode, when in schedule:
        if mode == "time":
            delay = t_start + when - time.monotonic()
            if delay > 0:
                time.sleep(delay)
        # wait out worker startup/restart: a kill scheduled before the
        # (re)launched worker wrote its pid must land, not be skipped.
        # Step-triggered kills additionally wait for the step log to
        # cross the threshold (rework included).
        pid = None
        wait_pid = time.monotonic() + (
            60.0 if mode == "time"
            else max(30.0, t_start + timeout * 0.75 - time.monotonic()))
        while time.monotonic() < wait_pid and cli.poll() is None:
            if mode == "step" and _read_last_step(steps_log) < when:
                time.sleep(0.05)
                continue
            pids = sorted((f for f in os.listdir(marker)
                           if f.startswith("pid_r")),
                          key=lambda s: int(s[5:]))
            if pids:
                try:
                    cand = int(open(os.path.join(marker, pids[-1])).read())
                    # a freshly-killed worker lingers as a zombie that
                    # still answers signal 0 — only a NEW pid counts
                    if cand not in {k["pid"] for k in killed}:
                        os.kill(cand, 0)  # alive?
                        pid = cand
                        break
                except (OSError, ValueError):
                    pass
            time.sleep(0.1)
        if pid is None:
            break
        try:
            os.kill(pid, signal.SIGKILL)
            killed.append({"t": round(time.monotonic() - t_start, 1),
                           "at_step": _read_last_step(steps_log),
                           "pid": pid})
        except OSError:
            pass
    try:
        out, _ = cli.communicate(
            timeout=max(5.0, t_start + timeout - time.monotonic()))
    except subprocess.TimeoutExpired:
        cli.kill()
        out, _ = cli.communicate()
    wall = time.monotonic() - t_start

    executed = 0
    try:
        with open(os.path.join(marker, "steps.log")) as f:
            executed = sum(1 for _ in f)
    except OSError:
        pass
    report: Dict = {
        "scenario": "preempt", "total_steps": total_steps, "dt": dt,
        "ckpt_interval": ckpt_interval, "flash": flash,
        "fused_steps": max(1, fused_steps),
        "kills": killed, "cli_rc": cli.returncode,
        "wall_s": round(wall, 1), "ideal_s": round(ideal, 1),
        "executed_steps": executed,
        "wasted_steps": max(0, executed - total_steps),
    }
    report["completed"] = os.path.exists(os.path.join(marker, "done"))
    # downtime decomposition (one GOODPUT LEDGER snapshot per worker
    # generation, telemetry/ledger.py): what each restart actually paid —
    # re-mesh compile, per-tier checkpoint restore, and re-executed work
    # — credited by the same production call sites, not drill timers.
    ledgers = []
    for name in os.listdir(marker):
        if not name.startswith("ledger_r") or name.endswith(".tmp"):
            continue
        try:
            with open(os.path.join(marker, name)) as f:
                ledgers.append(json.load(f))
        except (OSError, ValueError):
            pass
    ledgers.sort(key=lambda t: t.get("restart", 0))
    restarts_l = [t for t in ledgers if t.get("restart", 0) > 0]

    def led_s(snap, state):
        return float(snap.get("states", {}).get(state, 0.0))

    restore_states = ("restore_shm", "restore_replica", "restore_storage")
    report["downtime"] = {
        "compile_s": round(sum(led_s(t, "compile")
                               for t in restarts_l), 3),
        "compile_s_first": (round(led_s(ledgers[0], "compile"), 3)
                            if ledgers else 0.0),
        "restore_s": round(sum(led_s(t, st) for t in restarts_l
                               for st in restore_states), 3),
        "rework_s": round(sum(led_s(t, "rework") for t in ledgers), 3),
        "warm_restarts": sum(1 for t in restarts_l
                             if t.get("cache_warm")),
        "restarts": len(restarts_l),
    }
    # job-level ledger aggregate (sum of per-generation cumulative
    # snapshots — generations are disjoint processes, so summing is exact)
    agg: Dict[str, float] = {}
    for t in ledgers:
        for k, v in t.get("states", {}).items():
            agg[k] = agg.get(k, 0.0) + float(v)
    report["ledger"] = {
        "states": {k: round(v, 3) for k, v in sorted(agg.items())},
        "wall_s": round(sum(float(t.get("wall_s", 0.0))
                            for t in ledgers), 3),
        "generations": len(ledgers),
    }
    # goodput from STEP ACCOUNTING (useful/executed — re-executed steps
    # are the fault's waste); wall-clock goodput reported alongside (it
    # additionally charges restart latency and per-step staging, both of
    # which are fixed costs a toy-sized step exaggerates)
    report["goodput"] = (round(total_steps / executed, 4)
                         if executed >= total_steps else 0.0)
    report["goodput_wall"] = round(ideal / wall, 4) if wall > 0 else 0.0
    report["ok"] = bool(report["completed"] and cli.returncode == 0
                        and len(killed) == kills
                        and report["goodput"] >= target)
    if report["ok"]:
        import shutil

        shutil.rmtree(work, ignore_errors=True)
    else:
        report["cli_tail"] = out[-2000:]
        report["workdir"] = work
    return report


def preempt_table(total_steps: int = 600, dt: float = 0.1,
                  kills: int = 2, seed: int = 0,
                  out_dir: str = "") -> Dict:
    """The interval-vs-goodput curve (README): disk-only cadence at
    several intervals vs flash per-step staging, then two REAL-compile
    rows (model=True) contrasting warm vs cold restart compile cost —
    the downtime split makes the warm-pool win visible per-component,
    not just in aggregate goodput.

    The curve is also the adaptive-policy engine's OFFLINE PRIOR
    (brain/policy.py load_prior calibrates step time + checkpoint cost
    from it): rows persist atomically to `out_dir/policy/
    preempt_table.json` (default `$DWT_CKPT_DIR` or the system tmp dir)
    and the report carries `table_path` for `--policy-prior`."""
    rows = []
    # (interval, flash, model, compile_cache)
    grid = [(200, False, False, True), (50, False, False, True),
            (10, False, False, True), (50, True, False, True),
            (50, True, True, True), (50, True, True, False)]
    for interval, flash, model, compile_cache in grid:
        cache = (tempfile.mkdtemp(prefix="dwt-warmtbl-")
                 if model and compile_cache else "")
        r = preempt(total_steps=total_steps, dt=dt,
                    ckpt_interval=interval, kills=kills, seed=seed,
                    flash=flash, target=0.0, model=model,
                    cache_dir=cache, compile_cache=compile_cache)
        row = {"interval": interval, "flash": flash,
               "goodput": r["goodput"],
               "wasted_steps": r["wasted_steps"],
               "kills_landed": len(r["kills"]),
               "completed": r["completed"]}
        if model:
            row["compile_cache"] = compile_cache
            row["downtime"] = r["downtime"]
        rows.append(row)
        print(json.dumps(row), flush=True)
        if cache:
            import shutil

            shutil.rmtree(cache, ignore_errors=True)
    # a row where a scheduled kill never landed is NOT a valid curve
    # point — its goodput would be inflated silently
    report = {"scenario": "preempt-table", "rows": rows,
              "ok": all(r["completed"] and r["kills_landed"] == kills
                        for r in rows)}
    base = out_dir or os.getenv("DWT_CKPT_DIR", "") or tempfile.gettempdir()
    path = os.path.join(base, "policy", "preempt_table.json")
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"dt": dt, "total_steps": total_steps,
                       "kills": kills, "rows": rows}, f)
        os.replace(tmp, path)  # a crashed writer never tears the prior
        report["table_path"] = path
    except OSError:
        logger.warning("preempt-table: persisting %s failed", path,
                       exc_info=True)
        report["table_path"] = ""
    return report


def preempt_fused(total_steps: int = 300, dt: float = 0.05,
                  kills: int = 2, seed: int = 3,
                  fused_steps: int = 5) -> Dict:
    """Preemption drill with the fused K-step driver: elastic hooks
    (shm staging, disk saves, recovery) fire at fusion boundaries ONLY,
    and the goodput north star must still hold — the boundary
    quantization loses at most K-1 steps per kill, which flash staging
    keeps well inside the >=0.95 target at K=5."""
    r = preempt(total_steps=total_steps, dt=dt, ckpt_interval=50,
                kills=kills, seed=seed, flash=True, target=0.95,
                fused_steps=fused_steps)
    r["scenario"] = "preempt-fused"
    return r


def preempt_warm(total_steps: int = 120, dt: float = 0.05,
                 kills: int = 1, seed: int = 1,
                 timeout: float = 420.0) -> Dict:
    """Warm-restart proof: identical preemption drills, one compiling
    through the persistent cache (fresh dir — generation 0 cold, every
    restart served from disk), one with the cache disabled (every
    generation recompiles).  The headline number is `compile_s_saved`:
    the per-re-mesh compile time the warm path reclaims, which is
    exactly what the goodput accounting charges as dead time."""
    cache = tempfile.mkdtemp(prefix="dwt-warmdrill-")
    try:
        warm = preempt(total_steps=total_steps, dt=dt, ckpt_interval=20,
                       kills=kills, seed=seed, flash=True, target=0.0,
                       timeout=timeout, model=True, cache_dir=cache,
                       compile_cache=True)
        cold = preempt(total_steps=total_steps, dt=dt, ckpt_interval=20,
                       kills=kills, seed=seed, flash=True, target=0.0,
                       timeout=timeout, model=True,
                       compile_cache=False)
    finally:
        import shutil

        shutil.rmtree(cache, ignore_errors=True)
    saved = round(cold["downtime"]["compile_s"]
                  - warm["downtime"]["compile_s"], 3)
    report = {
        "scenario": "preempt-warm",
        "warm": {k: warm[k] for k in ("downtime", "goodput",
                                      "goodput_wall", "completed")},
        "cold": {k: cold[k] for k in ("downtime", "goodput",
                                      "goodput_wall", "completed")},
        "compile_s_saved": saved,
        "kills_landed": min(len(warm["kills"]), len(cold["kills"])),
    }
    report["ok"] = bool(
        warm["completed"] and cold["completed"]
        and len(warm["kills"]) == kills and len(cold["kills"]) == kills
        and warm["downtime"]["warm_restarts"]
        == warm["downtime"]["restarts"] > 0
        and cold["downtime"]["warm_restarts"] == 0
        and saved > 0)
    return report


# ---------------------------------------------------------- preempt adaptive


_ADAPTIVE_WORKER = r"""
import dataclasses, json, os, sys, time
import numpy as np

from dlrover_wuqiong_tpu.trainer.elastic import init_elastic
from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
    FlashCheckpointer, StorageType)
from dlrover_wuqiong_tpu.telemetry import get_ledger

(ckpt_dir, marker_dir, total_steps, dt, poll_steps, interval0) = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), float(sys.argv[4]),
    int(sys.argv[5]), int(sys.argv[6]))
ctx = init_elastic()
restart = ctx.world.restart_count
led = get_ledger()
led.start()
extra = {"restart": restart, "start_hits": 0, "start_misses": 0,
         "kchange_hits": 0, "kchange_misses": 0, "kchanges": [],
         "decisions": []}
ledger_path = os.path.join(marker_dir, f"ledger_r{restart}.json")


def dump_ledger():
    snap = dict(led.snapshot(), **extra)
    tmp = ledger_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f)
    os.replace(tmp, ledger_path)


# real model through the persistent compile cache, same build the
# warm-pool child replays (optax.adamw(3e-4), nano GPT, fsdp, abstract
# [8, 32] batch): the drill pre-warms the pool, so EVERY generation's
# startup compile and every policy fused-K switch must be cache HITS
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import optax
from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
from dlrover_wuqiong_tpu.auto.compile_cache import counters
from dlrover_wuqiong_tpu.auto.warm_pool import WarmPool, WarmSpec, model_spec
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig

cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                          use_flash_attention=False, remat=False)
model = GPT(cfg)
h0, m0 = counters.snapshot()
with led.window("compile"):
    res = auto_accelerate(model, optimizer=optax.adamw(3e-4),
                          devices=jax.devices(), strategy=[("fsdp", {})],
                          materialize=False)
    bsh = res.batch_sharding_fn(2, None, 0)
    ab = {"input_ids": jax.ShapeDtypeStruct((8, 32), jnp.int32,
                                            sharding=bsh),
          "labels": jax.ShapeDtypeStruct((8, 32), jnp.int32,
                                         sharding=bsh)}
    res.train_step.lower(res.state, ab).compile()
h1, m1 = counters.snapshot()
extra.update(start_hits=h1 - h0, start_misses=m1 - m0)
pool = WarmPool(os.environ["DWT_COMPILE_CACHE_DIR"])
knobs = {"interval": interval0, "cur_k": 1, "pending_k": None,
         "last_id": 0}


def spec_at(k):
    return WarmSpec(n_devices=len(jax.devices()),
                    strategy=[["fsdp", {}]], model=model_spec(model),
                    batch_shape=[8, 32], platform="cpu", fused_steps=k)


def switch_k(k):
    # fused-K cutover contract (trainer._prewarm_fused_k): only when the
    # pool holds a READY entry at the new K — otherwise kick a warm
    # compile and stay at the current K until a later boundary
    if pool._ready_entry_for(spec_at(k).spec_key()) is None:
        pool.warm_async(spec_at(k))
        return False
    hh0, mm0 = counters.snapshot()
    with led.window("compile"):
        bshk = res.batch_sharding_fn(3, None, 1)
        abk = {"input_ids": jax.ShapeDtypeStruct((k, 8, 32), jnp.int32,
                                                 sharding=bshk),
               "labels": jax.ShapeDtypeStruct((k, 8, 32), jnp.int32,
                                              sharding=bshk)}
        res.fused_train_step(k).lower(res.state, abk).compile()
    hh1, mm1 = counters.snapshot()
    extra["kchange_hits"] += hh1 - hh0
    extra["kchange_misses"] += mm1 - mm0
    extra["kchanges"].append({"k": k, "hits": hh1 - hh0,
                              "misses": mm1 - mm0})
    return True


dlog = open(os.path.join(marker_dir, "decisions.log"), "a")


def poll_policy():
    try:
        d = ctx.mc.get_policy_decision()
    except Exception:  # master outage: next boundary retries
        return
    if d.decision_id <= knobs["last_id"]:
        return
    knobs["last_id"] = d.decision_id
    seen = {"id": d.decision_id, "interval": d.ckpt_interval_steps,
            "fused": d.fused_steps, "replicas": d.replica_count,
            "route": d.recovery_route, "tier": d.preferred_tier,
            "restart": restart}
    extra["decisions"].append(seen)
    dlog.write(json.dumps(seen) + "\n")
    dlog.flush()
    if d.ckpt_interval_steps > 0:
        knobs["interval"] = d.ckpt_interval_steps
    if d.fused_steps > 1 and d.fused_steps != knobs["cur_k"]:
        knobs["pending_k"] = d.fused_steps
    elif d.fused_steps == 1:
        knobs["cur_k"] = 1
        knobs["pending_k"] = None


ckpt = FlashCheckpointer(ckpt_dir, job_name=os.environ["DWT_JOB_NAME"])
template = {"w": np.zeros((8, 8), np.float32),
            "step": np.zeros((), np.int64)}
state = ckpt.load_checkpoint(template)
start = int(state["step"]) + 1 if state is not None else 0
extra["start_step"] = start
prev_max = -1
try:
    with open(os.path.join(marker_dir, "steps.log")) as f:
        for ln in f:
            prev_max = max(prev_max, int(ln.split()[1]))
except (OSError, ValueError, IndexError):
    pass
poll_policy()  # a restarted generation adopts the live cadence at once
dump_ledger()
with open(os.path.join(marker_dir, f"pid_r{restart}"), "w") as f:
    f.write(str(os.getpid()))
log = open(os.path.join(marker_dir, "steps.log"), "a")
step = start - 1
s = start
while s < total_steps:
    if knobs["pending_k"] is not None and switch_k(knobs["pending_k"]):
        knobs["cur_k"] = knobs["pending_k"]
        knobs["pending_k"] = None
    k = knobs["cur_k"]
    k_eff = min(k - s % k, total_steps - s)
    n_rework = max(0, min(s + k_eff, prev_max + 1) - s)
    if n_rework:
        with led.window("rework"):
            time.sleep(dt * n_rework)
    if k_eff - n_rework:
        with led.window("productive"):
            time.sleep(dt * (k_eff - n_rework))
    step = s + k_eff - 1
    if any((s + i) % knobs["interval"] == 0 for i in range(k_eff)) or \
            step == total_steps - 1:
        sd = {"w": np.full((8, 8), float(step), np.float32),
              "step": np.int64(step)}
        ckpt.save_checkpoint(step, sd, storage_type=StorageType.DISK)
    for i in range(k_eff):
        log.write(f"{time.time()} {s + i} {restart}\n")
    log.flush()
    ctx.report_step(step)
    if any((s + i) % poll_steps == 0 for i in range(k_eff)):
        poll_policy()
    dump_ledger()
    s += k_eff
ok = ckpt.wait_latest_checkpoint(60)
dump_ledger()
with open(os.path.join(marker_dir, "done"), "w") as f:
    f.write(f"{ok} {step}")
"""


def _ledger_goodput(states: Dict) -> float:
    """Goodput from the GOODPUT LEDGER's own attribution (productive vs
    re-executed work), not drill timers: generations are disjoint
    processes, so summed cumulative snapshots divide exactly."""
    productive = float(states.get("productive", 0.0))
    rework = float(states.get("rework", 0.0))
    total = productive + rework
    return round(productive / total, 4) if total > 0 else 0.0


def preempt_adaptive(total_steps: int = 600, dt: float = 0.05,
                     kill_at_steps=(260, 330, 390),
                     static_interval: int = 200, margin: float = 0.08,
                     floor: float = 0.7, policy_prior: str = "",
                     timeout: float = 420.0) -> Dict:
    """Closed-loop acceptance drill: adaptive policy vs static cadence.

    The failure regime shifts mid-run — quiet, then a kill burst at
    fixed STEP positions, then quiet again (the 1%/hr → 10%/hr → 1%/hr
    pattern scaled to drill time).  Two runs take the identical fault
    schedule:

    - **baseline**: `preempt()` at the static `static_interval` cadence;
    - **adaptive**: the real stack with a SEPARATE journaled master
      running the policy engine (`--policy`), seeded from a
      preempt-table prior (`--policy-prior`); each worker SIGKILL feeds
      the EWMA preemption-rate estimator through the agent's
      NodeFailure report, and the worker adopts the re-tuned cadence /
      fused-K at fusion boundaries.

    Invariants:

    - adaptive goodput beats baseline by >= `margin` (and clears
      `floor`) on BOTH metrics — the gated one is ledger-derived
      (productive vs rework, the runtime's own attribution) with step
      accounting as a cross-check: the burst collapses the Young–Daly interval,
      so re-executed work shrinks while the static run keeps losing up
      to `static_interval` steps per kill;
    - the decision history TIGHTENS under the burst (min interval below
      the first quiet-regime decision) and raises protection (replica
      ring + warm route);
    - fused-K switches NEVER pay a cold compile: every generation's
      startup and every K cutover is served by the pre-warmed pool
      (compile-cache miss counters stay zero);
    - the master is SIGKILLed mid-run after the burst and restarted on
      the same journal: the decision history served afterwards preserves
      the pre-kill prefix, and the full history is reconstructable from
      the journal files alone (offline `MasterJournal.load`).
    """
    from .common.comm import addr_connectable, find_free_port

    kill_at_steps = sorted(int(s) for s in kill_at_steps)
    kills = len(kill_at_steps)
    report: Dict = {"scenario": "preempt-adaptive",
                    "kill_at_steps": kill_at_steps,
                    "static_interval": static_interval, "margin": margin}

    # ---- static-cadence baseline on the identical fault schedule
    baseline = preempt(total_steps=total_steps, dt=dt,
                       ckpt_interval=static_interval, flash=False,
                       target=0.0, timeout=timeout,
                       kill_at_steps=kill_at_steps, relaunch_always=True)
    report["baseline"] = {k: baseline.get(k) for k in
                          ("goodput", "goodput_wall", "executed_steps",
                           "completed", "cli_rc")}
    report["baseline"]["goodput_ledger"] = _ledger_goodput(
        baseline.get("ledger", {}).get("states", {}))
    report["baseline_kills_landed"] = len(baseline.get("kills", []))

    # ---- pre-warm the pool at K=1 and the quiet-regime ladder K so the
    # adaptive worker's startup and fused-K cutovers are cache hits
    import dataclasses as _dc

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from .auto.warm_pool import WarmPool, WarmSpec, model_spec
    from .models.gpt import GPT, GPTConfig

    cache = tempfile.mkdtemp(prefix="dwt-adaptive-cache-")
    mspec = model_spec(GPT(_dc.replace(
        GPTConfig.nano(), dtype=jnp.float32, use_flash_attention=False,
        remat=False)))
    n_dev = len(jax.devices())
    pool = WarmPool(cache)
    for k in (1, 4):
        pool.warm_async(WarmSpec(
            n_devices=n_dev, strategy=[["fsdp", {}]], model=mspec,
            batch_shape=[8, 32], platform="cpu", fused_steps=k))
    if not pool.wait(timeout=300):
        report.update(ok=False, error="warm-pool prewarm failed",
                      pool=pool.status())
        return report

    # ---- adaptive run: journaled master with the policy engine
    work = tempfile.mkdtemp(prefix="dwt-chaos-adaptive-")
    marker = os.path.join(work, "markers")
    journal_dir = os.path.join(work, "journal")
    os.makedirs(marker)
    script = os.path.join(work, "worker.py")
    with open(script, "w") as f:
        f.write(_ADAPTIVE_WORKER)
    prior = policy_prior
    if not prior:
        # drill-scale prior: the same shape preempt_table persists, with
        # regime thresholds sized for a ~minute-long run (config block —
        # brain/policy.py load_prior).  Curve rows calibrate C≈0.1s.
        prior = os.path.join(work, "prior.json")
        with open(prior, "w") as f:
            json.dump({
                "dt": dt, "kills": kills,
                "rows": [{"interval": 10, "goodput": 0.78},
                         {"interval": 200, "goodput": 0.97}],
                "config": {"tau_s": 20.0, "min_interval_steps": 10,
                           "max_interval_steps": static_interval,
                           "replica_mtbf_s": 60.0, "warm_mtbf_s": 300.0,
                           "hysteresis": 0.2,
                           "fused_ladder": [[4, 300.0]]},
            }, f)
    global _launch_seq
    _launch_seq += 1
    job = f"adaptive{os.getpid()}n{_launch_seq}"
    port = find_free_port()
    addr = f"127.0.0.1:{port}"
    env = dict(
        os.environ, DWT_JOB_NAME=job, JAX_PLATFORMS="cpu",
        DWT_SOCKET_DIR=os.path.join(work, "sockets"),
        # the kill burst is a preemption storm, not a crash loop: keep
        # relaunching through 3 consecutive SIGKILLs (same as baseline)
        DWT_CTX_RELAUNCH_ALWAYS="1",
        DWT_COMPILE_CACHE="1", DWT_COMPILE_CACHE_DIR=cache,
        PYTHONPATH=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) + os.pathsep +
        os.environ.get("PYTHONPATH", ""))

    def spawn_master():
        return subprocess.Popen(
            [sys.executable, "-m", "dlrover_wuqiong_tpu.master",
             f"--port={port}", "--min_nodes=1", "--max_nodes=1",
             f"--journal-dir={journal_dir}", "--poll-interval=0.25",
             "--policy", f"--policy-prior={prior}"],
            env=env, cwd=work, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    t_start = time.monotonic()
    master = spawn_master()
    cli = None
    out = ""
    tightened = protected = prefix_ok = False
    try:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not addr_connectable(addr):
            time.sleep(0.1)
        if not addr_connectable(addr):
            report.update(ok=False, error="master never came up")
            return report
        cli_env = dict(env, DWT_MASTER_ADDR=addr)
        cli = subprocess.Popen(
            [sys.executable, "-m", "dlrover_wuqiong_tpu.run",
             "--nnodes=1", "--nproc_per_node=1",
             f"--max_restarts={kills + 1}", script,
             os.path.join(work, "ckpt"), marker, str(total_steps),
             str(dt), "10", str(static_interval)],
            env=cli_env, cwd=work, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

        # step-triggered kill burst, identical to the baseline schedule
        steps_log = os.path.join(marker, "steps.log")
        killed = []
        for threshold in kill_at_steps:
            pid = None
            wait_pid = time.monotonic() + max(
                30.0, t_start + timeout * 0.75 - time.monotonic())
            while time.monotonic() < wait_pid and cli.poll() is None:
                if _read_last_step(steps_log) < threshold:
                    time.sleep(0.05)
                    continue
                pids = sorted((f for f in os.listdir(marker)
                               if f.startswith("pid_r")),
                              key=lambda s: int(s[5:]))
                if pids:
                    try:
                        cand = int(open(os.path.join(
                            marker, pids[-1])).read())
                        if cand not in {p["pid"] for p in killed}:
                            os.kill(cand, 0)
                            pid = cand
                            break
                    except (OSError, ValueError):
                        pass
                time.sleep(0.1)
            if pid is None:
                break
            try:
                os.kill(pid, signal.SIGKILL)
                killed.append({"t": round(time.monotonic() - t_start, 1),
                               "at_step": _read_last_step(steps_log),
                               "pid": pid})
            except OSError:
                pass
        report["kills"] = killed

        # ---- SIGKILL the master after the burst; pre-kill history must
        # survive the journal replay as an identical prefix
        from .agent.master_client import MasterClient

        mc = MasterClient(addr, node_id=9999)
        history_before: list = []
        h_deadline = time.monotonic() + 30.0
        while time.monotonic() < h_deadline and not history_before:
            try:
                history_before = mc.get_policy_history()
            except Exception:  # noqa: BLE001
                pass
            if not history_before:
                time.sleep(0.25)
        master.kill()  # SIGKILL — replay must come from the journal
        master.wait(timeout=10)
        time.sleep(1.0)
        master = spawn_master()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not addr_connectable(addr):
            time.sleep(0.05)

        try:
            out, _ = cli.communicate(
                timeout=max(10.0, t_start + timeout - time.monotonic()))
        except subprocess.TimeoutExpired:
            cli.kill()
            out, _ = cli.communicate()

        history_after: list = []
        try:
            history_after = mc.get_policy_history()
        except Exception:  # noqa: BLE001
            pass

        # ------------------------------------------------------ invariants
        report["cli_rc"] = cli.returncode
        report["completed"] = os.path.exists(os.path.join(marker, "done"))
        report["worker_generations"] = sum(
            1 for f in os.listdir(marker) if f.startswith("pid_r"))
        executed = 0
        try:
            with open(steps_log) as f:
                executed = sum(1 for _ in f)
        except OSError:
            pass
        report["executed_steps"] = executed
        adaptive_goodput = (round(total_steps / executed, 4)
                            if executed >= total_steps else 0.0)
        report["goodput"] = adaptive_goodput

        ledgers = []
        for name in os.listdir(marker):
            if not name.startswith("ledger_r") or name.endswith(".tmp"):
                continue
            try:
                with open(os.path.join(marker, name)) as f:
                    ledgers.append(json.load(f))
            except (OSError, ValueError):
                pass
        ledgers.sort(key=lambda t: t.get("restart", 0))
        agg: Dict[str, float] = {}
        for t in ledgers:
            for k, v in t.get("states", {}).items():
                agg[k] = agg.get(k, 0.0) + float(v)
        report["ledger"] = {
            "states": {k: round(v, 3) for k, v in sorted(agg.items())},
            "generations": len(ledgers)}
        report["goodput_ledger"] = _ledger_goodput(agg)
        report["warm"] = {
            "start_misses": sum(t.get("start_misses", 0)
                                for t in ledgers),
            "start_hits": sum(t.get("start_hits", 0) for t in ledgers),
            "kchange_misses": sum(t.get("kchange_misses", 0)
                                  for t in ledgers),
            "kchange_hits": sum(t.get("kchange_hits", 0)
                                for t in ledgers),
            "kchanges": [c for t in ledgers
                         for c in t.get("kchanges", [])]}

        decisions = []
        try:
            with open(os.path.join(marker, "decisions.log")) as f:
                for ln in f:
                    decisions.append(json.loads(ln))
        except (OSError, ValueError):
            pass
        report["decisions_applied"] = decisions
        intervals = [d["interval"] for d in decisions if d["interval"] > 0]
        tightened = bool(len(intervals) >= 2
                         and min(intervals[1:]) < intervals[0])
        protected = any(d.get("replicas", 0) >= 2
                        and d.get("route") == "warm" for d in decisions)

        def _did(d):
            if isinstance(d, dict):
                return int(d.get("decision_id", 0))
            return int(getattr(d, "decision_id", 0) or 0)

        ids_before = [_did(d) for d in history_before]
        ids_after = [_did(d) for d in history_after]
        report["history"] = {"before_kill": ids_before,
                             "after_replay": ids_after}
        prefix_ok = bool(ids_before
                         and ids_after[:len(ids_before)] == ids_before)
        return report
    finally:
        if master.poll() is None:
            master.terminate()
            try:
                master.wait(timeout=10)
            except subprocess.TimeoutExpired:
                master.kill()
        if cli is not None and cli.poll() is None:
            cli.kill()
        # decision log reconstructable from the JOURNAL ALONE: load the
        # snapshot + frames offline (master stopped) and compare ids
        journal_ids: list = []
        try:
            from .master.journal import MasterJournal

            snap, entries = MasterJournal(journal_dir, fsync=False).load()
            rebuilt = list((snap or {}).get("policy") or [])
            rebuilt += [e["data"]["decision"] for e in entries
                        if e.get("kind") == "policy"]
            journal_ids = sorted({
                int(d["decision_id"] if isinstance(d, dict)
                    else d.decision_id) for d in rebuilt})
        except Exception:  # noqa: BLE001
            logger.warning("journal reconstruction failed", exc_info=True)
        report["journal_decision_ids"] = journal_ids
        ids_after = report.get("history", {}).get("after_replay", [])
        report["journal_matches_history"] = bool(
            ids_after and journal_ids
            and set(ids_after).issubset(set(journal_ids)))
        baseline_ok = bool(
            report["baseline"]["completed"]
            and report["baseline"]["cli_rc"] == 0
            and report["baseline_kills_landed"] == kills)
        report["ok"] = bool(
            baseline_ok
            and report.get("completed") and report.get("cli_rc") == 0
            and len(report.get("kills", [])) == kills
            # the gated metric is LEDGER-derived (the runtime's own
            # attribution), with step accounting as a cross-check
            and report.get("goodput_ledger", 0.0)
            >= report["baseline"]["goodput_ledger"] + margin
            and report.get("goodput", 0.0)
            >= report["baseline"]["goodput"] + margin
            and report.get("goodput", 0.0) >= floor
            and len(report.get("decisions_applied", [])) >= 2
            and tightened and protected
            and report.get("warm", {}).get("kchange_hits", 0) >= 1
            and report.get("warm", {}).get("kchange_misses", 1) == 0
            and report.get("warm", {}).get("start_misses", 1) == 0
            and prefix_ok and report["journal_matches_history"])
        report["adaptation"] = {"tightened": tightened,
                                "protected": protected,
                                "history_prefix_preserved": prefix_ok}
        if report["ok"]:
            import shutil

            shutil.rmtree(work, ignore_errors=True)
            shutil.rmtree(cache, ignore_errors=True)
        else:
            report["cli_tail"] = (out or "")[-3000:]
            report["workdir"] = work


# ------------------------------------------------------------- ckpt corrupt


_CKPT_CORRUPT_SAVER = r"""
import os, sys
import numpy as np

from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
    FlashCheckpointer, StorageType)

ckpt_dir = sys.argv[1]
ck = FlashCheckpointer(ckpt_dir, job_name=os.environ["DWT_JOB_NAME"],
                       standalone=True)
ck.save_checkpoint(2, {"w": np.full((16, 16), 2.0, np.float32),
                       "step": np.int64(2)},
                   storage_type=StorageType.DISK)
assert ck.wait_latest_checkpoint(60)
# arm the crash: the NEXT persist hard-exits right after the shard file
# write, before meta/manifest — the SIGKILL-mid-persist moment
os.environ["DWT_CKPT_CRASH_POINT"] = "after-bin"
ck.save_checkpoint(4, {"w": np.full((16, 16), 4.0, np.float32),
                       "step": np.int64(4)},
                   storage_type=StorageType.DISK)
ck.wait_latest_checkpoint(60)  # unreachable: the saver dies mid-persist
"""


def ckpt_corrupt(timeout: float = 180.0) -> Dict:
    """Checkpoint trust-boundary drill: the full corruption fault matrix.

    Runs a live flash-checkpoint job (engine + in-process async saver +
    replica ring), commits generations {2, 4, 6}, snapshots the exact
    expected state, then injects each fault and asserts three invariants
    per case: (1) zero silent restores — the corruption is DETECTED (it
    appears in the restore report's fallbacks, or the torn generation is
    invisible by construction); (2) the restore selects the best healthy
    tier and the resumed state is BIT-IDENTICAL to the uncorrupted
    baseline for the step it claims; (3) after a degraded restore the
    recovered state is re-staged into shm / re-replicated (self-heal),
    so the next load takes the fast tier again.

    Faults: flipped byte in shm; flipped byte in storage; truncated
    shard file; missing manifest; stale-generation-only; corrupt replica
    blob (falls through to storage); SIGKILL mid-persist (subprocess
    saver hard-killed between shard write and manifest publish — restore
    falls back to generation N-1 and the doctor flags the torn dir).

    The drill also proves the telemetry contract: a degraded restore
    must reconstruct as ONE trace tree (`ckpt:restore` root + per-tier
    children) from a flight-recorder dump alone, and the goodput ledger
    must carry nonzero `restore_replica`/`restore_storage` credits.
    """
    import shutil

    import numpy as np

    from .checkpoint.checkpointer import FlashCheckpointer, StorageType
    from .checkpoint.ckpt_saver import AsyncCheckpointSaver
    from .checkpoint.integrity import QUARANTINE_DIR
    from .checkpoint.replica import CkptReplicaManager, ReplicaServer

    work = tempfile.mkdtemp(prefix="dwt-chaos-ckptcorrupt-")
    os.environ.setdefault("DWT_SOCKET_DIR", "/tmp/dwt/sockets")
    global _launch_seq
    _launch_seq += 1
    job = f"ckc{os.getpid()}n{_launch_seq}"
    ckpt_dir = os.path.join(work, "ckpt")
    cases = []
    report: Dict = {"scenario": "ckpt-corrupt", "cases": cases}

    def expected(step):
        return {"w": np.full((16, 16), float(step), np.float32),
                "step": np.int64(step)}

    def resume_step(w):
        # one deterministic "training step" — bit-identical resume means
        # this produces byte-equal results from restored vs. baseline
        import jax
        import jax.numpy as jnp

        return np.asarray(jax.jit(
            lambda x: x * jnp.float32(1.0001) + jnp.float32(1.0))(
                jnp.asarray(w)))

    def check(name, restored, rep, want_step, want_tier, extra_ok=True):
        exp = expected(want_step)
        identical = bool(
            restored is not None
            and np.array_equal(np.asarray(restored["w"]), exp["w"])
            and int(restored["step"]) == want_step
            and np.array_equal(resume_step(restored["w"]),
                               resume_step(exp["w"])))
        case = {"fault": name, "tier": rep.get("tier"),
                "step": rep.get("step"),
                "fallbacks": rep.get("fallbacks", []),
                "healed": rep.get("healed", False),
                "bit_identical": identical,
                "ok": bool(identical and rep.get("tier") == want_tier
                           and rep.get("step") == want_step and extra_ok)}
        cases.append(case)
        return case["ok"]

    AsyncCheckpointSaver.reset()
    srv = ReplicaServer()
    srv.start()
    template = {"w": np.zeros((16, 16), np.float32), "step": np.int64(0)}
    mgr = None
    ck = None
    try:
        addr = f"127.0.0.1:{srv.port}"
        # rank 1 is the REMOTE peer holding our backups (rank 0 itself
        # has no server entry: the ring walk refuses to ship a segment
        # back to its creator's own address)
        mgr = CkptReplicaManager(rank=0, peers={1: addr},
                                 job_name=job, replica_count=1)
        ck = FlashCheckpointer(ckpt_dir, job_name=job, standalone=True,
                               replica_fetch=mgr.restore)
        for s in (2, 4, 6):
            ck.save_checkpoint(s, expected(s),
                               storage_type=StorageType.DISK)
            assert ck.wait_latest_checkpoint(60), f"commit of step {s}"
        mgr.backup()  # peer now holds the verified step-6 segment

        shm = ck.engine._shm_handler  # noqa: SLF001 — drill injects faults

        def flip_shm():
            buf = shm._buf.buf  # noqa: SLF001
            buf[1 << 20] = (buf[1 << 20] + 1) % 256

        # --- 1) flipped byte in shm, valid replica -> replica tier serves
        flip_shm()
        restored = ck.load_checkpoint(template)
        rep = ck.last_restore_report
        ok1 = check("shm-flip->replica", restored, rep, 6, "replica",
                    extra_ok=any(f["tier"] == "shm"
                                 for f in rep["fallbacks"]))
        # self-heal: the fetched segment re-verifies, next load is shm
        restored = ck.load_checkpoint(template)
        ok1 = ok1 and ck.last_restore_report["tier"] == "shm"
        cases[-1]["ok"] = ok1

        # --- 2) flipped byte in shm AND in the replica blob -> storage
        flip_shm()
        with srv._lock:  # noqa: SLF001 — corrupt the held backup
            step6, blob = srv._store[0]
            bad = bytearray(blob)
            bad[1 << 20] ^= 0xFF
            srv._store[0] = (step6, bytes(bad))
        restored = ck.load_checkpoint(template)
        rep = ck.last_restore_report
        check("shm+replica-flip->storage", restored, rep, 6, "storage",
              extra_ok=(any(f["tier"] == "shm" for f in rep["fallbacks"])
                        and rep["healed"]))

        # --- 3) flipped byte in the newest storage generation
        shm.mark_empty()
        import glob as _glob

        bin6 = _glob.glob(os.path.join(
            ckpt_dir, "checkpoint-6", "shards_rank*.bin"))[0]
        raw = bytearray(open(bin6, "rb").read())
        raw[64] ^= 0x01
        open(bin6, "wb").write(raw)
        restored = ck.load_checkpoint(template)
        rep = ck.last_restore_report
        qdir = os.path.join(ckpt_dir, QUARANTINE_DIR)
        check("storage-flip->older-gen", restored, rep, 4, "storage",
              extra_ok=(any(f.get("step") == 6 and f.get("quarantined")
                            for f in rep["fallbacks"])
                        and os.path.isdir(qdir)))

        # --- 4) truncated shard file in the (now newest) generation
        shm.mark_empty()
        bin4 = _glob.glob(os.path.join(
            ckpt_dir, "checkpoint-4", "shards_rank*.bin"))[0]
        with open(bin4, "rb+") as f:
            f.truncate(100)
        restored = ck.load_checkpoint(template)
        rep = ck.last_restore_report
        check("truncated-leaf->older-gen", restored, rep, 2, "storage",
              extra_ok=any(f.get("reason") == "truncated-shard-file"
                           for f in rep["fallbacks"]))

        # --- 5) missing manifest on a committed generation
        shm.mark_empty()
        # rebuild a fresh committed gen 8, then rip its manifest out
        ck.save_checkpoint(8, expected(8), storage_type=StorageType.DISK)
        assert ck.wait_latest_checkpoint(60)
        shm.mark_empty()
        os.remove(os.path.join(ckpt_dir, "checkpoint-8", "manifest.json"))
        restored = ck.load_checkpoint(template)
        rep = ck.last_restore_report
        check("missing-manifest->older-gen", restored, rep, 2, "storage",
              extra_ok=any(f.get("reason") == "missing-manifest"
                           for f in rep["fallbacks"]))

        # --- 6) stale generation only: tracker names a vanished gen,
        # only an OLDER committed generation survives on storage
        shm.mark_empty()
        shutil.rmtree(os.path.join(ckpt_dir, "checkpoint-2"))
        ck.save_checkpoint(1, expected(1), storage_type=StorageType.DISK)
        # wait on the generation's OWN manifest: the tracker still says 2
        # (repointed by the earlier quarantine), so the step-agnostic
        # wait_latest_checkpoint would return before the persist lands
        manifest1 = os.path.join(ckpt_dir, "checkpoint-1", "manifest.json")
        deadline = time.monotonic() + 60
        while not os.path.exists(manifest1) and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert os.path.exists(manifest1), "step-1 persist never committed"
        shm.mark_empty()
        from .common.constants import CheckpointConstant

        with open(os.path.join(ckpt_dir,  # graftlint: disable=commit-order,atomic-publish -- drill forges a stale tracker on purpose
                               CheckpointConstant.TRACKER_FILE), "w") as f:
            f.write("2")  # retention ate checkpoint-2; tracker is stale
        restored = ck.load_checkpoint(template)
        rep = ck.last_restore_report
        check("stale-generation-only", restored, rep, 1, "storage",
              extra_ok=any(f.get("reason") == "missing-generation"
                           for f in rep["fallbacks"]))
    finally:
        if ck is not None:
            try:
                ck.close()
            except Exception:  # noqa: BLE001
                pass
        AsyncCheckpointSaver.reset()
        if mgr is not None:
            mgr.close()
        srv.stop()

    # flight recorder: every restore above recorded a `ckpt:restore`
    # span with per-tier children (telemetry/spans.py via engine.load).
    # Flush the ring next to the checkpoints and prove a DEGRADED
    # restore reconstructs as one trace tree from the dump alone —
    # root + >1 distinct tier children sharing its trace_id/span_id.
    from .telemetry import get_ledger, get_recorder, load_flight_dumps

    get_recorder().flush(ckpt_dir, "drill")
    dumps = load_flight_dumps(ckpt_dir)
    spans = [e["data"] for d in dumps for e in d.get("events", [])
             if e.get("kind") == "span"]
    roots = [s for s in spans if s.get("name") == "ckpt:restore"]
    trace_trees = 0
    for root in roots:
        tiers = {s["name"] for s in spans
                 if s.get("trace_id") == root.get("trace_id")
                 and s.get("parent_span") == root.get("span_id")
                 and s.get("name", "").startswith("ckpt:restore:")}
        if len(tiers) > 1 and root.get("attrs", {}).get("fallbacks", 0):
            trace_trees += 1
    led_states = get_ledger().snapshot()["states"]
    report["flight"] = {
        "dumps": len(dumps), "restore_spans": len(roots),
        "degraded_trace_trees": trace_trees,
        "ledger": {k: round(led_states.get(k, 0.0), 4)
                   for k in ("restore_shm", "restore_replica",
                             "restore_storage")},
    }
    flight_ok = bool(dumps and trace_trees > 0
                     and led_states.get("restore_replica", 0.0) > 0
                     and led_states.get("restore_storage", 0.0) > 0)

    # --- 7) SIGKILL mid-persist (subprocess saver, crash between shard
    # write and manifest publish) -> restore serves generation N-1
    sub_work = os.path.join(work, "midpersist")
    os.makedirs(sub_work)
    sub_ckpt = os.path.join(sub_work, "ckpt")
    _launch_seq += 1
    sub_job = f"ckm{os.getpid()}n{_launch_seq}"
    env = dict(os.environ, DWT_JOB_NAME=sub_job, JAX_PLATFORMS="cpu",
               DWT_SOCKET_DIR=os.path.join(sub_work, "sockets"),
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))) + os.pathsep +
               os.environ.get("PYTHONPATH", ""))
    script = os.path.join(sub_work, "saver.py")
    with open(script, "w") as f:
        f.write(_CKPT_CORRUPT_SAVER)
    proc = subprocess.run([sys.executable, script, sub_ckpt], env=env,
                          cwd=sub_work, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True,
                          timeout=timeout)
    AsyncCheckpointSaver.reset()
    _launch_seq += 1
    verify_job = f"ckv{os.getpid()}n{_launch_seq}"
    ck2 = FlashCheckpointer(sub_ckpt, job_name=verify_job,
                            standalone=True)
    try:
        restored = ck2.load_checkpoint(
            {"w": np.zeros((16, 16), np.float32), "step": np.int64(0)})
        rep = ck2.last_restore_report
        # the dead saver's shm segment must have been reaped on startup
        # (stale-segment sweeper) — its creator pid is gone
        swept = not os.path.exists(f"/dev/shm/{sub_job}_ckpt_shm_0")
        torn_dir = os.path.join(sub_ckpt, "checkpoint-4")
        torn_detectable = (os.path.isdir(torn_dir) and not os.path.exists(
            os.path.join(torn_dir, "manifest.json")))
        identical = bool(
            restored is not None
            and np.array_equal(np.asarray(restored["w"]),
                               np.full((16, 16), 2.0, np.float32))
            and int(restored["step"]) == 2)
        cases.append({
            "fault": "sigkill-mid-persist", "tier": rep.get("tier"),
            "step": rep.get("step"), "saver_rc": proc.returncode,
            "bit_identical": identical, "swept_stale_shm": swept,
            "torn_gen_detectable": torn_detectable,
            "ok": bool(proc.returncode == 137 and identical
                       and rep.get("step") == 2 and swept
                       and torn_detectable)})
    finally:
        ck2.close()
        AsyncCheckpointSaver.reset()

    # the doctor must independently flag the torn generation
    import json as _json

    doctor = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "ckpt_doctor.py"),
         sub_ckpt], stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, timeout=60)
    try:
        verdict = _json.loads(doctor.stdout.strip().splitlines()[-1])
        bad = [g for g in verdict["ckpt_doctor"]["generations"]
               if not g["ok"]]
        report["doctor"] = {"rc": doctor.returncode,
                            "flagged_steps": [g["step"] for g in bad]}
        doctor_ok = doctor.returncode == 1 and any(
            g["step"] == 4 for g in bad)
    except (ValueError, KeyError, IndexError):
        report["doctor"] = {"rc": doctor.returncode, "parse": "failed"}
        doctor_ok = False

    report["silent_restores"] = sum(
        1 for c in cases if not c.get("bit_identical"))
    report["ok"] = bool(all(c["ok"] for c in cases) and doctor_ok
                        and flight_ok and len(cases) == 7)
    if report["ok"]:
        shutil.rmtree(work, ignore_errors=True)
    else:
        report["workdir"] = work
        if proc.stdout:
            report["saver_tail"] = proc.stdout[-1500:]
    return report


# -------------------------------------------------------------- master kill


_MASTER_KILL_WORKER = r"""
import json, os, sys, time

from dlrover_wuqiong_tpu.trainer.elastic import init_elastic
from dlrover_wuqiong_tpu.telemetry import get_ledger

(_ckpt_dir, marker_dir, dataset_size, batch, minibatches, dt) = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
    int(sys.argv[5]), float(sys.argv[6]))
ctx = init_elastic()
restart = ctx.world.restart_count
# the outage's cost surfaces in the GOODPUT LEDGER: master_client
# credits `degraded` for every second a verb burned blocked on the dead
# master, while training time through the outage stays `productive`
led = get_ledger()
led.start()
with open(os.path.join(marker_dir, f"start_r{restart}"), "w") as f:
    f.write(str(os.getpid()))
# dynamic sharding straight off the master: every fetched range and every
# completed range is logged so the drill can prove the journal replayed
# EXACTLY (no range lost, none handed out twice across the restart)
sc = ctx.sharding_client("chaos-mk", batch_size=batch,
                         dataset_size=dataset_size,
                         num_minibatches_per_shard=minibatches)
log = open(os.path.join(marker_dir, "shards.log"), "a")
step = 0
while True:
    task = sc.fetch_shard(wait=True, timeout=120.0)
    if task is None:
        break
    log.write(f"fetch {time.time():.3f} {task.task_id} "
              f"{task.shard.start} {task.shard.end}\n")
    log.flush()
    for i in range((task.shard.end - task.shard.start) // batch):
        with led.window("productive"):
            time.sleep(dt)  # one training step
        step += 1
        # per-step heartbeat: CRITICAL during the drill — these are the
        # frames that must buffer (not block, not crash) while the master
        # is dead, then drain after reconnect
        ctx.mc.report_heart_beat(step)
        log.write(f"step {time.time():.3f} {step}\n")
        log.flush()
    sc.report_shard_done(task.task_id)
    log.write(f"done {time.time():.3f} {task.task_id} "
              f"{task.shard.start} {task.shard.end}\n")
    log.flush()
stats = ctx.mc.degraded_stats()
with open(os.path.join(marker_dir, "done"), "w") as f:
    json.dump({"steps": step, "stats": stats,
               "ledger": led.snapshot()}, f)
# flight dump carries this worker's ledger + events into the incident
# timeline the drill gates on (telemetry/timeline.py): flush BEFORE
# exit so the offline assembly sees the same artifacts the live
# TimelineQuery does
from dlrover_wuqiong_tpu.telemetry import get_recorder
get_recorder().flush(_ckpt_dir, "drill-end")
"""


def master_kill(dataset_size: int = 576, batch: int = 4,
                minibatches: int = 24, dt: float = 0.08,
                outage_s: float = 1.5, target: float = 0.5,
                timeout: float = 240.0) -> Dict:
    """SIGKILL the job MASTER mid-run; restart it on the same journal.

    The reference's headline claim — no single process is fatal — applied
    to the master itself: the drill runs the real stack with the master as
    a SEPARATE process journaling every control-plane mutation
    (master/journal.py), hard-kills it while the worker is mid-shard, and
    restarts it on the same journal + port.  Invariants:

    - the worker NEVER crashes or restarts (exit clean, one generation);
    - dataset ranges tile exactly: none lost, none double-trained —
      journal replay reconstructed splitter cursors + in-flight tasks;
    - training steps land INSIDE the outage window (elastic hooks do not
      block on the dead master — heartbeats buffer in degraded mode);
    - the heartbeat buffer fully drains after reconnect, and the client
      observed the fencing-epoch bump + re-registered;
    - the worker's GOODPUT LEDGER shows the split: `degraded` (seconds
      burned blocked on the dead master) is nonzero AND `productive`
      kept accruing through the outage (telemetry/ledger.py);
    - wall-clock goodput (ideal step time / span) stays over `target`.
    """
    from .common.comm import addr_connectable, find_free_port

    work = tempfile.mkdtemp(prefix="dwt-chaos-masterkill-")
    marker = os.path.join(work, "markers")
    journal_dir = os.path.join(work, "journal")
    os.makedirs(marker)
    script = os.path.join(work, "worker.py")
    with open(script, "w") as f:
        f.write(_MASTER_KILL_WORKER)
    global _launch_seq
    _launch_seq += 1
    job = f"masterkill{os.getpid()}n{_launch_seq}"
    port = find_free_port()
    addr = f"127.0.0.1:{port}"
    env = dict(
        os.environ, DWT_JOB_NAME=job, JAX_PLATFORMS="cpu",
        DWT_SOCKET_DIR=os.path.join(work, "sockets"),
        PYTHONPATH=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) + os.pathsep +
        os.environ.get("PYTHONPATH", ""))

    def spawn_master():
        return subprocess.Popen(
            [sys.executable, "-m", "dlrover_wuqiong_tpu.master",
             f"--port={port}", "--min_nodes=1", "--max_nodes=1",
             f"--journal-dir={journal_dir}", "--poll-interval=0.5"],
            env=env, cwd=work, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    report: Dict = {"scenario": "master-kill", "outage_s": outage_s}
    master = spawn_master()
    cli = None
    out = ""
    try:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not addr_connectable(addr):
            time.sleep(0.1)
        if not addr_connectable(addr):
            report.update(ok=False, error="master never came up")
            return report
        cli_env = dict(env, DWT_MASTER_ADDR=addr)
        cli = subprocess.Popen(
            [sys.executable, "-m", "dlrover_wuqiong_tpu.run",
             "--nnodes=1", "--nproc_per_node=1", "--max_restarts=2",
             script, os.path.join(work, "ckpt"), marker,
             str(dataset_size), str(batch), str(minibatches), str(dt)],
            env=cli_env, cwd=work, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

        # kill the master just after a mid-run shard fetch: the worker is
        # then provably mid-shard through the outage window
        shards_log = os.path.join(marker, "shards.log")
        kill_after_fetches = 2
        kill_t = restart_t = -1.0
        deadline = time.monotonic() + timeout / 2
        while time.monotonic() < deadline and cli.poll() is None:
            try:
                with open(shards_log) as f:
                    fetches = sum(1 for ln in f if ln.startswith("fetch "))
                if fetches >= kill_after_fetches:
                    break
            except OSError:
                pass
            time.sleep(0.05)
        else:
            report.update(ok=False, error="worker never reached the kill "
                                          "point", cli_rc=cli.poll())
            return report
        time.sleep(dt * 2)  # be safely inside the shard's step loop
        master.kill()  # SIGKILL — no snapshot, no goodbye
        master.wait(timeout=10)
        kill_t = time.time()
        logger.info("master-kill: SIGKILLed master pid=%d", master.pid)
        time.sleep(outage_s)
        master = spawn_master()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not addr_connectable(addr):
            time.sleep(0.05)
        # kill_t/restart_t stay WALL clock: they bracket step timestamps
        # the worker logs with time.time() in another process
        restart_t = time.time()
        report["measured_outage_s"] = round(restart_t - kill_t, 2)
        # restart-the-world baseline NET of the drill's deliberate idle
        # window: process spawn + jax import + journal replay until the
        # replacement answers.  `chaos master-failover` asserts its
        # promotion gap beats this number measured in the SAME
        # environment (never a hardcoded threshold).
        report["restart_gap_s"] = round(restart_t - kill_t - outage_s, 2)

        try:
            out, _ = cli.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            cli.kill()
            out, _ = cli.communicate()

        # ------------------------------------------------------ invariants
        report["cli_rc"] = cli.returncode
        report["worker_generations"] = sum(
            1 for f in os.listdir(marker) if f.startswith("start_r"))
        done_path = os.path.join(marker, "done")
        report["completed"] = os.path.exists(done_path)
        stats: Dict = {}
        worker_ledger: Dict = {}
        if report["completed"]:
            with open(done_path) as f:
                payload = json.load(f)
            stats = payload.get("stats", {})
            report["degraded"] = stats
            worker_ledger = payload.get("ledger", {})
        led_states = worker_ledger.get("states", {})
        # the ledger is the drill's downtime split: blocked-on-dead-master
        # seconds land in `degraded`, steps through the outage stay
        # `productive` (master_client._account_degraded)
        report["ledger"] = {
            "degraded_s": round(float(led_states.get("degraded", 0.0)), 3),
            "productive_s": round(
                float(led_states.get("productive", 0.0)), 3),
            "goodput_fraction": round(
                float(worker_ledger.get("goodput_fraction", 0.0)), 4),
        }
        fetched, completed, steps = [], [], []
        try:
            with open(shards_log) as f:
                for ln in f:
                    parts = ln.split()
                    if parts[0] == "fetch":
                        fetched.append((int(parts[3]), int(parts[4])))
                    elif parts[0] == "done":
                        completed.append((int(parts[3]), int(parts[4])))
                    elif parts[0] == "step":
                        steps.append(float(parts[1]))
        except OSError:
            pass
        # exact tiling: completed ranges cover [0, dataset_size) once
        covered = sorted(completed)
        tiles_ok = (sum(e - s for s, e in covered) == dataset_size
                    and all(covered[i][1] == covered[i + 1][0]
                            for i in range(len(covered) - 1))
                    and bool(covered) and covered[0][0] == 0
                    and covered[-1][1] == dataset_size)
        report["shards_completed"] = len(completed)
        report["shards_fetched"] = len(fetched)
        report["no_shard_lost_or_double"] = bool(
            tiles_ok and len(fetched) == len(completed))
        report["steps_in_outage"] = sum(
            1 for t in steps if kill_t <= t <= restart_t)
        total_steps = dataset_size // batch
        if steps:
            span = max(steps) - min(steps) + dt
            report["goodput_wall"] = round(total_steps * dt / span, 3)
        else:
            report["goodput_wall"] = 0.0
        report["heartbeats_buffered"] = stats.get("buffered_total", 0)
        report["buffer_drained"] = (stats.get("pending", 1) == 0
                                    and stats.get("dropped_total", 1) == 0)
        report["epoch_bumped"] = 2 in stats.get("epochs_seen", [])
        report["reregistered"] = stats.get("reregistrations", 0) >= 1

        # ------------------------------------------- incident timeline gate
        # The drill's observability claim (telemetry/timeline.py): the live
        # TimelineQuery against the RESTARTED master byte-equals the offline
        # assembly from the same disk artifacts, every journaled event
        # appears exactly once in (epoch, seq) order across the fencing
        # bump, and the narrative's degraded attribution agrees with the
        # worker's own ledger.
        from .agent.master_client import MasterClient
        from .telemetry import timeline as tl

        ckpt_dir = os.path.join(work, "ckpt")
        mc = MasterClient(addr, node_id=-1)
        try:
            live = mc.get_timeline(ckpt_dir=ckpt_dir)
            # the restarted master must be running the group-commit
            # journal (the default): the drill's exactly-once claims
            # below hold UNDER batched fsync, not just per-frame
            js = mc.get_journal_stats()
            report["journal_group_commit"] = {
                "enabled": js.enabled, "group_commit": js.group_commit,
                "max_frames": js.max_frames,
                "batch_mean": round(js.batch_mean, 2),
                "durable_seq": js.durable_seq}
        finally:
            mc.close()
        offline = tl.assemble_incident(journal_dir=journal_dir,
                                       ckpt_dir=ckpt_dir)
        report["timeline_events"] = live.events
        report["timeline_byte_equal"] = (
            live.content == tl.incident_json(offline))
        jkeys = [(e["epoch"], e["seq"]) for e in offline["events"]
                 if e["source"] == "journal" and e["kind"] != "flush"]
        report["timeline_causal"] = (
            jkeys == sorted(jkeys) and len(jkeys) == len(set(jkeys))
            and len(jkeys) == offline["counts"]["journal_events"])
        report["timeline_epochs"] = offline["counts"]["epochs"]
        narr = offline["narrative"]
        deg_lost = sum(float(i.get("lost_s", 0.0))
                       for i in narr["incidents"]
                       if i.get("attributed_state") == "degraded")
        report["timeline_degraded_s"] = round(deg_lost, 3)
        report["timeline_attribution_ok"] = abs(
            deg_lost - report["ledger"]["degraded_s"]) <= 0.05
        # the offline CLI on the same artifacts must hash to the live bytes
        tools_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        p = subprocess.run(
            [sys.executable, os.path.join(tools_dir, "incident_report.py"),
             "--journal", journal_dir, "--flight", ckpt_dir],
            capture_output=True, text=True, env=env, timeout=120)
        try:
            cli_line = json.loads(p.stdout)
        except ValueError:
            cli_line = {}
        report["incident_report_rc"] = p.returncode
        report["incident_report_sha_match"] = bool(
            p.returncode == 0
            and cli_line.get("timeline_sha256")
            == tl.incident_sha256(live.content))

        report["ok"] = bool(
            report["completed"] and cli.returncode == 0
            and report["worker_generations"] == 1
            and report["no_shard_lost_or_double"]
            and report["steps_in_outage"] > 0
            and report["heartbeats_buffered"] > 0
            and report["buffer_drained"]
            and report["epoch_bumped"] and report["reregistered"]
            and report["ledger"]["degraded_s"] > 0
            and report["ledger"]["productive_s"] > 0
            and report["goodput_wall"] >= target
            and report["timeline_byte_equal"]
            and report["timeline_causal"]
            and report["timeline_epochs"] == [1, 2]
            and report["timeline_attribution_ok"]
            and report["incident_report_sha_match"]
            and report["journal_group_commit"]["enabled"]
            and report["journal_group_commit"]["group_commit"])
        return report
    finally:
        if master.poll() is None:
            master.terminate()
            try:
                master.wait(timeout=10)
            except subprocess.TimeoutExpired:
                master.kill()
        if cli is not None and cli.poll() is None:
            cli.kill()
        if report.get("ok"):
            import shutil

            shutil.rmtree(work, ignore_errors=True)
        else:
            report["cli_tail"] = (out or "")[-2000:]
            report["workdir"] = work


# ---------------------------------------------------------- master failover


def master_failover(dataset_size: int = 576, batch: int = 4,
                    minibatches: int = 24, dt: float = 0.08,
                    lease_ttl: float = 1.0, target: float = 0.5,
                    timeout: float = 300.0) -> Dict:
    """SIGKILL the PRIMARY master; a warm standby takes over, fenced.

    The master-kill drill's gap — the fleet buffering until something
    restarts the process — is the cost ISSUE 20 removes: here a standby
    (`--standby-of`) tails the primary's journal over the fetch_journal
    verb, the primary heartbeats a leadership lease into that same
    journal, and on lease expiry the standby journals a ``failover``
    frame and promotes with an epoch strictly above anything the corpse
    could issue.  Invariants:

    - the worker NEVER restarts (one generation) and its endpoint list
      ("primary,standby") fails over with at least one rotation;
    - dataset ranges tile exactly across the takeover — the standby's
      mirrored journal reconstructed cursors + in-flight tasks, and
      idem-keyed retries stay exactly-once under the NEW epoch;
    - buffered verbs drain to the new leader (pending=0, dropped=0) and
      the client observed the promoted epoch (old+2) + re-registered;
    - the promotion gap (SIGKILL → standby serving as leader, lease-ttl
      detection included) beats the restart-the-world baseline measured
      in THIS environment: reviving the corpse and timing spawn→serving
      (the same quantity master-kill reports as ``restart_gap_s``) plus
      the SAME lease-ttl detection floor — no supervisor restarts a
      master it has not yet declared dead.  Never a hardcoded number;
    - the revived corpse self-fences via its ``--peer`` probe: read
      verbs answer, mutating verbs bounce with NotLeaderError;
    - the live incident timeline from the PROMOTED master byte-equals
      the offline assembly over BOTH journal dirs merged in (epoch,
      seq) order, with the takeover narrated as incident kind
      ``failover``.
    """
    from .common.comm import (RpcClient, RpcError, addr_connectable,
                              find_free_port)
    from .common import messages as msg

    work = tempfile.mkdtemp(prefix="dwt-chaos-failover-")
    marker = os.path.join(work, "markers")
    jd_primary = os.path.join(work, "journal-primary")
    jd_standby = os.path.join(work, "journal-standby")
    os.makedirs(marker)
    script = os.path.join(work, "worker.py")
    with open(script, "w") as f:
        f.write(_MASTER_KILL_WORKER)
    global _launch_seq
    _launch_seq += 1
    job = f"failover{os.getpid()}n{_launch_seq}"
    port_p, port_sb = find_free_port(), find_free_port()
    addr_p = f"127.0.0.1:{port_p}"
    addr_sb = f"127.0.0.1:{port_sb}"
    env = dict(
        os.environ, DWT_JOB_NAME=job, JAX_PLATFORMS="cpu",
        DWT_SOCKET_DIR=os.path.join(work, "sockets"),
        PYTHONPATH=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) + os.pathsep +
        os.environ.get("PYTHONPATH", ""))

    def spawn_primary():
        return subprocess.Popen(
            [sys.executable, "-m", "dlrover_wuqiong_tpu.master",
             f"--port={port_p}", "--min_nodes=1", "--max_nodes=1",
             f"--journal-dir={jd_primary}", "--poll-interval=0.5",
             f"--lease-ttl={lease_ttl}", f"--peer={addr_sb}"],
            env=env, cwd=work, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    def spawn_standby():
        return subprocess.Popen(
            [sys.executable, "-m", "dlrover_wuqiong_tpu.master",
             f"--port={port_sb}", "--min_nodes=1", "--max_nodes=1",
             f"--journal-dir={jd_standby}", "--poll-interval=0.5",
             f"--lease-ttl={lease_ttl}", f"--standby-of={addr_p}"],
            env=env, cwd=work, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    def _probe(addr, timeout_s=2.0):
        """One JournalStatsQuery, None on any failure."""
        client = RpcClient(addr, node_id=-4, node_type="probe",
                           timeout=timeout_s, retries=1,
                           base_delay_s=0.02, max_delay_s=0.05)
        try:
            return client.get(msg.JournalStatsQuery())
        except RpcError:
            return None
        finally:
            client.close()

    report: Dict = {"scenario": "master-failover", "lease_ttl": lease_ttl}
    primary = spawn_primary()
    standby = cli = corpse = None
    out = ""
    try:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not addr_connectable(addr_p):
            time.sleep(0.1)
        if not addr_connectable(addr_p):
            report.update(ok=False, error="primary never came up")
            return report
        standby = spawn_standby()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not addr_connectable(addr_sb):
            time.sleep(0.1)
        if not addr_connectable(addr_sb):
            report.update(ok=False, error="standby never came up")
            return report
        # gate the kill on the mirror actually flowing: the primary's
        # shipping gauges go live on the standby's first fetch
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            s = _probe(addr_p)
            if s is not None and s.standby_lag_frames >= 0:
                break
            time.sleep(0.1)
        else:
            report.update(ok=False, error="standby never fetched")
            return report

        cli_env = dict(env, DWT_MASTER_ADDR=f"{addr_p},{addr_sb}")
        cli = subprocess.Popen(
            [sys.executable, "-m", "dlrover_wuqiong_tpu.run",
             "--nnodes=1", "--nproc_per_node=1", "--max_restarts=2",
             script, os.path.join(work, "ckpt"), marker,
             str(dataset_size), str(batch), str(minibatches), str(dt)],
            env=cli_env, cwd=work, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

        # kill just after a mid-run shard fetch (same point as master-kill)
        shards_log = os.path.join(marker, "shards.log")
        deadline = time.monotonic() + timeout / 2
        while time.monotonic() < deadline and cli.poll() is None:
            try:
                with open(shards_log) as f:
                    fetches = sum(1 for ln in f if ln.startswith("fetch "))
                if fetches >= 2:
                    break
            except OSError:
                pass
            time.sleep(0.05)
        else:
            report.update(ok=False, error="worker never reached the kill "
                                          "point", cli_rc=cli.poll())
            return report
        time.sleep(dt * 2)
        pre = _probe(addr_p)
        report["pre_kill"] = {
            "durable_seq": getattr(pre, "durable_seq", -1),
            "shipped_seq": getattr(pre, "shipped_seq", -1),
            "standby_lag_frames": getattr(pre, "standby_lag_frames", -2)}
        primary.kill()  # SIGKILL — no snapshot, no goodbye
        primary.wait(timeout=10)
        kill_t = time.time()
        logger.info("master-failover: SIGKILLed primary pid=%d",
                    primary.pid)

        # promotion gap: SIGKILL → the standby answering as leader
        promoted_t = -1.0
        deadline = time.monotonic() + lease_ttl * 10 + 60.0
        while time.monotonic() < deadline:
            s = _probe(addr_sb, timeout_s=1.0)
            if s is not None and s.is_leader:
                promoted_t = time.time()
                report["promoted_epoch"] = s.epoch
                break
            time.sleep(0.05)
        if promoted_t < 0:
            report.update(ok=False, error="standby never promoted")
            return report
        report["promotion_gap_s"] = round(promoted_t - kill_t, 2)

        try:
            out, _ = cli.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            cli.kill()
            out, _ = cli.communicate()

        # ------------------------------------------------------ invariants
        report["cli_rc"] = cli.returncode
        report["worker_generations"] = sum(
            1 for f in os.listdir(marker) if f.startswith("start_r"))
        done_path = os.path.join(marker, "done")
        report["completed"] = os.path.exists(done_path)
        stats: Dict = {}
        worker_ledger: Dict = {}
        if report["completed"]:
            with open(done_path) as f:
                payload = json.load(f)
            stats = payload.get("stats", {})
            report["degraded"] = stats
            worker_ledger = payload.get("ledger", {})
        led_states = worker_ledger.get("states", {})
        report["ledger"] = {
            "degraded_s": round(float(led_states.get("degraded", 0.0)), 3),
            "productive_s": round(
                float(led_states.get("productive", 0.0)), 3),
        }
        fetched, completed, steps = [], [], []
        try:
            with open(shards_log) as f:
                for ln in f:
                    parts = ln.split()
                    if parts[0] == "fetch":
                        fetched.append((int(parts[3]), int(parts[4])))
                    elif parts[0] == "done":
                        completed.append((int(parts[3]), int(parts[4])))
                    elif parts[0] == "step":
                        steps.append(float(parts[1]))
        except OSError:
            pass
        covered = sorted(completed)
        tiles_ok = (sum(e - s for s, e in covered) == dataset_size
                    and all(covered[i][1] == covered[i + 1][0]
                            for i in range(len(covered) - 1))
                    and bool(covered) and covered[0][0] == 0
                    and covered[-1][1] == dataset_size)
        report["shards_completed"] = len(completed)
        report["no_shard_lost_or_double"] = bool(
            tiles_ok and len(fetched) == len(completed))
        total_steps = dataset_size // batch
        if steps:
            span = max(steps) - min(steps) + dt
            report["goodput_wall"] = round(total_steps * dt / span, 3)
        else:
            report["goodput_wall"] = 0.0
        report["heartbeats_buffered"] = stats.get("buffered_total", 0)
        report["buffer_drained"] = (stats.get("pending", 1) == 0
                                    and stats.get("dropped_total", 1) == 0)
        report["client_failovers"] = stats.get("failovers", 0)
        promoted_epoch = report.get("promoted_epoch", -1)
        report["epoch_fenced"] = promoted_epoch in stats.get(
            "epochs_seen", [])
        report["reregistered"] = stats.get("reregistrations", 0) >= 1

        # ------------------------------------- restart-the-world baseline
        # revive the corpse on its own journal: spawn→serving is exactly
        # the restart_gap_s master-kill measures, in the SAME environment.
        # The full restart-the-world cost ADDS the detection floor: no
        # supervisor restarts a master it has not yet declared dead, and
        # the cheapest honest declaration is the same lease ttl of
        # silence the standby itself waited out — so the comparison puts
        # the identical detection term on both sides and lets the
        # MEASURED mechanics (promote-in-place vs spawn+import+replay)
        # decide, never a hardcoded number.
        spawn_t = time.monotonic()
        corpse = spawn_primary()
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and not addr_connectable(addr_p):
            time.sleep(0.05)
        if not addr_connectable(addr_p):
            report.update(ok=False, error="corpse never came back")
            return report
        report["restart_gap_s"] = round(time.monotonic() - spawn_t, 2)
        report["restart_the_world_s"] = round(
            report["restart_gap_s"] + lease_ttl, 2)
        report["promotion_beats_restart"] = bool(
            report["promotion_gap_s"] < report["restart_the_world_s"])

        # ------------------------------------------------ split-brain gate
        cs = _probe(addr_p)
        report["corpse_fenced"] = bool(
            cs is not None and not cs.is_leader
            and cs.epoch < promoted_epoch)
        corpse_cli = RpcClient(addr_p, node_id=-4, node_type="probe",
                               timeout=2.0, retries=1)
        try:
            read_ok = not corpse_cli.get(
                msg.KVStoreGetRequest(key="chaos-fo")).found
            try:
                corpse_cli.report(msg.KVStoreSetRequest(
                    key="chaos-fo", value=b"split"))
                mutation_refused = False
            except RpcError as e:
                mutation_refused = "NotLeaderError" in str(e)
        finally:
            corpse_cli.close()
        report["corpse_read_ok"] = bool(read_ok)
        report["corpse_mutation_refused"] = bool(mutation_refused)

        # ---------------------------------------- incident timeline gate
        # live (promoted standby, BOTH dirs) vs offline over the same
        # ordered dir list — byte-equal, exactly-once (epoch, seq), and
        # the takeover narrated as kind="failover"
        from .agent.master_client import MasterClient
        from .telemetry import timeline as tl

        ckpt_dir = os.path.join(work, "ckpt")
        mc = MasterClient(addr_sb, node_id=-1)
        try:
            live = mc.get_timeline(ckpt_dir=ckpt_dir,
                                   journal_dirs=[jd_standby, jd_primary])
        finally:
            mc.close()
        offline = tl.assemble_incident(journal_dir=jd_standby,
                                       ckpt_dir=ckpt_dir,
                                       journal_dirs=[jd_primary])
        report["timeline_byte_equal"] = (
            live.content == tl.incident_json(offline))
        jkeys = [(e["epoch"], e["seq"]) for e in offline["events"]
                 if e["source"] == "journal" and e["kind"] != "flush"]
        report["timeline_causal"] = (
            jkeys == sorted(jkeys) and len(jkeys) == len(set(jkeys))
            and len(jkeys) == offline["counts"]["journal_events"])
        kinds = [i["kind"] for i in offline["narrative"]["incidents"]]
        report["timeline_failover_incident"] = "failover" in kinds

        report["ok"] = bool(
            report["completed"] and cli.returncode == 0
            and report["worker_generations"] == 1
            and report["no_shard_lost_or_double"]
            and report["heartbeats_buffered"] > 0
            and report["buffer_drained"]
            and report["client_failovers"] >= 1
            and report["epoch_fenced"] and report["reregistered"]
            and report["ledger"]["degraded_s"] > 0
            and report["ledger"]["productive_s"] > 0
            and report["goodput_wall"] >= target
            and report["pre_kill"]["standby_lag_frames"] >= 0
            and report["promotion_beats_restart"]
            and report["corpse_fenced"]
            and report["corpse_read_ok"]
            and report["corpse_mutation_refused"]
            and report["timeline_byte_equal"]
            and report["timeline_causal"]
            and report["timeline_failover_incident"])
        return report
    finally:
        for proc in (corpse, standby):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        if primary.poll() is None:
            primary.kill()
        if cli is not None and cli.poll() is None:
            cli.kill()
        if report.get("ok"):
            import shutil

            shutil.rmtree(work, ignore_errors=True)
        else:
            report["cli_tail"] = (out or "")[-2000:]
            report["workdir"] = work


_HOT_SWAP_WORKER = r"""
import json, os, sys, time

import numpy as np

import jax
import jax.numpy as jnp

(ckpt_dir, marker_dir, rank_s, nodes_s, steps_s, kfuse_s, dt_s) = \
    sys.argv[1:8]
rank, n_nodes = int(rank_s), int(nodes_s)
total_steps, K, dt = int(steps_s), int(kfuse_s), float(dt_s)
addr = os.environ["DWT_MASTER_ADDR"]

from dlrover_wuqiong_tpu.agent.master_client import MasterClient
from dlrover_wuqiong_tpu.checkpoint.replica import (CkptReplicaManager,
                                                    ReplicaServer)
from dlrover_wuqiong_tpu.checkpoint.shm_handler import SharedMemoryHandler
from dlrover_wuqiong_tpu.telemetry import get_ledger, get_recorder
from dlrover_wuqiong_tpu.trainer.hotswap import HotSwapParticipant

log = open(os.path.join(marker_dir, f"log_r{rank}"), "a")


def emit(line):
    log.write(line + "\n")
    log.flush()


mc = MasterClient(addr, node_id=rank)
mc.register_node(rank)
led = get_ledger()
led.start()

# replica ring: one server per node, addresses exchanged via the KV store
server = ReplicaServer(host="127.0.0.1")
server.start()
mc.kv_store_set(f"hsw/replica/{rank}", f"127.0.0.1:{server.port}".encode())
peers = {}
while len(peers) < n_nodes:
    for r in range(n_nodes):
        if r not in peers:
            v = mc.kv_store_get(f"hsw/replica/{r}")
            if v:
                peers[r] = v.decode()
    time.sleep(0.05)
job = os.environ["DWT_JOB_NAME"] + f"r{rank}"
shm = SharedMemoryHandler(0, job)
rep = CkptReplicaManager(rank=rank, peers=peers, job_name=job,
                         replica_count=1, lock_timeout=0.2)

mc.join_rendezvous(rank, 1, node_ip="127.0.0.1", free_port=server.port)
while True:
    st = mc.get_comm_world()
    if st.complete and len(st.world) >= n_nodes:
        break
    time.sleep(0.05)
emit(f"world {time.time():.3f} {st.rdzv_round}")

# deterministic per-shard "training": the update is ELEMENTWISE, so
# stepping the shards separately bit-equals stepping their concatenation
# — the drill's golden degraded-mesh run relies on this
DIM = 16


def shard_init(r):
    return (np.arange(DIM, dtype=np.float32) + 1.0) * np.float32(
        0.1 * (r + 1))


traces = {"n": 0}


def _step(w, s):
    traces["n"] += 1  # trace-time side effect: counts XLA compiles
    g = w * jnp.float32(0.01) + jnp.float32(1e-4) * s.astype(jnp.float32)
    return w - jnp.float32(0.1) * g


stepfn = jax.jit(_step)
# warm-pool analog: compile BOTH mesh geometries up front — cutover onto
# the degraded (full-vector) executable must never pay a cold compile
stepfn(jnp.zeros((DIM,), jnp.float32), jnp.int32(0)).block_until_ready()
stepfn(jnp.zeros((n_nodes * DIM,), jnp.float32),
       jnp.int32(0)).block_until_ready()
warm = traces["n"]

w = jnp.asarray(shard_init(rank))
cur = {"w": w, "step": 0}
hist = {}


def cutover_cb(hydrated, st):
    if hydrated is None:
        return False
    dstep, flat, extra = hydrated
    dstep = int(dstep)
    own = hist.get(dstep)
    if own is None:
        # survivor paused BEHIND the victim's last stage: roll the own
        # shard forward to the merge step (shard-local update — exact)
        if dstep < cur["step"]:
            return False
        wtmp, s = cur["w"], cur["step"]
        while s < dstep:
            wtmp = stepfn(wtmp, jnp.int32(s))
            s += 1
        own = np.asarray(wtmp)
    parts = {rank: np.asarray(own, np.float32),
             int(st.dead_rank): np.asarray(flat["w"], np.float32)}
    full = np.concatenate([parts[r] for r in sorted(parts)])
    cur["resume"] = (dstep, jnp.asarray(full))
    return True


hs = HotSwapParticipant(mc, node_id=rank, replica_manager=rep,
                        cutover_cb=cutover_cb, ledger=led)

mode = "duo"
step = 0
swap_seen = -1.0
while True:
    if cur.get("resume") is not None:
        dstep, wfull = cur.pop("resume")
        step, w, mode = dstep, wfull, "solo"
        emit(f"cutover {time.time():.3f} {dstep} {traces['n']}")
        if swap_seen > 0:
            emit(f"recover {time.time():.3f} "
                 f"{time.monotonic() - swap_seen:.3f}")
    if step >= total_steps:
        break
    for _ in range(K):  # one fused window; boundary work below only
        with led.window("productive"):
            w = stepfn(w, jnp.int32(step))
            time.sleep(dt)
        step += 1
    cur["w"], cur["step"] = w, step
    if mode == "duo":
        arr = np.asarray(w)
        hist[step] = arr.copy()
        shm.save_state_dict({"w": arr}, step=step)
        rep.backup()
        emit(f"stage {time.time():.3f} {step}")
    else:
        loss = float(jnp.mean(w * w))
        emit(f"loss {time.time():.3f} {step} {loss.hex()}")
    mc.report_heart_beat(step)
    ph = hs.poll()  # fusion boundary: the ONLY place swap work happens
    if ph is not None and swap_seen < 0:
        swap_seen = time.monotonic()
        emit(f"swapseen {time.time():.3f} {step} {ph}")
    while hs.mid_ladder:  # park at this boundary until the ladder ends
        time.sleep(0.25)
        hs.poll()

with open(os.path.join(marker_dir, f"done_r{rank}"), "w") as f:
    json.dump({"rank": rank, "steps": step, "mode": mode,
               "warm_traces": warm, "final_traces": traces["n"],
               "fence_epoch": hs.fence_epoch,
               "ledger": led.snapshot()}, f)
get_recorder().flush(ckpt_dir, "drill-end")
"""


_HOT_SWAP_GOLDEN = r"""
import json, sys

import numpy as np

import jax
import jax.numpy as jnp

total_steps, fused_k, cut_step, n_nodes = map(int, sys.argv[1:5])
dim = 16
full = np.concatenate([(np.arange(dim, dtype=np.float32) + 1.0)
                       * np.float32(0.1 * (r + 1))
                       for r in range(n_nodes)])


@jax.jit
def step(w, s):
    g = w * jnp.float32(0.01) + jnp.float32(1e-4) * s.astype(jnp.float32)
    return w - jnp.float32(0.1) * g


w = jnp.asarray(full)
out = {}
for s in range(total_steps):
    w = step(w, jnp.int32(s))
    if (s + 1) % fused_k == 0 and (s + 1) > cut_step:
        out[str(s + 1)] = float(jnp.mean(w * w)).hex()
print(json.dumps(out))
"""


def hot_swap(total_steps: int = 64, fused_k: int = 4, dt: float = 0.02,
             kill_stage: int = 12, outage_s: float = 0.5,
             timeout: float = 240.0) -> Dict:
    """SIGKILL one worker of N mid-train; survivors absorb IN PLACE.

    The tentpole's proof drill: a 2-node world trains a sharded state
    with per-boundary shm staging + ring replication, the drill
    hard-kills one worker and reports the failure (as the agent
    supervisor would), and the master — whose adaptive policy route says
    "hotswap" — drives the journaled mesh-transition ladder
    (master/mesh_transition.py) instead of restarting the world.  The
    MASTER is then SIGKILLed mid-transition and restarted on the same
    journal.  Invariants:

    - the survivor NEVER restarts (one process, exit 0) and finishes
      the run in "solo" mode on the degraded mesh;
    - hydration is replica-tier: the dead rank's staged shard came from
      its ring holder digest-verified (trainer/hotswap.py), and the
      post-cutover loss trajectory is BIT-IDENTICAL to an uninterrupted
      run of the merged state on the degraded mesh (golden computed
      in-process with the same jitted step);
    - zero cold compiles after the warm-up: the degraded-mesh executable
      was pre-compiled (warm-pool analog), so the survivor's XLA trace
      count never moves after cutover;
    - the master crash mid-transition REPLAYS to the same transition
      (same tid, phase no earlier than last observed) and the ladder
      completes to "done" with the world rewritten minus the dead node;
    - the journal narrates the swap as ONE mesh_transition incident
      (telemetry/timeline.py) and the live TimelineQuery byte-equals
      the offline assembly + the incident_report CLI's sha;
    - transition time credits the ledger's restore_replica/rework
      states (nonzero), and recovery lands in seconds.
    """
    from .common.comm import addr_connectable, find_free_port

    phases_order = ["propose", "fence", "hydrate", "cutover", "release",
                    "done"]
    work = tempfile.mkdtemp(prefix="dwt-chaos-hotswap-")
    marker = os.path.join(work, "markers")
    journal_dir = os.path.join(work, "journal")
    ckpt_dir = os.path.join(work, "ckpt")
    os.makedirs(marker)
    os.makedirs(ckpt_dir)
    script = os.path.join(work, "worker.py")
    with open(script, "w") as f:
        f.write(_HOT_SWAP_WORKER)
    global _launch_seq
    _launch_seq += 1
    job = f"hotswap{os.getpid()}n{_launch_seq}"
    port = find_free_port()
    addr = f"127.0.0.1:{port}"
    env = dict(
        os.environ, DWT_JOB_NAME=job, JAX_PLATFORMS="cpu",
        DWT_SOCKET_DIR=os.path.join(work, "sockets"),
        DWT_MASTER_ADDR=addr,
        PYTHONPATH=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) + os.pathsep +
        os.environ.get("PYTHONPATH", ""))

    def spawn_master():
        return subprocess.Popen(
            [sys.executable, "-m", "dlrover_wuqiong_tpu.master",
             f"--port={port}", "--min_nodes=2", "--max_nodes=2",
             f"--journal-dir={journal_dir}", "--poll-interval=0.5"],
            env=env, cwd=work, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    def spawn_worker(r):
        return subprocess.Popen(
            [sys.executable, script, ckpt_dir, marker, str(r), "2",
             str(total_steps), str(fused_k), str(dt)],
            env=env, cwd=work, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    def staged(r):
        try:
            with open(os.path.join(marker, f"log_r{r}")) as f:
                return max((int(ln.split()[2]) for ln in f
                            if ln.startswith("stage ")), default=-1)
        except (OSError, ValueError):
            return -1

    report: Dict = {"scenario": "hot-swap", "outage_s": outage_s}
    master = spawn_master()
    workers: Dict[int, subprocess.Popen] = {}
    out = ""
    from .agent.master_client import MasterClient
    mc = None
    try:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not addr_connectable(addr):
            time.sleep(0.1)
        if not addr_connectable(addr):
            report.update(ok=False, error="master never came up")
            return report
        # the adaptive route that arms in-place takeover (brain/plugins)
        mc = MasterClient(addr, node_id=-1)
        from .common import messages as msg
        mc.report_policy_decision(msg.PolicyDecision(
            decision_id=1, recovery_route="hotswap",
            preferred_tier="replica", reason="chaos hot-swap drill"))
        workers = {r: spawn_worker(r) for r in (0, 1)}

        # kill the victim once BOTH ranks have staged + replicated past
        # the kill point — the ring then provably holds its shard
        deadline = time.monotonic() + timeout / 2
        while time.monotonic() < deadline:
            if min(staged(0), staged(1)) >= kill_stage:
                break
            if any(p.poll() is not None for p in workers.values()):
                report.update(ok=False, error="worker died before kill",
                              rcs={r: p.poll()
                                   for r, p in workers.items()})
                return report
            time.sleep(0.05)
        else:
            report.update(ok=False,
                          error="workers never reached the kill point")
            return report
        workers[1].kill()  # SIGKILL — the pod is simply gone
        workers[1].wait(timeout=10)
        t_kill = time.monotonic()
        # the agent supervisor's job: report the node-level death
        vic = MasterClient(addr, node_id=1)
        try:
            vic.report_failure("SIGKILL", level="node")
        finally:
            vic.close()

        # catch the transition mid-ladder, then SIGKILL the master too
        observed = ""
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                ts = mc.get_mesh_transition()
            except Exception:  # noqa: BLE001 — keep polling
                time.sleep(0.03)
                continue
            if ts.transition_id == 1 and ts.phase in phases_order[:4]:
                observed = ts.phase
                break
            time.sleep(0.03)
        report["phase_at_master_kill"] = observed
        if not observed:
            report.update(ok=False, error="transition never observed")
            return report
        mc.close()
        mc = None
        master.kill()  # SIGKILL mid-transition — no snapshot, no goodbye
        master.wait(timeout=10)
        time.sleep(outage_s)
        master = spawn_master()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not addr_connectable(addr):
            time.sleep(0.05)
        mc = MasterClient(addr, node_id=-1)
        ts = mc.get_mesh_transition()
        report["phase_after_replay"] = ts.phase
        # replay lands on the SAME transition, no earlier than observed
        # (an ack in flight at kill time may have advanced it one rung)
        report["replay_same_transition"] = bool(
            ts.transition_id == 1 and ts.phase in phases_order
            and phases_order.index(ts.phase)
            >= phases_order.index(observed))

        # survivor finishes the run solo
        done_path = os.path.join(marker, "done_r0")
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and not os.path.exists(done_path):
            if workers[0].poll() is not None:
                break
            time.sleep(0.1)
        try:
            out, _ = workers[0].communicate(timeout=30)
        except subprocess.TimeoutExpired:
            workers[0].kill()
            out, _ = workers[0].communicate()
        report["survivor_rc"] = workers[0].returncode
        report["completed"] = os.path.exists(done_path)
        if not report["completed"]:
            report.update(ok=False, error="survivor never finished")
            return report
        with open(done_path) as f:
            done = json.load(f)
        report["survivor_mode"] = done.get("mode")
        report["fence_epoch"] = done.get("fence_epoch")
        # zero cold compiles: the trace counter never moved after the
        # two warm-up compiles (duo + degraded geometries)
        report["cold_compiles_after_warm"] = (
            int(done.get("final_traces", -1))
            - int(done.get("warm_traces", 0)))
        led_states = (done.get("ledger") or {}).get("states", {})
        report["ledger"] = {
            "restore_replica_s": round(
                float(led_states.get("restore_replica", 0.0)), 4),
            "rework_s": round(float(led_states.get("rework", 0.0)), 4),
            "productive_s": round(
                float(led_states.get("productive", 0.0)), 3),
        }

        # survivor log: cutover step + recovery wall + solo losses
        cut_step, recover_s, losses = -1, -1.0, {}
        with open(os.path.join(marker, "log_r0")) as f:
            for ln in f:
                parts = ln.split()
                if parts[0] == "cutover":
                    cut_step = int(parts[2])
                elif parts[0] == "recover":
                    recover_s = float(parts[2])
                elif parts[0] == "loss":
                    losses[int(parts[2])] = parts[3]
        report["cutover_step"] = cut_step
        report["recovery_s"] = round(recover_s, 3)
        report["solo_boundaries"] = len(losses)

        # golden: the UNINTERRUPTED degraded-mesh run — the merged full
        # vector stepped by the same jitted fn from step 0 (elementwise
        # update: separate shards ≡ concatenation, see worker script).
        # Computed in a JAX_PLATFORMS=cpu subprocess: the drill process
        # may sit on a real TPU backend, and bit-identity needs the same
        # XLA:CPU executable the worker compiled.
        golden_py = os.path.join(work, "golden.py")
        with open(golden_py, "w") as f:
            f.write(_HOT_SWAP_GOLDEN)
        p = subprocess.run(
            [sys.executable, golden_py, str(total_steps), str(fused_k),
             str(cut_step), "2"],
            capture_output=True, text=True, env=env, timeout=120)
        try:
            golden = json.loads(p.stdout)
        except ValueError:
            golden = None
        report["loss_bit_identical"] = bool(
            losses and cut_step > 0
            and {str(k): v for k, v in losses.items()} == golden)

        # ------------------------------------------- incident timeline gate
        from .telemetry import timeline as tl

        live = mc.get_timeline(ckpt_dir=ckpt_dir)
        offline = tl.assemble_incident(journal_dir=journal_dir,
                                       ckpt_dir=ckpt_dir)
        report["timeline_byte_equal"] = (
            live.content == tl.incident_json(offline))
        narr = offline["narrative"]
        swaps = [i for i in narr["incidents"]
                 if i["kind"] == "mesh_transition"]
        report["mesh_incidents"] = len(swaps)
        inc = swaps[0] if swaps else {}
        report["incident_phase"] = inc.get("phase")
        swap_lost = float(inc.get("lost_s", 0.0))
        want_lost = (report["ledger"]["restore_replica_s"]
                     + report["ledger"]["rework_s"])
        report["timeline_attribution_ok"] = (
            abs(swap_lost - want_lost) <= 0.05)
        tools_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        p = subprocess.run(
            [sys.executable,
             os.path.join(tools_dir, "incident_report.py"),
             "--journal", journal_dir, "--flight", ckpt_dir],
            capture_output=True, text=True, env=env, timeout=120)
        try:
            cli_line = json.loads(p.stdout)
        except ValueError:
            cli_line = {}
        report["incident_report_sha_match"] = bool(
            p.returncode == 0
            and cli_line.get("timeline_sha256")
            == tl.incident_sha256(live.content))

        # journal-level exactly-once: ONE propose, phase frames a strict
        # ladder prefix ending "done" — replay re-advanced nothing
        proposes, phase_frames = 0, []
        with open(os.path.join(journal_dir, "journal.frames"), "rb") as f:
            for line in f.read().split(b"\n"):
                if not line.strip():
                    continue
                try:
                    frame = json.loads(line.decode("utf-8"))
                except ValueError:
                    break
                if frame.get("kind") != "mesh_transition":
                    continue
                data = frame.get("data") or {}
                ev = data.get("event")
                if ev == "propose":
                    proposes += 1
                elif ev == "phase":
                    phase_frames.append(str(data.get("phase", "")))
        report["journal_proposes"] = proposes
        report["journal_phases"] = phase_frames
        report["journal_ladder_ok"] = bool(
            proposes == 1
            and phase_frames == phases_order[1:])

        report["ok"] = bool(
            report["survivor_rc"] == 0
            and report["survivor_mode"] == "solo"
            and report["fence_epoch"] == 2
            and report["cold_compiles_after_warm"] == 0
            and report["ledger"]["restore_replica_s"] > 0
            and report["ledger"]["rework_s"] > 0
            and 0 < report["recovery_s"] <= 30.0
            and report["solo_boundaries"] > 0
            and report["loss_bit_identical"]
            and report["replay_same_transition"]
            and report["mesh_incidents"] == 1
            and report["incident_phase"] == "done"
            and report["timeline_byte_equal"]
            and report["timeline_attribution_ok"]
            and report["incident_report_sha_match"]
            and report["journal_ladder_ok"])
        return report
    finally:
        if mc is not None:
            mc.close()
        if master.poll() is None:
            master.terminate()
            try:
                master.wait(timeout=10)
            except subprocess.TimeoutExpired:
                master.kill()
        for p in workers.values():
            if p.poll() is None:
                p.kill()
        # SIGKILLed processes leak their POSIX shm segments (CLAUDE.md)
        from .checkpoint.shm_handler import SharedMemoryHandler
        for r in (0, 1):
            try:
                SharedMemoryHandler(0, f"{job}r{r}").unlink()
            except Exception:  # noqa: BLE001 — best-effort reap
                pass
        if report.get("ok"):
            import shutil

            shutil.rmtree(work, ignore_errors=True)
        else:
            report["cli_tail"] = (out or "")[-2000:]
            report["workdir"] = work


def serve_drain(n_requests: int = 8, max_new_tokens: int = 24,
                kill_after_done: int = 2, timeout: float = 300.0) -> Dict:
    """SIGKILL a decode WORKER mid-traffic; drain to a replacement.

    The serving subsystem's headline invariant: in-flight inference
    requests survive the death of the worker decoding them.  The drill
    runs a journaled standalone master, submits a batch of requests,
    starts a real `python -m dlrover_wuqiong_tpu.serving` worker,
    SIGKILLs it while some requests are done and others are mid-decode,
    reports the failure (the production attribution path is the
    heartbeat sweep; the drill reports explicitly, like the reference's
    chaosblade harness), starts a SECOND worker and drains.  Invariants:

    - zero dropped: every request gets a result with exactly
      `max_new_tokens` tokens despite the kill;
    - bit-identical: results equal an alone-decode of the same
      (weights, prompt, seed) on a fresh local engine with DIFFERENT
      batch geometry — re-admitted requests restart from the prompt
      (never a corrupt half-state) and the position-keyed sampler
      (serving/engine.py) makes the replay exact;
    - recovery is ATTRIBUTED: `requeued_total` > 0 in the serve summary
      and surfaces under the pinned `requeued` ledger counter;
    - one trace tree per request reconstructs from the flight dumps of
      BOTH worker generations (trace ids derive from request ids,
      serving/scheduler.request_trace_id) with admit + finish events.
    """
    from .agent.master_client import MasterClient
    from .common import messages as msg
    from .common.comm import addr_connectable, find_free_port
    from .serving.scheduler import request_trace_id
    from .telemetry.recorder import load_flight_dumps

    work = tempfile.mkdtemp(prefix="dwt-chaos-servedrain-")
    journal_dir = os.path.join(work, "journal")
    # ONE flight-dump dir shared by both worker generations: the trace
    # reconstruction must join spans across the kill
    ckpt_dir = os.path.join(work, "ckpt")
    os.makedirs(ckpt_dir)
    global _launch_seq
    _launch_seq += 1
    job = f"servedrain{os.getpid()}n{_launch_seq}"
    port = find_free_port()
    addr = f"127.0.0.1:{port}"
    env = dict(
        os.environ, DWT_JOB_NAME=job, JAX_PLATFORMS="cpu",
        DWT_SOCKET_DIR=os.path.join(work, "sockets"),
        PYTHONPATH=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) + os.pathsep +
        os.environ.get("PYTHONPATH", ""))

    def spawn_master():
        return subprocess.Popen(
            [sys.executable, "-m", "dlrover_wuqiong_tpu.master",
             f"--port={port}", "--min_nodes=1", "--max_nodes=1",
             f"--journal-dir={journal_dir}", "--poll-interval=0.5"],
            env=env, cwd=work, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    def spawn_worker(node_id: int):
        return subprocess.Popen(
            [sys.executable, "-m", "dlrover_wuqiong_tpu.serving",
             "--master", addr, "--node-id", str(node_id),
             "--slots", "2", "--max-len", "64", "--max-prompt-len", "8",
             "--fused-tokens", "2", "--stats-every", "1",
             "--model-seed", "0", "--ckpt-dir", ckpt_dir],
            env=env, cwd=work, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    report: Dict = {"scenario": "serve-drain", "requests": n_requests,
                    "max_new_tokens": max_new_tokens}
    master = spawn_master()
    w1 = w2 = None
    cli = None
    try:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not addr_connectable(addr):
            time.sleep(0.1)
        if not addr_connectable(addr):
            report.update(ok=False, error="master never came up")
            return report
        cli = MasterClient(addr, node_id=90, node_type="chaos")
        reqs = [msg.ServeRequest(
                    request_id=f"req-{i:02d}",
                    prompt=[1 + i, 7, 13, 2 + i][:3 + i % 2],
                    max_new_tokens=max_new_tokens, temperature=1.0,
                    seed=1000 + i, submitted_at=time.time())
                for i in range(n_requests)]
        report["accepted"] = cli.submit_serve_requests(reqs).accepted

        w1 = spawn_worker(1)
        # wait for MID-TRAFFIC: some requests done AND some leased (the
        # kill must land on held leases, or there is nothing to recover)
        deadline = time.monotonic() + timeout / 2
        done_at_kill = -1
        while time.monotonic() < deadline and w1.poll() is None:
            summ = cli.get_serve_summary()
            if summ.done_total >= kill_after_done and summ.leased > 0:
                done_at_kill = summ.done_total
                break
            time.sleep(0.05)
        report["done_at_kill"] = done_at_kill
        if not (0 <= done_at_kill < n_requests):
            report.update(ok=False, w1_rc=w1.poll(),
                          error="never reached mid-traffic kill point")
            return report
        w1.kill()  # SIGKILL — admitted requests die with their slots
        w1.wait(timeout=10)
        logger.info("serve-drain: SIGKILLed worker pid=%d at done=%d",
                    w1.pid, done_at_kill)
        failed_cli = MasterClient(addr, node_id=1,
                                  node_type="serve-worker")
        try:
            failed_cli.report_failure("chaos serve-drain SIGKILL",
                                      level="process")
        finally:
            failed_cli.close()

        w2 = spawn_worker(2)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cli.get_serve_summary().done_total >= n_requests:
                break
            time.sleep(0.1)
        resp = cli.get_serve_results([r.request_id for r in reqs])
        got = {r.request_id: [int(t) for t in r.tokens]
               for r in resp.results}
        summ = cli.get_serve_summary()
        report["results"] = len(got)
        report["requeued_total"] = summ.requeued_total
        report["requeued_counter"] = int(
            summ.counters.get("requeued", 0))
        report["zero_dropped"] = bool(
            len(got) == n_requests
            and all(len(t) == max_new_tokens for t in got.values()))

        # bit-identical replay: alone-decode on a fresh local engine
        # with DIFFERENT batch geometry (composition must not matter)
        import jax

        jax.config.update("jax_platforms", "cpu")
        from .models.gpt import GPT, GPTConfig
        from .serving import LocalServer, ServeSpec, ServingEngine

        cfg = GPTConfig.nano()
        params = GPT(cfg).init_params(jax.random.PRNGKey(0))
        srv = LocalServer(ServingEngine(cfg, params, ServeSpec(
            max_slots=3, max_len=64, max_prompt_len=8, fused_tokens=4)))
        for r in reqs:
            srv.submit(r.request_id, list(r.prompt),
                       max_new_tokens=r.max_new_tokens, seed=r.seed,
                       temperature=r.temperature)
        expected = srv.drain()
        mismatched = [rid for rid in expected
                      if got.get(rid) != expected[rid]]
        report["bit_identical"] = not mismatched
        if mismatched:
            report["mismatched"] = mismatched[:4]

        # one trace tree per request, reconstructed from flight dumps
        dumps = load_flight_dumps(ckpt_dir)
        report["flight_dumps"] = len(dumps)
        seen = set()  # (trace, span) — the ring re-flushes cumulatively
        names_by_trace: Dict = {}
        pids_by_trace: Dict = {}
        for d in dumps:
            for evt in d.get("events", []):
                if evt.get("kind") != "span":
                    continue
                rec = evt.get("data", {})
                key = (rec.get("trace_id", ""), rec.get("span_id", ""))
                if key in seen:
                    continue
                seen.add(key)
                tid = rec.get("trace_id", "")
                names_by_trace.setdefault(tid, set()).add(
                    rec.get("name", ""))
                pids_by_trace.setdefault(tid, set()).add(rec.get("pid"))
        trees_ok = True
        cross_generation = 0
        for r in reqs:
            tid = request_trace_id(r.request_id)
            if not {"serve:admit", "serve:finish"} <= \
                    names_by_trace.get(tid, set()):
                trees_ok = False
            if len(pids_by_trace.get(tid, set())) > 1:
                cross_generation += 1
        report["trace_trees_complete"] = trees_ok
        # requests admitted by gen-1 and re-admitted by gen-2 join one
        # tree with spans from two pids (informational: lease timing
        # decides whether a killed request was already admitted)
        report["trace_trees_cross_generation"] = cross_generation

        # ------------------------------------------- incident timeline gate
        # w2 re-flushes its flight ring on every stats push — freeze the
        # artifacts FIRST or live-vs-offline byte equality is a race
        w2.kill()
        w2.wait(timeout=10)
        from .telemetry import timeline as tl

        # the serve verbs above (journaled+idem submit/lease/result)
        # must have ridden the group-commit journal — batched fsync is
        # the default this drill now gates on, with the frames-per-sync
        # gauge surfaced as evidence
        js = cli.get_journal_stats()
        report["journal_group_commit"] = {
            "enabled": js.enabled, "group_commit": js.group_commit,
            "max_frames": js.max_frames,
            "batch_mean": round(js.batch_mean, 2),
            "durable_seq": js.durable_seq}

        live = cli.get_timeline(ckpt_dir=ckpt_dir)
        offline = tl.assemble_incident(journal_dir=journal_dir,
                                       ckpt_dir=ckpt_dir)
        report["timeline_events"] = live.events
        report["timeline_byte_equal"] = (
            live.content == tl.incident_json(offline))
        jkeys = [(e["epoch"], e["seq"]) for e in offline["events"]
                 if e["source"] == "journal"]
        report["timeline_causal"] = (
            jkeys == sorted(jkeys) and len(jkeys) == len(set(jkeys)))
        # exactly-once on the timeline itself: the serve_result journal
        # events' request ids tile the submitted set exactly once (the
        # requeue produced a second LEASE, never a second result), and
        # the batch submit journaled exactly one frame
        result_ids: list = []
        n_submit = 0
        for e in offline["events"]:
            if e["source"] != "journal":
                continue
            if e["kind"] == "serve_result":
                result_ids += list(e["data"].get("request_ids", []))
            elif e["kind"] == "serve_submit":
                n_submit += 1
        report["timeline_serve_exactly_once"] = (
            sorted(result_ids) == sorted(r.request_id for r in reqs)
            and n_submit == 1)
        # the offline CLI on the same artifacts must hash to the live bytes
        tools_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        p = subprocess.run(
            [sys.executable, os.path.join(tools_dir, "incident_report.py"),
             "--journal", journal_dir, "--flight", ckpt_dir],
            capture_output=True, text=True, env=env, timeout=120)
        try:
            cli_line = json.loads(p.stdout)
        except ValueError:
            cli_line = {}
        report["incident_report_rc"] = p.returncode
        report["incident_report_sha_match"] = bool(
            p.returncode == 0
            and cli_line.get("timeline_sha256")
            == tl.incident_sha256(live.content))

        report["ok"] = bool(
            report["zero_dropped"] and report["bit_identical"]
            and report["requeued_total"] > 0
            and report["requeued_counter"] > 0 and trees_ok
            and report["timeline_byte_equal"]
            and report["timeline_causal"]
            and report["timeline_serve_exactly_once"]
            and report["incident_report_sha_match"]
            and report["journal_group_commit"]["enabled"]
            and report["journal_group_commit"]["group_commit"])
        return report
    finally:
        tails = {}
        for name, p in (("w1", w1), ("w2", w2)):
            if p is None:
                continue
            if p.poll() is None:
                p.kill()
            try:
                out, _ = p.communicate(timeout=10)
            except (subprocess.TimeoutExpired, ValueError):
                out = ""
            tails[name] = (out or "")[-2000:]
        if cli is not None:
            cli.close()
        if master.poll() is None:
            master.terminate()
            try:
                master.wait(timeout=10)
            except subprocess.TimeoutExpired:
                master.kill()
        if report.get("ok"):
            import shutil

            shutil.rmtree(work, ignore_errors=True)
        else:
            report.update(workdir=work, **{f"{k}_tail": v
                                           for k, v in tails.items()})


def perf_regress() -> Dict:
    """Perf-regression sentinel drill (telemetry/perf.py) — jax-free.

    Three invariants, all on the REAL BaselineStore + RegressionSentinel
    (seeded synthetic windows, so the drill is hermetic and fast):

    1. QUIET: shared-tunnel-scale +-10% noise around the baseline never
       fires — the MAD bound absorbs normal drift.
    2. THROTTLED: a sustained ~1.5x step-time slowdown whose extra wall
       sits in the collective category fires `perf-regression` after
       EXACTLY M consecutive beyond-bound windows, once per excursion,
       and attributes the moved category.
    3. KEY ISOLATION: flipping a TRACE_ENV_VARS toggle (through the
       tuner's sanctioned `variant_env`) changes the executable key
       (a different executable is a new baseline, never a false
       regression), and the published store survives an atomic write +
       reload round-trip with identical stats.
    4. TUNER CUTOVER: after a variant cutover the sentinel judges the
       new key against its OWN fresh baseline — step times that fired
       under the old key never fire post-cutover.
    """
    import random
    import shutil

    from .telemetry.perf import (BaselineStore, RegressionSentinel,
                                 executable_key)

    work = tempfile.mkdtemp(prefix="dwt-chaos-perfregress-")
    report: Dict = {"scenario": "perf-regress", "ok": False}
    try:
        m_consec = 3
        store = BaselineStore(
            path=os.path.join(work, "perf", "baseline.json"))
        sentinel = RegressionSentinel(store, m_consecutive=m_consec)
        key = executable_key("drill-fingerprint", 8, "cpu")
        rng = random.Random(1234)

        def window(v, coll_frac):
            cats = {"matmul": v * (1 - coll_frac),
                    "collective": v * coll_frac}
            beyond, event = sentinel.observe(key, v, cats, step=window.n)
            window.n += 8
            if not beyond:
                store.update(key, v, cats)
                store.publish()
            return event
        window.n = 0

        # 1) quiet phase: baseline forms, nothing fires
        quiet_events = [e for _ in range(16)
                        if (e := window(0.1 * (1 + 0.1 * (
                            rng.random() * 2 - 1)), 0.3)) is not None]
        # 2) throttled phase: +60% wall, all of it collective
        fired = []
        for i in range(2 * m_consec):
            e = window(0.16, 0.56)
            if e is not None:
                fired.append((i + 1, e))
        # 3) key isolation across a trace-env flip + store round-trip —
        #    flipped through the tuner's sanctioned scoped writer
        #    (auto/tuner.py; graftlint env-flip-outside-tuner forbids
        #    raw os.environ writes of TRACE_ENV_VARS names).  The flip
        #    exercises the ISSUE-16 quant axis (DWT_FP8_DENSE) — the
        #    numerics-changing variant must re-key exactly like the
        #    layout-neutral DWT_FA_* toggles
        from .auto.tuner import variant_env

        with variant_env({"DWT_FP8_DENSE": "1"}):
            flipped = executable_key("drill-fingerprint", 8, "cpu")
        # 4) tuner cutover: the flipped variant is a NEW executable key,
        #    so its windows land on a FRESH baseline — step times that
        #    would be deep beyond-bound under the OLD key (the throttled
        #    phase already fired on them) must never fire the sentinel
        #    after a cutover
        cutover_events = []
        n_cut = 0
        for i in range(4 * m_consec):
            beyond, event = sentinel.observe(
                flipped, 0.16, {"matmul": 0.112, "collective": 0.048},
                step=n_cut)
            n_cut += 8
            if event is not None:
                cutover_events.append(event)
            if not beyond:
                store.update(flipped, 0.16,
                             {"matmul": 0.112, "collective": 0.048})
                store.publish()
        reloaded = BaselineStore(
            path=os.path.join(work, "perf", "baseline.json"))
        report.update(
            quiet_events=len(quiet_events),
            fired_after_windows=fired[0][0] if fired else -1,
            fired_total=len(fired),
            fired_kind=fired[0][1]["kind"] if fired else "",
            attributed_category=fired[0][1]["category"] if fired else "",
            key_changed_on_env_flip=flipped != key,
            cutover_windows=4 * m_consec,
            cutover_fired=len(cutover_events),
            cutover_baseline_n=int((store.stats(flipped) or
                                    {}).get("n", 0)),
            baseline_roundtrip=reloaded.stats(key) == store.stats(key)
            and store.stats(key) is not None,
        )
        report["ok"] = (
            not quiet_events
            and len(fired) == 1
            and fired[0][0] == m_consec
            and fired[0][1]["kind"] == "perf-regression"
            and fired[0][1]["category"] == "collective"
            and report["key_changed_on_env_flip"]
            and not cutover_events
            and report["cutover_baseline_n"] > 0
            and report["baseline_roundtrip"])
        return report
    finally:
        if report.get("ok"):
            shutil.rmtree(work, ignore_errors=True)
        else:
            report["workdir"] = work


SCENARIOS = {"pod-kill": pod_kill, "straggler": straggler,
             "network-partition": network_partition,
             "preempt": preempt, "preempt-table": preempt_table,
             "preempt-warm": preempt_warm,
             "preempt-fused": preempt_fused,
             "preempt-adaptive": preempt_adaptive,
             "ckpt-corrupt": ckpt_corrupt,
             "master-kill": master_kill,
             "master-failover": master_failover,
             "hot-swap": hot_swap,
             "serve-drain": serve_drain,
             "perf-regress": perf_regress}


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    # --policy-prior PATH seeds preempt-adaptive from a persisted
    # preempt-table curve instead of the built-in drill-scale prior
    prior = ""
    filtered = []
    it = iter(argv)
    for a in it:
        if a == "--policy-prior":
            prior = next(it, "")
        elif a.startswith("--policy-prior="):
            prior = a.split("=", 1)[1]
        else:
            filtered.append(a)
    names = filtered or list(SCENARIOS)
    ok = True
    for name in names:
        fn = SCENARIOS.get(name)
        if fn is None:
            print(f"unknown scenario {name!r}; have {list(SCENARIOS)}",
                  file=sys.stderr)
            return 2
        report = (fn(policy_prior=prior)
                  if name == "preempt-adaptive" and prior else fn())
        print(json.dumps(report))
        ok = ok and report.get("ok", False)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Worker-side device probe — collective-hang localization.

Parity: reference `atorch/atorch/fault_tolerance/hanging_detector.py:86`
(probe collectives + shared-store relaunch flags that localize which rank
wedged).

TPU redesign: a probe *collective* would enqueue behind the stuck
collective and wedge with everyone else, telling us nothing.  Instead each
worker periodically enqueues a tiny single-device op under a watchdog
thread:

- probe completes fast → this worker's device queue is IDLE.  If its step
  reports are also stalled, it never REACHED the collective — it is the
  likely culprit, stuck in host code / data loading while its peers wait.
- probe never completes → the device is wedged inside the collective along
  with its peers (a victim, not the cause).

Results flow to the master as `report_diagnosis("probe", ...)` and the
diagnosis chain combines them with step cadence to name the wedged rank
(`manager.py ResolveHangCauseOperator`).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Optional

from ..common.log import get_logger

logger = get_logger("probe")


def _default_probe_op() -> None:
    """A tiny op on this process's first addressable device."""
    import jax
    import jax.numpy as jnp

    dev = jax.local_devices()[0]
    with jax.default_device(dev):
        jnp.add(1.0, 1.0).block_until_ready()


class DeviceProber:
    """Background thread: probe the device queue, report liveness."""

    def __init__(self, master_client=None, interval: float = 30.0,
                 timeout: float = 10.0,
                 probe_op: Optional[Callable[[], None]] = None):
        self.mc = master_client
        self.interval = interval
        self.timeout = timeout
        self._probe_op = probe_op or _default_probe_op
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._inflight: Optional[threading.Thread] = None
        self.last_result: Optional[dict] = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dwt-device-prober")
        self._thread.start()

    def probe_once(self) -> dict:
        """One probe with watchdog; returns {ok, latency_s}."""
        if self._inflight is not None and self._inflight.is_alive():
            # the previous probe is still stuck behind the device queue —
            # that IS the signal; don't stack more blocked threads
            result = {"ok": False, "latency_s": self.timeout}
        else:
            t0 = time.monotonic()
            done = threading.Event()

            def _run():
                try:
                    self._probe_op()
                    done.set()
                except Exception:  # noqa: BLE001 — a dying device reads
                    logger.debug("probe op failed", exc_info=True)  # as hung

            t = threading.Thread(target=_run, daemon=True,
                                 name="dwt-probe-op")
            t.start()
            ok = done.wait(self.timeout)
            self._inflight = None if ok else t
            result = {"ok": bool(ok),
                      "latency_s": round(time.monotonic() - t0, 4)}
        self.last_result = result
        if self.mc is not None:
            try:
                self.mc.report_diagnosis("probe", json.dumps(result))
            except Exception:  # noqa: BLE001
                logger.debug("probe report failed", exc_info=True)
        return result

    def _loop(self):
        while not self._stopped.wait(self.interval):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001
                logger.debug("probe loop error", exc_info=True)

    def stop(self):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

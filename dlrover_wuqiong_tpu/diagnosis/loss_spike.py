"""Loss-spike detection feeding the diagnosis chain.

Parity: reference `atorch/atorch/utils/loss_spike_utils.py:1-156`
(TokenLossSpike: sliding loss window, spike = ratio-over-average, sample
capture for postmortem).

TPU/control-plane redesign: workers push per-step losses through the
existing typed diagnosis report stream ("loss" payloads); the master-side
operator below runs inside the InferenceChain next to hang/straggler/OOM
detection, so a spike becomes a first-class DiagnosisAction ("rollback" —
restart the worker, which auto-resumes from the last committed flash
checkpoint, i.e. a state from before the spike) instead of a
worker-local log line.

Detection is ROBUST-statistics based: a spike must exceed the trailing
window's median by `sigma` robust standard deviations (MAD * 1.4826) AND
by a multiplicative `ratio` — the double test keeps ordinary optimization
noise (tiny MAD early in training, heavy-tailed batches later) from
firing.  A non-finite loss is always a spike.
"""

from __future__ import annotations

import math
import statistics
from typing import List

from ..common.log import get_logger
from .manager import DiagnosisDataManager, Inference, InferenceOperator

logger = get_logger("loss_spike")


class CheckLossSpikeOperator(InferenceOperator):
    """Symptom operator: windowed robust spike test per node."""

    name = "loss_spike"

    def __init__(self, sigma: float = 4.0, ratio: float = 1.5,
                 min_points: int = 10, max_age: float = 300.0):
        self.sigma = sigma
        self.ratio = ratio
        self.min_points = min_points
        self.max_age = max_age

    def infer(self, data: DiagnosisDataManager,
              problems: List[Inference]) -> List[Inference]:
        import time as _time

        out = []
        now = _time.time()
        for node_id, series in data.loss_series().items():
            if not series:
                continue
            ts, last_step, last = series[-1]
            if now - ts > self.max_age:
                # stale tail (worker restarting / eval phase): without this
                # gate the SAME spike sample re-fires a rollback every
                # cooldown interval until a fresh report displaces it
                continue
            if not math.isfinite(last):
                out.append(Inference(
                    "loss_spike", node_id=node_id, is_conclusion=True,
                    detail=f"non-finite loss {last} at step {last_step}",
                    step=int(last_step)))
                continue
            hist = [x for _, _, x in series[:-1] if math.isfinite(x)]
            if len(hist) < self.min_points:
                continue
            med = statistics.median(hist)
            mad = statistics.median(abs(x - med) for x in hist) * 1.4826
            # floor the scale: a perfectly flat window must still allow
            # ordinary float jitter without declaring a spike
            scale = max(mad, 1e-3, abs(med) * 0.01)
            if (last > med + self.sigma * scale
                    and last > self.ratio * max(med, 1e-8)):
                out.append(Inference(
                    "loss_spike", node_id=node_id, is_conclusion=True,
                    detail=(f"loss {last:.4g} at step {last_step} vs "
                            f"median {med:.4g} (mad {mad:.4g}) over "
                            f"{len(hist)} points"),
                    step=int(last_step)))
        return out

"""Diagnosis subsystem: collect runtime reports, infer failures (hang, slow).

Parity: reference `dlrover/python/master/diagnosis/` (`DiagnosisManager` :31,
`_diagnose_failures` :67, `InferenceChain`, `CheckTrainingHangOperator`) and
data model `common/diagnosis.py`.  TPU adaptation: reports carry step progress,
host resource stats, and (later) libtpu chip metrics instead of CudaLog.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from typing import Deque, Dict, List, Optional

from ..common import messages as msg
from ..common.log import get_logger

logger = get_logger("diagnosis")


class InferenceOperator:
    """One rule in the inference chain: observations -> conclusions."""

    name = "base"

    def infer(self, data: "DiagnosisDataManager") -> List[msg.DiagnosisAction]:
        return []


class CheckTrainingHangOperator(InferenceOperator):
    """Training is hanged if no node reported step progress for `timeout` s.

    Parity: reference diagnosis/operator/check_training_hang_operator.py.
    """

    name = "check_training_hang"

    def __init__(self, timeout: float = 1800.0):
        self.timeout = timeout

    def infer(self, data: "DiagnosisDataManager") -> List[msg.DiagnosisAction]:
        latest = data.latest_step_time()
        if latest is None:
            return []
        if time.time() - latest > self.timeout:
            return [msg.DiagnosisAction(
                action="restart_worker",
                reason=f"no step progress for >{self.timeout}s")]
        return []


class CheckResourceAnomalyOperator(InferenceOperator):
    """Flag nodes with pathological host-memory growth (OOM precursor)."""

    name = "check_resource_anomaly"

    def __init__(self, memory_limit_mb: float = 0.0):
        self.memory_limit_mb = memory_limit_mb

    def infer(self, data: "DiagnosisDataManager") -> List[msg.DiagnosisAction]:
        if self.memory_limit_mb <= 0:
            return []
        actions = []
        for node_id, stats in data.latest_resource_stats().items():
            if stats.get("memory_mb", 0.0) > self.memory_limit_mb:
                actions.append(msg.DiagnosisAction(
                    action="relaunch_node", node_id=node_id,
                    reason="host memory over limit"))
        return actions


class DiagnosisDataManager:
    """Sliding-window store of diagnosis reports."""

    def __init__(self, window: int = 600):
        self._lock = threading.Lock()
        self._step_reports: Deque = deque(maxlen=window)
        self._resource: Dict[int, Dict[str, float]] = {}
        self._stacks: Dict[int, str] = {}

    def store_report(self, report: msg.DiagnosisReport):
        with self._lock:
            ts = report.timestamp or time.time()
            if report.payload_type == "step":
                self._step_reports.append((ts, report.node_id,
                                           report.content))
            elif report.payload_type == "resource":
                try:
                    import json
                    self._resource[report.node_id] = json.loads(
                        report.content)
                except ValueError:
                    pass
            elif report.payload_type == "stack":
                self._stacks[report.node_id] = report.content

    def latest_step_time(self) -> Optional[float]:
        with self._lock:
            if not self._step_reports:
                return None
            return self._step_reports[-1][0]

    def latest_resource_stats(self) -> Dict[int, Dict[str, float]]:
        with self._lock:
            return dict(self._resource)

    def node_stack(self, node_id: int) -> str:
        with self._lock:
            return self._stacks.get(node_id, "")


class DiagnosisManager:
    """Periodic inference over collected metrics (parity diagnosis.py:31)."""

    def __init__(self, hang_timeout: float = 1800.0):
        self.data = DiagnosisDataManager()
        self._operators: List[InferenceOperator] = [
            CheckTrainingHangOperator(hang_timeout),
            CheckResourceAnomalyOperator(),
        ]
        self._pending_actions: Deque[msg.DiagnosisAction] = deque()
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def collect_report(self, report: msg.DiagnosisReport) -> msg.DiagnosisAction:
        self.data.store_report(report)
        with self._lock:
            if self._pending_actions:
                return self._pending_actions.popleft()
        return msg.DiagnosisAction()

    def diagnose_once(self) -> List[msg.DiagnosisAction]:
        actions: List[msg.DiagnosisAction] = []
        for op in self._operators:
            try:
                actions.extend(op.infer(self.data))
            except Exception:  # noqa: BLE001
                logger.exception("diagnosis operator %s failed", op.name)
        with self._lock:
            self._pending_actions.extend(actions)
        return actions

    def start(self, interval: float = 60.0):
        def _loop():
            while not self._stopped.wait(interval):
                acts = self.diagnose_once()
                for a in acts:
                    logger.warning("diagnosis action: %s (%s)", a.action,
                                   a.reason)

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="dwt-diagnosis")
        self._thread.start()

    def stop(self):
        self._stopped.set()

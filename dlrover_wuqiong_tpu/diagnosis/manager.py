"""Diagnosis subsystem: observe → infer → act.

Parity: reference `dlrover/python/master/diagnosis/` — `DiagnosisManager`
(diagnosis.py:31, `_diagnose_failures` :67), `InferenceChain`
(inferencechain/inference_chain.py), `CheckTrainingHangOperator`
(operator/check_training_hang_operator.py), data model
`common/diagnosis.py`, and the restart-decision coupling back into the job
manager.

TPU adaptation: reports carry step progress, host resource stats and worker
stacks instead of CudaLog; the "chip" signal is step cadence (an ICI/HBM
fault shows up as a straggling or stalled step long before anything else).

Structure: symptom operators raise `Inference` problems; cause operators
refine compatible problems into root-cause conclusions; the manager turns
conclusions into `DiagnosisAction`s and (when wired with a job manager)
executes them — restart_worker sets the restart flag delivered via
heartbeat, relaunch_node pushes a FAILED event through the relaunch
decision table.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..common import messages as msg
from ..common.log import get_logger

logger = get_logger("diagnosis")


# -------------------------------------------------------------- data model


@dataclasses.dataclass
class Inference:
    """A problem or conclusion flowing through the chain.

    Parity: reference common/inference.py (name/attribution/description).
    """

    name: str                      # e.g. "training_hang", "straggler"
    node_id: int = -1
    detail: str = ""
    is_conclusion: bool = False
    step: int = -1                 # onset step (loss_spike), -1 = n/a


class DiagnosisDataManager:
    """Sliding-window store of diagnosis reports."""

    def __init__(self, window: int = 600):
        self._lock = threading.Lock()
        self._step_reports: Deque = deque(maxlen=window)
        self._node_steps: Dict[int, Deque] = {}
        self._resource: Dict[int, Deque] = {}
        self._stacks: Dict[int, str] = {}
        self._op_profiles: Dict[int, Tuple[float, str]] = {}
        self._probes: Dict[int, Tuple[float, bool]] = {}
        self._losses: Dict[int, Deque] = {}

    def forget_node(self, node_id: int):
        """Drop a departed node's series — stale timestamps otherwise keep
        getting blamed as hang culprits / OOM candidates forever."""
        with self._lock:
            self._node_steps.pop(node_id, None)
            self._resource.pop(node_id, None)
            self._stacks.pop(node_id, None)
            self._op_profiles.pop(node_id, None)
            self._probes.pop(node_id, None)
            self._losses.pop(node_id, None)

    def store_report(self, report: msg.DiagnosisReport):
        with self._lock:
            ts = report.timestamp or time.time()
            if report.payload_type == "step":
                self._step_reports.append((ts, report.node_id,
                                           report.content))
                self._node_steps.setdefault(
                    report.node_id, deque(maxlen=64)).append(ts)
            elif report.payload_type == "resource":
                try:
                    stats = json.loads(report.content)
                    self._resource.setdefault(
                        report.node_id, deque(maxlen=64)).append(
                        (ts, stats))
                except ValueError:
                    pass
            elif report.payload_type == "stack":
                self._stacks[report.node_id] = report.content
            elif report.payload_type == "op_profile":
                # xpu_timer parity: worker-pushed top-slow-collective JSON
                # (utils/xplane.py OpProfile.collective_evidence)
                self._op_profiles[report.node_id] = (ts, report.content)
            elif report.payload_type == "loss":
                # {"step": N, "loss": x} — feeds CheckLossSpikeOperator
                try:
                    d = json.loads(report.content)
                    self._losses.setdefault(
                        report.node_id, deque(maxlen=256)).append(
                        (ts, int(d.get("step", -1)),
                         float(d.get("loss", float("nan")))))
                except (ValueError, TypeError):
                    pass
            elif report.payload_type == "probe":
                # device-queue liveness (diagnosis/probe.py DeviceProber)
                try:
                    res = json.loads(report.content)
                    if isinstance(res, dict):
                        self._probes[report.node_id] = (ts,
                                                        bool(res.get("ok")))
                except ValueError:
                    pass

    def latest_step_time(self) -> Optional[float]:
        with self._lock:
            if not self._step_reports:
                return None
            return self._step_reports[-1][0]

    def node_step_times(self) -> Dict[int, List[float]]:
        with self._lock:
            return {n: list(d) for n, d in self._node_steps.items()}

    def latest_resource_stats(self) -> Dict[int, Dict[str, float]]:
        with self._lock:
            return {n: d[-1][1] for n, d in self._resource.items() if d}

    def resource_series(self, node_id: int) -> List:
        with self._lock:
            return list(self._resource.get(node_id, ()))

    def node_stack(self, node_id: int) -> str:
        with self._lock:
            return self._stacks.get(node_id, "")

    def loss_series(self) -> Dict[int, List[Tuple[float, int, float]]]:
        with self._lock:
            return {n: list(d) for n, d in self._losses.items()}

    def probe_status(self, max_age: float = 300.0) -> Dict[int, bool]:
        """node → device-queue-idle? from recent DeviceProber reports."""
        now = time.time()
        with self._lock:
            return {n: ok for n, (ts, ok) in self._probes.items()
                    if now - ts <= max_age}

    def node_op_profile(self, node_id: int, max_age: float = 3600.0) -> str:
        """Latest collective-latency evidence, unless stale — a fire-once
        profile window must not be cited for a hang hours later."""
        with self._lock:
            ts, content = self._op_profiles.get(node_id, (0.0, ""))
            if content and time.time() - ts > max_age:  # graftlint: disable=wall-clock-duration -- ts is a node-reported wall timestamp (cross-process)
                return ""
            return content

    # evidence JSON keys derived from a PerfSnapshot — ADD-ONLY (pinned
    # by tests/test_perf.py: ResolveHangCauseOperator and operators yet
    # to come read these names out of node_op_profile content)
    PERF_EVIDENCE_KEYS = ("source", "step", "key", "step_time_s",
                         "categories")

    def store_perf_snapshot(self, node_id: int, snapshot: Dict):
        """Fold a perf-observatory snapshot (telemetry/perf.py
        PERF_SNAPSHOT_KEYS dict) into the SAME op-profile store the
        worker-pushed ``op_profile`` DiagnosisReport lands in — the
        master keeps ONE source of truth for "where device time goes",
        whether it arrived as diagnosis evidence or perf telemetry."""
        if not isinstance(snapshot, dict) or not snapshot.get("categories"):
            return
        evidence = json.dumps({
            "source": "perf_snapshot",
            "step": int(snapshot.get("step", -1)),
            "key": str(snapshot.get("key", "")),
            "step_time_s": float(snapshot.get("step_time_s", 0.0)),
            "categories": {str(k): float(v) for k, v in
                           sorted(snapshot.get("categories", {}).items())},
        })
        with self._lock:
            self._op_profiles[node_id] = (
                float(snapshot.get("captured_at", 0.0)) or time.time(),
                evidence)


# --------------------------------------------------------------- operators


class InferenceOperator:
    """One rule in the chain. Symptom ops take no input problems; cause ops
    declare which problem names they refine."""

    name = "base"
    refines: tuple = ()  # problem names this operator can resolve

    def infer(self, data: DiagnosisDataManager,
              problems: List[Inference]) -> List[Inference]:
        return []


class CheckTrainingHangOperator(InferenceOperator):
    """Symptom: no step progress anywhere for `timeout` seconds.

    Parity: reference operator/check_training_hang_operator.py.
    """

    name = "check_training_hang"

    def __init__(self, timeout: float = 1800.0):
        self.timeout = timeout

    def infer(self, data, problems):
        latest = data.latest_step_time()
        if latest is None:
            return []
        if time.time() - latest > self.timeout:  # graftlint: disable=wall-clock-duration -- step-report timestamps are node wall clock (cross-process)
            return [Inference("training_hang",
                              detail=f"no step progress for "
                                     f">{self.timeout:.0f}s")]
        return []


class ResolveHangCauseOperator(InferenceOperator):
    """Cause: which node stopped first / looks stuck (stack available)."""

    name = "resolve_hang_cause"
    refines = ("training_hang",)

    def infer(self, data, problems):
        out = []
        for p in problems:
            if p.name not in self.refines:
                continue
            node_steps = data.node_step_times()
            if not node_steps:
                out.append(Inference("training_hang", is_conclusion=True,
                                     detail=p.detail))
                continue
            probes = data.probe_status()
            culprit, how = self._localize(node_steps, probes)
            stack = data.node_stack(culprit)
            ops = data.node_op_profile(culprit)
            out.append(Inference(
                "hang_culprit", node_id=culprit, is_conclusion=True,
                detail=(p.detail + f"; node {culprit} {how}"
                        + ("; stack available" if stack else "")
                        + (f"; slowest collectives: {ops}" if ops
                           else ""))))
        return out

    @staticmethod
    def _localize(node_steps, probes):
        """Name the wedged rank from step cadence + device probes.

        A rank whose device probe still completes (queue IDLE) while peers'
        probes wedge never REACHED the collective — it is the cause, not a
        victim (diagnosis/probe.py).  Without probe disagreement, fall back
        to the oldest step report."""
        if probes and any(probes.values()) and not all(probes.values()):
            idle = [n for n, ok in probes.items() if ok]
            # among idle-device nodes, the one with the oldest step stalled
            # in host code first
            cand = [(node_steps[n][-1], n) for n in idle
                    if node_steps.get(n)]
            if cand:
                _, culprit = min(cand)
                return culprit, ("never joined the collective (device "
                                 "idle while peers wedged)")
        culprit, _ = min(((n, t[-1]) for n, t in node_steps.items() if t),
                         key=lambda kv: kv[1])
        return culprit, "stalled first"


class CheckStragglerOperator(InferenceOperator):
    """Symptom+conclusion: a node stepping far slower than its peers.

    Parity: the straggler half of the network-check subsystem
    (rdzv_manager.py:532 get_straggler) driven from runtime cadence.
    """

    name = "check_straggler"

    def __init__(self, ratio: float = 3.0, min_reports: int = 6):
        self.ratio = ratio
        self.min_reports = min_reports

    @staticmethod
    def _median(xs: List[float]) -> float:
        s = sorted(xs)
        return s[len(s) // 2]

    def infer(self, data, problems):
        cadence = {}
        for node, times in data.node_step_times().items():
            if len(times) >= self.min_reports:
                deltas = [b - a for a, b in zip(times, times[1:])]
                cadence[node] = self._median(deltas)
        if len(cadence) < 2:
            return []
        med = self._median(list(cadence.values()))
        if med <= 0:
            return []
        out = []
        for node, c in cadence.items():
            if c > self.ratio * med:
                out.append(Inference(
                    "straggler", node_id=node, is_conclusion=True,
                    detail=f"step cadence {c:.2f}s vs peer median "
                           f"{med:.2f}s"))
        return out


class CheckMemoryTrendOperator(InferenceOperator):
    """Conclusion: host memory trending toward the limit (OOM precursor)."""

    name = "check_memory_trend"

    def __init__(self, memory_limit_mb: float = 0.0,
                 horizon_s: float = 600.0, min_points: int = 4):
        self.memory_limit_mb = memory_limit_mb
        self.horizon_s = horizon_s
        self.min_points = min_points

    def infer(self, data, problems):
        if self.memory_limit_mb <= 0:
            return []
        out = []
        now = time.time()
        for node_id in list(data.latest_resource_stats()):
            series = [(ts, s.get("memory_mb", 0.0))
                      for ts, s in data.resource_series(node_id)]
            if not series:
                continue
            mem_now = series[-1][1]
            if mem_now > self.memory_limit_mb:
                out.append(Inference(
                    "memory_over_limit", node_id=node_id,
                    is_conclusion=True,
                    detail=f"{mem_now:.0f}MB > {self.memory_limit_mb:.0f}"
                           f"MB"))
                continue
            if len(series) < self.min_points:
                continue
            (t0, m0), (t1, m1) = series[0], series[-1]
            if t1 <= t0 or m1 <= m0:
                continue
            slope = (m1 - m0) / (t1 - t0)  # MB/s
            eta = (self.memory_limit_mb - m1) / slope
            if eta < self.horizon_s:
                out.append(Inference(
                    "memory_trend", node_id=node_id, is_conclusion=True,
                    detail=f"{m1:.0f}MB growing {slope * 60:.1f}MB/min — "
                           f"limit in ~{eta:.0f}s"))
        return out


class InferenceChain:
    """Run symptom operators, then refine until conclusions stabilize.

    Parity: reference inferencechain/inference_chain.py.
    """

    def __init__(self, operators: List[InferenceOperator]):
        self.operators = operators

    def run(self, data: DiagnosisDataManager) -> List[Inference]:
        problems: List[Inference] = []
        for op in self.operators:
            if op.refines:
                continue
            try:
                problems.extend(op.infer(data, []))
            except Exception:  # noqa: BLE001
                logger.exception("diagnosis operator %s failed", op.name)
        open_problems = [p for p in problems if not p.is_conclusion]
        conclusions = [p for p in problems if p.is_conclusion]
        for op in self.operators:
            if not op.refines or not open_problems:
                continue
            try:
                refined = op.infer(data, open_problems)
            except Exception:  # noqa: BLE001
                logger.exception("diagnosis operator %s failed", op.name)
                continue
            resolved_names = {p.name for p in open_problems
                              if p.name in op.refines}
            open_problems = [p for p in open_problems
                             if p.name not in resolved_names]
            conclusions.extend(r for r in refined if r.is_conclusion)
            open_problems.extend(r for r in refined if not r.is_conclusion)
        # unrefined problems surface as conclusions of their own
        conclusions.extend(open_problems)
        return conclusions


_ACTION_FOR = {
    "training_hang": "restart_worker",
    "hang_culprit": "restart_worker",
    "straggler": "report",           # surfaced; operator policy decides
    "memory_over_limit": "relaunch_node",
    "memory_trend": "report",
    # rollback = restart the worker; the action carries the spike-onset
    # step so the resume targets the newest committed flash checkpoint
    # PRECEDING the spike (the latest commit may postdate onset)
    "loss_spike": "rollback",
}


class DiagnosisManager:
    """Periodic inference + action execution (parity diagnosis.py:31)."""

    def __init__(self, hang_timeout: float = 1800.0,
                 memory_limit_mb: float = 0.0, job_manager=None,
                 action_cooldown: float = 0.0):
        from .loss_spike import CheckLossSpikeOperator

        self.data = DiagnosisDataManager()
        self.chain = InferenceChain([
            CheckTrainingHangOperator(hang_timeout),
            CheckStragglerOperator(),
            CheckMemoryTrendOperator(memory_limit_mb),
            CheckLossSpikeOperator(),
            ResolveHangCauseOperator(),
        ])
        self.job_manager = job_manager
        # min seconds between re-firing the same (action, node) — a hang
        # that takes minutes to recover must not be re-killed every tick
        # while the restarted worker is still compiling.  Default: half
        # the hang timeout.
        self.action_cooldown = action_cooldown or max(hang_timeout / 2,
                                                      120.0)
        self._last_fired: Dict[tuple, float] = {}
        self._pending_actions: Deque[msg.DiagnosisAction] = deque(
            maxlen=100)
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def collect_report(self, report: msg.DiagnosisReport
                       ) -> msg.DiagnosisAction:
        self.data.store_report(report)
        with self._lock:
            if self._pending_actions:
                return self._pending_actions.popleft()
        return msg.DiagnosisAction()

    def diagnose_once(self) -> List[msg.DiagnosisAction]:
        conclusions = self.chain.run(self.data)
        now = time.time()
        actions = []
        for c in conclusions:
            action = _ACTION_FOR.get(c.name, "report")
            key = (action, c.node_id)
            if action != "report":
                last = self._last_fired.get(key, 0.0)
                if now - last < self.action_cooldown:
                    continue  # still recovering from the previous action
                self._last_fired[key] = now
            actions.append(msg.DiagnosisAction(
                action=action, node_id=c.node_id,
                reason=f"{c.name}: {c.detail}", step=c.step))
        for a in actions:
            self._execute(a)
        with self._lock:
            self._pending_actions.extend(
                a for a in actions if a.action != "report")
        return actions

    def _execute(self, action: msg.DiagnosisAction):
        """Couple conclusions back into the job manager's decision table.

        Parity: the reference master acts on diagnosis through the same
        relaunch machinery as platform events.
        """
        if self.job_manager is None or action.action == "report":
            return
        try:
            if action.action in ("restart_worker", "rollback"):
                nodes = ([self.job_manager.get_node(action.node_id)]
                         if action.node_id >= 0
                         else self.job_manager.running_nodes())
                for node in nodes:
                    if node is not None:
                        node.restart_training = True
                        if action.action == "rollback" and action.step >= 0:
                            # spike onset: the restarted worker must resume
                            # from a ckpt committed BEFORE this step — the
                            # latest commit can postdate onset (ADVICE r4)
                            node.rollback_before_step = action.step
            elif action.action == "relaunch_node":
                from ..common.constants import (
                    NodeEventType,
                    NodeExitReason,
                    NodeStatus,
                )
                from ..common.node import Node, NodeEvent

                target = self.job_manager.get_node(action.node_id)
                if target is not None:
                    ev = Node(target.type, target.id,
                              rank_index=target.rank_index)
                    ev.status = NodeStatus.FAILED
                    ev.exit_reason = NodeExitReason.OOM
                    self.job_manager.process_event(
                        NodeEvent(NodeEventType.MODIFIED, ev))
        except Exception:  # noqa: BLE001
            logger.exception("diagnosis action %s failed", action.action)

    def start(self, interval: float = 60.0):
        def _loop():
            while not self._stopped.wait(interval):
                acts = self.diagnose_once()
                for a in acts:
                    logger.warning("diagnosis action: %s (%s)", a.action,
                                   a.reason)

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="dwt-diagnosis")
        self._thread.start()

    def stop(self):
        self._stopped.set()

"""Job/node management: registry, heartbeats, relaunch decisions.

Parity: reference `master/node/dist_job_manager.py` (`_monitor_nodes` :334,
`_should_relaunch` :561, `_relaunch_node` :605), `master/node/local_job_manager.py`,
and event-callback wiring (`master/node/event_callback.py`).  Round 1 ships the
local/in-process variant plus the platform-agnostic decision logic; the k8s
scaler/watcher pair plugs into the same interfaces.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from ..common.global_context import get_context
from ..common.log import get_logger
from ..common.node import Node, NodeEvent, NodeStateFlow
from .error_monitor import ErrorMonitor

logger = get_logger("job_manager")


class NodeEventCallback:
    """Parity: reference event_callback.py; hooks on node phase transitions."""

    def on_node_started(self, node: Node):
        pass

    def on_node_succeeded(self, node: Node):
        pass

    def on_node_failed(self, node: Node):
        pass

    def on_node_deleted(self, node: Node):
        pass


class Scaler:
    """Applies scale decisions to the platform (create/remove nodes)."""

    def scale_up(self, node: Node):
        raise NotImplementedError

    def scale_down(self, node: Node):
        raise NotImplementedError


class NoopScaler(Scaler):
    def scale_up(self, node: Node):
        logger.info("noop scaler: would launch %s", node)

    def scale_down(self, node: Node):
        logger.info("noop scaler: would remove %s", node)


class WarmMeshPolicy:
    """Scale-plan preference for worlds whose train_step is already
    compiled (auto/warm_pool.py state, read as plain JSON — no JAX).

    PHOENIX/ElasWave stance (PAPERS.md): when reconfiguration cost is
    near zero the optimal elastic policy changes.  A degraded world with
    a ready warm-pool entry restarts in restore-time only, so the master
    should (a) form it immediately instead of holding the straggler
    grace window open, and (b) when several target sizes are valid,
    prefer the largest warm one.  Pool state is host-local; on a
    multi-host control plane this is the master-host view — agents keep
    their own pools for the worker-side XLA hit, which is the one that
    pays.
    """

    def __init__(self, cache_dir: Optional[str] = None,
                 devices_per_node_fn: Optional[Callable[[], int]] = None):
        if cache_dir is None:
            from ..auto.compile_cache import default_cache_dir

            cache_dir = default_cache_dir()
        self.cache_dir = cache_dir
        self._devices_per_node_fn = devices_per_node_fn or (lambda: 1)

    def world_devices(self, n_nodes: int) -> int:
        return n_nodes * max(1, int(self._devices_per_node_fn()))

    def is_warm_world(self, n_nodes: int) -> bool:
        from ..auto.warm_pool import warm_device_counts

        counts = warm_device_counts(self.cache_dir)
        return counts.get(self.world_devices(n_nodes), 0) > 0

    def preferred_world_size(self, candidates) -> Optional[int]:
        """Largest candidate node count with a warm mesh; None when cold
        everywhere (no preference — capacity wins)."""
        for n in sorted(set(candidates), reverse=True):
            if n > 0 and self.is_warm_world(n):
                return n
        return None


class JobManager:
    """Tracks training nodes, processes events, decides relaunches."""

    def __init__(self, scaler: Optional[Scaler] = None,
                 max_relaunch_count: Optional[int] = None):
        ctx = get_context()
        self._lock = threading.Lock()
        self._nodes: Dict[int, Node] = {}
        self._scaler = scaler or NoopScaler()
        self._max_relaunch = (max_relaunch_count
                              if max_relaunch_count is not None
                              else ctx.max_relaunch_count)
        self._callbacks: List[NodeEventCallback] = []
        self._next_node_id = 0
        self._stopped = threading.Event()
        self._heartbeat_timeout = ctx.node_heartbeat_timeout
        self.error_monitor = ErrorMonitor()
        self._relaunch_listeners: List[Callable[[Node, Node], None]] = []

    # ------------------------------------------------------------- registry

    def add_node_event_callback(self, cb: NodeEventCallback):
        self._callbacks.append(cb)

    def register_node(self, node_type: str, node_id: Optional[int] = None,
                      rank_index: Optional[int] = None, addr: str = "") -> Node:
        with self._lock:
            if node_id is None:
                node_id = self._next_node_id
            self._next_node_id = max(self._next_node_id, node_id + 1)
            node = self._nodes.get(node_id)
            if node is None:
                node = Node(node_type, node_id, rank_index=rank_index,
                            max_relaunch_count=self._max_relaunch)
                self._nodes[node_id] = node
            node.addr = addr or node.addr
            node.heartbeat_time = time.time()
            return node

    def get_node(self, node_id: int) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(node_id)

    def all_nodes(self) -> List[Node]:
        with self._lock:
            return list(self._nodes.values())

    def running_nodes(self) -> List[Node]:
        with self._lock:
            return [n for n in self._nodes.values()
                    if n.status == NodeStatus.RUNNING]

    # ------------------------------------------------------------- heartbeats

    def collect_heartbeat(self, node_id: int,
                          timestamp: Optional[float] = None) -> str:
        """Returns an action for the node ("" | "restart" | "stop")."""
        return self.collect_heartbeat_full(node_id, timestamp)[0]

    def collect_heartbeat_full(self, node_id: int,
                               timestamp: Optional[float] = None
                               ) -> tuple:
        """(action, rollback_before_step) — step is -1 unless a loss-spike
        rollback pinned a pre-spike resume ceiling on the node."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return "", -1
            node.heartbeat_time = timestamp or time.time()
            if node.status in (NodeStatus.INITIAL, NodeStatus.PENDING):
                node.update_status(NodeStatus.RUNNING)
            if node.restart_training:
                node.restart_training = False
                rb, node.rollback_before_step = node.rollback_before_step, -1
                return "restart", rb
            return "", -1

    def get_dead_nodes(self) -> List[Node]:
        """Nodes whose heartbeat timed out (parity `_get_dead_node_event`)."""
        now = time.time()
        with self._lock:
            return [
                n for n in self._nodes.values()
                if n.status == NodeStatus.RUNNING
                and n.heartbeat_time > 0
                and now - n.heartbeat_time > self._heartbeat_timeout
            ]

    # ------------------------------------------------------------- events

    def process_event(self, event: NodeEvent):
        """Apply a platform event through the state machine; maybe relaunch.

        Parity: reference `_process_event` dist_job_manager.py:473.
        """
        node = self.register_node(event.node.type, event.node.id,
                                  event.node.rank_index)
        old_status = node.status
        new_status = event.node.status
        if event.event_type == NodeEventType.DELETED:
            new_status = NodeStatus.DELETED
        if not NodeStateFlow.can_transition(old_status, new_status):
            return
        node.update_status(new_status)
        node.exit_reason = event.node.exit_reason or node.exit_reason
        if node.exit_reason and \
                node.exit_reason not in NodeExitReason.KNOWN:
            # scheduler watchers report raw strings ("exit_code=137",
            # "actor_died") — run them through the error catalogue so the
            # relaunch table acts on a class and the rank accrues history
            reason, _ = self.error_monitor.process_error(
                node.rank_index, node.relaunch_count, node.exit_reason,
                node_id=node.id)
            node.exit_reason = reason
        self._fire_callbacks(node, old_status, new_status)
        if NodeStateFlow.should_relaunch(old_status, new_status):
            if self._should_relaunch(node):
                self._relaunch_node(node)
            else:
                node.relaunchable = False
                logger.warning("node %s not relaunchable (reason=%s count=%d)",
                               node.id, node.exit_reason, node.relaunch_count)

    def _fire_callbacks(self, node: Node, old: str, new: str):
        for cb in self._callbacks:
            try:
                if new == NodeStatus.RUNNING:
                    cb.on_node_started(node)
                elif new == NodeStatus.SUCCEEDED:
                    cb.on_node_succeeded(node)
                elif new in (NodeStatus.FAILED, NodeStatus.BREAKDOWN):
                    cb.on_node_failed(node)
                elif new == NodeStatus.DELETED:
                    cb.on_node_deleted(node)
            except Exception:  # noqa: BLE001
                logger.exception("node event callback error")

    def _should_relaunch(self, node: Node) -> bool:
        """Parity: reference `_should_relaunch` dist_job_manager.py:561 +
        the error-class catalogue (monitor/error_monitor.py)."""
        ctx = get_context()
        if node.is_released:
            return False
        if node.exit_reason == NodeExitReason.FATAL_ERROR and \
                not ctx.relaunch_always:
            return False
        if node.exit_reason == NodeExitReason.OOM:
            # bump memory ask and retry (resource optimizer refines it)
            node.config_resource.memory_mb *= 1.5
        # keyed by rank_index: node ids change across relaunches but the
        # rank's error history is what reveals a persistent failure
        repeated = self.error_monitor.repeated_class(node.rank_index)
        if repeated is not None and not ctx.relaunch_always:
            # the same error class on 3+ consecutive restarts: relaunching
            # is not fixing it — stop burning restarts
            logger.warning("node %s keeps failing with %r — not "
                           "relaunching", node.id, repeated)
            return False
        if node.relaunch_count >= node.max_relaunch_count:
            return False
        return True

    def _relaunch_node(self, old_node: Node):
        with self._lock:
            new_id = self._next_node_id
            self._next_node_id += 1
            new_node = old_node.get_relaunch_node_info(new_id)
            self._nodes[new_id] = new_node
            old_node.is_released = True
        logger.info("relaunching %s as node %s (attempt %d)", old_node,
                    new_id, new_node.relaunch_count)
        # a hung node (heartbeat timeout) is still RUNNING on the platform —
        # tear it down before its replacement, or both consume resources
        # (delete of an already-dead pod/process is an idempotent no-op)
        self._scaler.scale_down(old_node)
        self._scaler.scale_up(new_node)
        for listener in self._relaunch_listeners:
            listener(old_node, new_node)

    def add_relaunch_listener(self, fn: Callable[[Node, Node], None]):
        self._relaunch_listeners.append(fn)

    # ------------------------------------------------------------- scale plan

    def devices_per_node(self) -> int:
        """Largest accelerator count any registered node declared (the
        agent registers nproc_per_node); 1 before any registration."""
        with self._lock:
            return max(
                [n.config_resource.accelerator_num
                 for n in self._nodes.values()
                 if n.config_resource.accelerator_num > 0] or [1])

    def make_warm_mesh_policy(self, cache_dir: Optional[str] = None
                              ) -> WarmMeshPolicy:
        """Policy bound to this job's observed topology — wired into the
        rendezvous manager by the master so re-formed worlds prefer
        already-compiled meshes."""
        return WarmMeshPolicy(cache_dir=cache_dir,
                              devices_per_node_fn=self.devices_per_node)

    # ------------------------------------------------------------- status

    def all_workers_exited(self) -> bool:
        with self._lock:
            workers = [n for n in self._nodes.values()
                       if n.type == NodeType.WORKER and not n.is_released]
            return bool(workers) and all(n.exited() for n in workers)

    def all_workers_succeeded(self) -> bool:
        with self._lock:
            workers = [n for n in self._nodes.values()
                       if n.type == NodeType.WORKER and not n.is_released]
            return bool(workers) and all(
                n.status == NodeStatus.SUCCEEDED for n in workers)

    def has_failed_worker(self) -> bool:
        with self._lock:
            return any(n.type == NodeType.WORKER
                       and n.status == NodeStatus.FAILED
                       and not n.relaunchable
                       for n in self._nodes.values())


class DistJobManager(JobManager):
    """Platform-backed manager: scheduler client + scaler + watcher.

    Parity: reference `DistributedJobManager` (`dist_job_manager.py:88`) —
    `start` creates the initial scale plan (`_create_initial_scale_plan`
    :242) and starts the watch/heartbeat threads (:334, :355); relaunch
    decisions flow through the PodScaler instead of a noop.
    """

    def __init__(self, scheduler_client, num_workers: int = 1,
                 spec_factory=None, max_relaunch_count: Optional[int] = None):
        from ..scheduler.subprocess_scheduler import (
            SubprocessSchedulerClient,
        )
        from .scaler import PodScaler, ScalePlan
        from .watcher import PodWatcher

        if spec_factory is None and isinstance(scheduler_client,
                                               SubprocessSchedulerClient):
            # the default spec has no command — every launch would fail
            # through the retry queue and silently drop the node
            raise ValueError(
                "DistJobManager over the subprocess backend needs a "
                "spec_factory that sets NodeSpec.command")
        self._client = scheduler_client
        scaler = PodScaler(scheduler_client, spec_factory=spec_factory)
        super().__init__(scaler=scaler,
                         max_relaunch_count=max_relaunch_count)
        self._num_workers = num_workers
        self._watcher = PodWatcher(scheduler_client, self.process_event)
        self._heartbeat_thread: Optional[threading.Thread] = None
        self._ScalePlan = ScalePlan

    def start(self):
        """Initial scale plan + watch/heartbeat monitors."""
        plan = self._ScalePlan()
        for i in range(self._num_workers):
            node = self.register_node(NodeType.WORKER, i, rank_index=i)
            node.update_status(NodeStatus.PENDING)
            plan.launch_nodes.append(self._scaler.spec_for(node))
        self._scaler.scale(plan)
        self._watcher.start()
        self._start_heartbeat_monitor()

    def _start_heartbeat_monitor(self):
        def _loop():
            while not self._stopped.wait(
                    get_context().node_heartbeat_interval):
                for node in self.get_dead_nodes():
                    logger.warning("node %s heartbeat timed out", node.id)
                    ev = Node(node.type, node.id,
                              rank_index=node.rank_index)
                    ev.status = NodeStatus.FAILED
                    ev.exit_reason = NodeExitReason.HANG
                    self.process_event(NodeEvent(NodeEventType.MODIFIED,
                                                 ev))

        self._heartbeat_thread = threading.Thread(
            target=_loop, daemon=True, name="dwt-heartbeat-monitor")
        self._heartbeat_thread.start()

    def stop(self):
        self._stopped.set()
        self._watcher.stop()
        self._scaler.stop()


class LocalJobManager(JobManager):
    """Single-node manager backing `--standalone` (parity local_job_manager.py)."""

    def start(self, num_workers: int = 1):
        for i in range(num_workers):
            node = self.register_node(NodeType.WORKER, i, rank_index=i)
            node.update_status(NodeStatus.PENDING)

    def _relaunch_node(self, old_node: Node):
        # local processes keep their identity across restarts: reset in place
        with self._lock:
            old_node.inc_relaunch_count()
            old_node.status = NodeStatus.PENDING
            old_node.exit_reason = ""
            old_node.heartbeat_time = time.time()
        logger.info("local relaunch of %s (attempt %d)", old_node,
                    old_node.relaunch_count)
        for listener in self._relaunch_listeners:
            listener(old_node, old_node)

"""Serving request queue on the master: admission, leases, recovery.

Parity: reference `dlrover/python/master/shard/task_manager.py` (the
training-shard dispatch queue) — this is its serving counterpart.  The
same durability contract applies: every mutating verb is journaled
BEFORE the ack (servicer.py), so a master restart replays submissions,
leases and results and no in-flight request is ever dropped — the
property the `chaos serve-drain` drill pins.

Lifecycle: submitted → pending (FIFO) → leased (per worker) → done.
A worker death moves its leased requests back to the FRONT of the
pending queue (`recover_node`) and bumps ``requeued_total`` — recovery
is *attributed*, mirroring how `TaskManager.recover_tasks` re-queues
dispatched shards.  Submission is idempotent per ``request_id`` (replay
+ client retries both hit the dedupe).

Worker serving-ledger snapshots aggregate latest-SENT-wins per node,
exactly like the master's goodput collection (master.py
collect_goodput): reports ride the BUFFERED verb class and a drained
stale buffer must not overwrite a fresher snapshot.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional

from ..common.messages import (
    ServeRequest,
    ServeResult,
    ServeStatsReport,
    ServeSummary,
)


class ServeQueueManager:
    """Thread-safe FIFO of serving requests with per-worker leases."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: collections.deque = collections.deque()  # request_ids
        self._requests: Dict[str, ServeRequest] = {}
        self._leased: Dict[str, int] = {}          # request_id -> node_id
        self._done: Dict[str, ServeResult] = {}
        self._submitted_total = 0
        self._requeued_total = 0
        self._done_total = 0
        # BUFFERED-verb telemetry under its OWN lock: stats ingestion
        # (hundreds of workers, latest-wins) must never contend with the
        # journaled submit/lease/result path on the queue lock
        self._stats_lock = threading.Lock()
        self._stats: Dict[int, ServeStatsReport] = {}

    # ------------------------------------------------------------ mutations

    def submit(self, requests: List[ServeRequest]) -> int:
        """Enqueue; duplicates (by request_id) are ignored. Returns the
        number newly accepted."""
        accepted = 0
        with self._lock:
            for req in requests:
                rid = req.request_id
                if not rid or rid in self._requests or rid in self._done:
                    continue
                self._requests[rid] = req
                self._pending.append(rid)
                self._submitted_total += 1
                accepted += 1
        return accepted

    def lease(self, node_id: int, max_requests: int) -> List[ServeRequest]:
        """Pop up to `max_requests` from the queue front for `node_id`."""
        out: List[ServeRequest] = []
        with self._lock:
            while self._pending and len(out) < max(0, max_requests):
                rid = self._pending.popleft()
                req = self._requests.get(rid)
                if req is None:
                    continue
                self._leased[rid] = node_id
                out.append(req)
        return out

    def lease_exact(self, node_id: int, request_ids: List[str]):
        """Journal replay: re-assign these exact requests to `node_id`
        (the original lease order was journaled; replay must not re-pop
        a different set)."""
        with self._lock:
            for rid in request_ids:
                if rid in self._requests and rid not in self._done:
                    try:
                        self._pending.remove(rid)
                    except ValueError:
                        pass
                    self._leased[rid] = node_id

    def complete(self, results: List[ServeResult]) -> int:
        """Record finished results; releases the lease. Idempotent per
        request_id (worker retries / journal replay)."""
        n = 0
        with self._lock:
            for res in results:
                rid = res.request_id
                if not rid or rid in self._done:
                    continue
                self._done[rid] = res
                self._leased.pop(rid, None)
                self._requests.pop(rid, None)
                self._done_total += 1
                n += 1
        return n

    def recover_node(self, node_id: int) -> int:
        """A worker died: move its leased requests back to the queue
        FRONT (they were admitted first; re-admit them first)."""
        with self._lock:
            lost = [rid for rid, nid in self._leased.items()
                    if nid == node_id]
            for rid in reversed(lost):
                del self._leased[rid]
                self._pending.appendleft(rid)
            self._requeued_total += len(lost)
        return len(lost)

    def take_results(self, request_ids: List[str]
                     ) -> (List[ServeResult], int):
        """Pop finished results for these ids; returns (results,
        still-pending count among the queried ids)."""
        out: List[ServeResult] = []
        pending = 0
        with self._lock:
            for rid in request_ids:
                res = self._done.pop(rid, None)
                if res is not None:
                    out.append(res)
                elif rid in self._requests:
                    pending += 1
        return out, pending

    def collect_stats(self, report: ServeStatsReport):
        """Latest-SENT-wins per worker (BUFFERED verb class drains stale
        snapshots after reconnect)."""
        with self._stats_lock:
            prev = self._stats.get(report.node_id)
            if prev is None or report.sent_at >= prev.sent_at:
                self._stats[report.node_id] = report

    # ------------------------------------------------------------ queries

    def summary(self) -> ServeSummary:
        with self._stats_lock:
            stats = list(self._stats.values())
        with self._lock:
            summ = ServeSummary(
                queue_depth=len(self._pending),
                leased=len(self._leased),
                done=len(self._done),
                submitted_total=self._submitted_total,
                requeued_total=self._requeued_total,
                done_total=self._done_total,
                workers=len(stats),
            )
        counters: Dict[str, int] = {}
        states: Dict[str, float] = {}
        wall = 0.0
        finished = 0
        for rep in stats:
            summ.active_slots += rep.active_slots
            wall = max(wall, rep.wall_s)
            for k, v in rep.counters.items():
                counters[k] = counters.get(k, 0) + v
            for k, v in rep.states.items():
                states[k] = states.get(k, 0.0) + v
            finished += rep.counters.get("finished", 0)
        # recovery is attributed by the MASTER (workers cannot see their
        # own death): requeues land under the pinned `requeued` counter
        counters["requeued"] = (counters.get("requeued", 0)
                                + summ.requeued_total)
        summ.counters = counters
        summ.states = states
        # job-level tails: worst worker (a conservative upper bound —
        # exact job tails would need raw samples on the wire)
        summ.p50_ms = max((r.p50_ms for r in stats), default=0.0)
        summ.p99_ms = max((r.p99_ms for r in stats), default=0.0)
        summ.ttft_p50_ms = max((r.ttft_p50_ms for r in stats), default=0.0)
        summ.ttft_p99_ms = max((r.ttft_p99_ms for r in stats), default=0.0)
        summ.rps = (finished / wall) if wall > 0 else 0.0
        return summ

    # ------------------------------------------------------------ snapshot

    def export_state(self) -> Dict:
        """Journal-snapshot payload (master._journal_state)."""
        with self._lock:
            return {
                "pending": list(self._pending),
                "requests": dict(self._requests),
                "leased": dict(self._leased),
                "done": dict(self._done),
                "submitted_total": self._submitted_total,
                "requeued_total": self._requeued_total,
                "done_total": self._done_total,
            }

    def restore_state(self, state: Optional[Dict]):
        if not state:
            return
        with self._lock:
            self._pending = collections.deque(state.get("pending", []))
            self._requests = dict(state.get("requests", {}))
            # JSON object keys are strings; node ids are ints
            self._leased = {rid: int(nid) for rid, nid
                            in state.get("leased", {}).items()}
            self._done = dict(state.get("done", {}))
            self._submitted_total = int(state.get("submitted_total", 0))
            self._requeued_total = int(state.get("requeued_total", 0))
            self._done_total = int(state.get("done_total", 0))

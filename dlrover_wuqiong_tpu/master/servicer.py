"""RPC dispatch: single get/report envelope over all master services.

Parity: reference `dlrover/python/master/servicer.py` (`MasterServicer.get` :98,
`.report` :296) — dispatch keyed on message type.

Master fault tolerance (master/journal.py): every state-mutating verb is
journaled here, after the managers applied it and before the response frame
leaves — an acked mutation is a durable one.  Verbs that arrive with an
idempotency key (``idem``) are answered from the journaled idem cache when
retried across a master restart, so report_task_result / kv_store_add /
join_rendezvous stay at-most-once even when the retry lands on a freshly
replayed master.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from ..common import messages as msg
from ..common.comm import RpcServer
from ..common.global_context import get_context
from ..common.log import get_logger
from ..common.node import Node, NodeEvent
from ..common.constants import NodeEventType, NodeStatus

logger = get_logger("servicer")


class NotLeaderError(RuntimeError):
    """Mutating verb hit a standby or fenced master (ISSUE 20).

    Surfaces client-side as an RpcError whose text carries this class
    name — MasterClient treats it as "advance to the next endpoint",
    the ONE sanctioned re-dial of an answered RPC (the verb was never
    applied here, so re-sending it to the real leader is safe, and the
    original idem key keeps it exactly-once)."""


#: verbs a non-leader still answers: pure reads with no queue/state
#: movement.  Everything else gets NotLeaderError BEFORE the idem cache
#: — a fenced corpse's replayed cache may be stale relative to the
#: promoted standby, so mutations must be answered by the leader only.
READ_ONLY_VERBS = (
    "CommWorldRequest", "WaitingNodeNumRequest", "NetworkReadyRequest",
    "StragglerExistRequest", "KVStoreGetRequest",
    "KVStoreMultiGetRequest", "ShardCheckpointRequest",
    "ParallelConfigRequest", "GoodputQuery", "PerfQuery",
    "JournalStatsQuery", "FetchJournalRequest", "ServeResultQuery",
    "ServeStatsQuery", "PolicyStateRequest", "PolicyHistoryRequest",
    "MeshTransitionQuery", "TimelineQuery",
)


class MasterServicer:
    def __init__(self, job_master):
        self.m = job_master

    # --------------------------------------------------------------- dispatch

    def handle(self, verb: str, node_id: int, node_type: str,
               payload: Any, idem: Optional[str] = None) -> Any:
        if not getattr(self.m, "is_leader", True) and \
                type(payload).__name__ not in READ_ONLY_VERBS:
            raise NotLeaderError(
                f"not the leader (epoch {getattr(self.m, 'epoch', 0)}) — "
                f"{type(payload).__name__} must go to the active primary")
        cache = getattr(self.m, "idem_cache", None)
        if idem and cache is not None:
            hit = cache.get(idem)
            if hit is not cache.MISS:
                logger.info("idem replay for %s (%s) — returning the "
                            "recorded response", idem,
                            type(payload).__name__)
                return hit
        if verb == "get":
            resp = self._get(node_id, node_type, payload, idem=idem)
        else:
            resp = self._report(node_id, node_type, payload, idem=idem)
        if idem and cache is not None:
            cache.put(idem, resp)
        return resp

    def _journal(self, kind: str, data: dict, idem: Optional[str] = None,
                 resp: Any = None):
        """Append one event frame; idem-keyed events carry their response
        so replay rebuilds the at-most-once cache atomically with the
        mutation (a separate idem frame could be lost between appends).

        Group commit: the frame is enqueued and the ack gates on the
        journal's DURABLE WATERMARK covering its seq — concurrent verbs
        share one fsync, journal-before-ack holds per frame."""
        journal = getattr(self.m, "journal", None)
        if journal is None:
            return
        if idem:
            data = {**data, "idem": idem, "resp": resp}
        seq = journal.append_nowait(kind, data)
        journal.wait_durable(seq)

    def _get(self, node_id: int, node_type: str, payload: Any,
             idem: Optional[str] = None) -> Any:
        m = self.m
        if isinstance(payload, msg.TaskRequest):
            task = m.task_manager.get_dataset_task(node_id,
                                                   payload.dataset_name)
            if task is None:
                finished = m.task_manager.finished(payload.dataset_name)
                return msg.Task(
                    task_id=-1,
                    task_type="none" if finished else "wait",
                    dataset_name=payload.dataset_name)
            resp = msg.Task(
                task_id=task.task_id, task_type=task.task_type,
                shard=msg.ShardConfig(start=task.shard.start,
                                      end=task.shard.end,
                                      indices=task.shard.record_indices),
                dataset_name=payload.dataset_name)
            # idem matters here: a retried TaskRequest crossing a master
            # restart must get the SAME task back — a fresh dispatch would
            # strand the journaled one in `doing` forever
            self._journal("dispatch", {
                "dataset_name": payload.dataset_name,
                "task_id": task.task_id, "node_id": node_id,
                "start": task.shard.start, "end": task.shard.end,
                "indices": task.shard.record_indices},
                idem=idem, resp=resp)
            return resp

        if isinstance(payload, msg.CommWorldRequest):
            rdzv = m.rdzv_managers.get(payload.rdzv_name)
            rdzv_round, group, world = rdzv.get_comm_world(payload.node_id)
            state = msg.RendezvousState(rdzv_round=rdzv_round, group=group)
            if world:
                state.world = {
                    str(rank): [s.node_id, s.local_world_size, s.node_ip,
                                s.free_port]
                    for rank, s in world.items()
                }
                state.coordinator_addr = rdzv.coordinator_addr()
                state.complete = True
            return state

        if isinstance(payload, msg.WaitingNodeNumRequest):
            rdzv = m.rdzv_managers.get(payload.rdzv_name)
            return msg.WaitingNodeNumResponse(
                waiting_num=rdzv.num_nodes_waiting())

        if isinstance(payload, msg.NetworkReadyRequest):
            rdzv = m.rdzv_managers.get("network-check")
            success, reason = rdzv.network_check_success()
            return msg.OkResponse(success=success, reason=reason)

        if isinstance(payload, msg.StragglerExistRequest):
            rdzv = m.rdzv_managers.get("network-check")
            stragglers, reason = rdzv.get_straggler()
            return msg.NetworkStatusResponse(nodes=stragglers, reason=reason)

        if isinstance(payload, msg.KVStoreGetRequest):
            value = m.kv_store.get(payload.key)
            return msg.KVStoreResponse(found=value is not None,
                                       value=value or b"")

        if isinstance(payload, msg.KVStoreMultiGetRequest):
            values = m.kv_store.multi_get(payload.keys)
            if any(v is None for v in values):
                return msg.KVStoreResponse(found=False)
            return msg.KVStoreResponse(found=True, values=values)

        if isinstance(payload, msg.KVStoreAddRequest):
            num = m.kv_store.add(payload.key, payload.amount)
            resp = msg.KVStoreResponse(found=True, num=num)
            # counter adds are NOT naturally idempotent — the idem key and
            # response ride in the same frame so a cross-restart retry
            # replays the answer instead of drifting the counter; the
            # ABSOLUTE result is journaled (replay = set, last-writer-wins)
            # so a frame that races a concurrent snapshot converges instead
            # of double-adding
            self._journal("kv_add", {"key": payload.key,
                                     "amount": payload.amount,
                                     "result": num},
                          idem=idem, resp=resp)
            return resp

        if isinstance(payload, msg.ShardCheckpointRequest):
            content = m.task_manager.get_dataset_checkpoint(
                payload.dataset_name)
            return msg.ShardCheckpoint(content=content)

        if isinstance(payload, msg.ParallelConfigRequest):
            return m.get_paral_config(payload.node_id)

        if isinstance(payload, msg.GoodputQuery):
            return m.goodput_summary()

        if isinstance(payload, msg.PerfQuery):
            return m.perf_summary()

        if isinstance(payload, msg.JournalStatsQuery):
            # read-only gauge poll (never journaled): group-commit batch
            # sizes + durable watermark for the fleet bench and perf_probe
            return m.journal_stats()

        if isinstance(payload, msg.FetchJournalRequest):
            # standby pull (POLLING class, read-only, NEVER journaled —
            # journaling a fetch would make shipping feed itself):
            # durable frames after from_seq verbatim, snapshot handoff
            # when compaction already truncated the range
            return m.fetch_journal(payload.from_seq, payload.max_frames)

        if isinstance(payload, msg.ServeLeaseRequest):
            leased = m.serve_queue.lease(payload.node_id,
                                         payload.max_requests)
            resp = msg.ServeLease(requests=leased)
            if not leased:
                return resp
            # a lease moves queue state: like TaskRequest dispatch, a
            # retried lease crossing a master restart must get the SAME
            # requests back or the originals strand in `leased` forever
            self._journal("serve_lease", {
                "node_id": payload.node_id,
                "request_ids": [r.request_id for r in leased]},
                idem=idem, resp=resp)
            return resp

        if isinstance(payload, msg.ServeResultQuery):
            results, pending = m.serve_queue.take_results(
                payload.request_ids)
            return msg.ServeResultResponse(results=results,
                                           pending=pending)

        if isinstance(payload, msg.ServeStatsQuery):
            return m.serve_summary()

        if isinstance(payload, msg.PolicyStateRequest):
            return m.policy_current()

        if isinstance(payload, msg.PolicyHistoryRequest):
            return msg.PolicyHistory(content=m.policy_history_json())

        if isinstance(payload, msg.MeshTransitionQuery):
            # read-only poll (POLLING class, never journaled): survivors
            # learn the current hot-swap phase at fusion boundaries
            return m.mesh.state_message()

        if isinstance(payload, msg.TimelineQuery):
            # read-only incident assembly from disk artifacts (never
            # journaled): the answer must stay byte-equal to the offline
            # reconstruction, so no in-memory state contributes
            return m.timeline_report(
                payload.ckpt_dir,
                journal_dirs=list(payload.journal_dirs))

        raise ValueError(f"unknown get message: {type(payload).__name__}")

    def _report(self, node_id: int, node_type: str, payload: Any,
                idem: Optional[str] = None) -> Any:
        m = self.m
        if isinstance(payload, msg.JoinRendezvousRequest):
            rdzv = m.rdzv_managers.get(payload.rdzv_name)
            rdzv_round = rdzv.join_rendezvous(
                payload.node_id, payload.node_rank, payload.local_world_size,
                payload.node_ip, payload.free_port, payload.slice_id)
            m.job_manager.register_node("worker", payload.node_id,
                                        rank_index=payload.node_rank)
            m.job_manager.collect_heartbeat(payload.node_id)
            resp = msg.RendezvousState(rdzv_round=rdzv_round)
            self._journal("rdzv_join", {
                "rdzv_name": payload.rdzv_name, "node_id": payload.node_id,
                "node_rank": payload.node_rank,
                "local_world_size": payload.local_world_size,
                "node_ip": payload.node_ip, "free_port": payload.free_port,
                "slice_id": payload.slice_id}, idem=idem, resp=resp)
            return resp

        if isinstance(payload, msg.TaskResult):
            success = not payload.err_message
            m.task_manager.report_dataset_task(
                node_id, payload.dataset_name, payload.task_id, success)
            resp = msg.OkResponse()
            self._journal("task_result", {
                "dataset_name": payload.dataset_name,
                "task_id": payload.task_id, "node_id": node_id,
                "success": success}, idem=idem, resp=resp)
            return resp

        if isinstance(payload, msg.DatasetShardParams):
            created = m.task_manager.new_dataset(
                batch_size=payload.batch_size,
                dataset_size=payload.dataset_size,
                dataset_name=payload.dataset_name,
                num_epochs=payload.num_epochs,
                shuffle=payload.shuffle,
                num_minibatches_per_shard=payload.num_minibatches_per_shard,
                storage_type=payload.storage_type,
                task_type=payload.task_type)
            if created:
                self._journal("dataset", {
                    "batch_size": payload.batch_size,
                    "dataset_size": payload.dataset_size,
                    "dataset_name": payload.dataset_name,
                    "num_epochs": payload.num_epochs,
                    "shuffle": payload.shuffle,
                    "num_minibatches_per_shard":
                        payload.num_minibatches_per_shard,
                    "storage_type": payload.storage_type,
                    "task_type": payload.task_type})
            return msg.OkResponse()

        if isinstance(payload, msg.HeartBeat):
            action, rb = m.job_manager.collect_heartbeat_full(
                payload.node_id, payload.timestamp)
            if payload.global_step:
                m.speed_monitor.collect_global_step(payload.global_step,
                                                    payload.timestamp)
            return msg.HeartbeatResponse(action=action,
                                         rollback_before_step=rb)

        if isinstance(payload, msg.NodeMeta):
            node = m.job_manager.register_node(
                payload.node_type, payload.node_id,
                rank_index=payload.node_rank, addr=payload.addr)
            node.config_resource.cpu = payload.cpu
            node.config_resource.memory_mb = payload.memory_mb
            node.config_resource.accelerator_type = payload.accelerator_type
            node.config_resource.accelerator_num = payload.accelerator_num
            self._journal("node", {
                "node_type": payload.node_type, "node_id": payload.node_id,
                "node_rank": payload.node_rank, "addr": payload.addr,
                "accelerator_type": payload.accelerator_type,
                "accelerator_num": payload.accelerator_num})
            return msg.OkResponse()

        if isinstance(payload, msg.NetworkCheckResult):
            rdzv = m.rdzv_managers.get("network-check")
            rdzv.report_network_check_result(
                payload.node_id, payload.normal, payload.elapsed_time)
            return msg.OkResponse()

        if isinstance(payload, msg.GlobalStep):
            m.speed_monitor.collect_global_step(payload.step,
                                                payload.timestamp)
            return msg.OkResponse()

        if isinstance(payload, msg.NodeFailure):
            live = m.job_manager.get_node(payload.node_id)
            rank = live.rank_index if live is not None else payload.node_id
            # error-class catalogue: raw error text → NodeExitReason that
            # the relaunch decision table understands
            reason, relaunchable = m.job_manager.error_monitor.process_error(
                rank, payload.restart_count, payload.error_data,
                payload.level, node_id=payload.node_id)
            node = Node("worker", payload.node_id)
            node.status = NodeStatus.FAILED
            node.exit_reason = reason
            m.job_manager.process_event(NodeEvent(NodeEventType.MODIFIED,
                                                  node))
            m.task_manager.recover_tasks(payload.node_id)
            m.serve_queue.recover_node(payload.node_id)
            for rdzv in m.rdzv_managers.values():
                rdzv.remove_alive_node(payload.node_id)
            m.note_policy_failure(payload.node_id)
            # journal the shard recovery (not the classification — error
            # history is advisory): a replayed master must not keep the
            # dead node's shards parked in `doing` forever
            self._journal("recover", {"node_id": payload.node_id})
            # hot-swap route: when the policy says survivors should
            # absorb the dead rank in place, propose the fenced mesh
            # transition (its propose frame is journaled by the master)
            try:
                m.maybe_start_hotswap(payload.node_id, reason=reason)
            except Exception:  # noqa: BLE001 — restart-the-world is the
                # fallback; a failed propose must not fail the verb
                logger.exception("hot-swap propose failed")
            # tell the agent whether process restarts can fix this class —
            # a user-code error restarts into the same crash every time,
            # and a class repeating across restarts is equally unfixable.
            # relaunch_always overrides, same as _should_relaunch: on
            # preemption-heavy pools a SIGKILL storm classifies as
            # host_oom (exit_code=137 is ambiguous) and would otherwise
            # strand the job after 3 kills
            repeated = m.job_manager.error_monitor.repeated_class(rank)
            if repeated is not None and not get_context().relaunch_always:
                relaunchable = False
                why = f"error class {repeated!r} repeats across restarts"
            else:
                why = f"error class not restartable ({reason})"
            return msg.OkResponse(success=relaunchable,
                                  reason="" if relaunchable else why)

        if isinstance(payload, msg.NodeEventReport):
            logger.info("node event from %s: %s %s", payload.node_id,
                        payload.event_type, payload.message)
            m.record_node_event(payload)
            return msg.OkResponse()

        if isinstance(payload, msg.KVStoreSetRequest):
            m.kv_store.set(payload.key, payload.value)
            self._journal("kv_set", {"key": payload.key,
                                     "value": payload.value})
            return msg.OkResponse()

        if isinstance(payload, msg.ShardCheckpoint):
            ok = m.task_manager.restore_dataset_from_checkpoint(
                payload.content)
            if ok:
                self._journal("shard_ckpt", {"content": payload.content})
            return msg.OkResponse(success=ok)

        if isinstance(payload, msg.ResourceStats):
            node = m.job_manager.get_node(payload.node_id)
            if node is not None:
                node.update_resource_usage(payload.cpu_percent,
                                           payload.memory_mb,
                                           payload.accelerator_stats)
            return msg.OkResponse()

        if isinstance(payload, (msg.ModelInfo, msg.CustomMetric)):
            m.collect_custom_data(payload)
            return msg.OkResponse()

        if isinstance(payload, msg.GoodputLedgerReport):
            # pure telemetry (cumulative snapshot, latest-wins) — no
            # journal frame; a master restart just waits for the next one
            m.collect_goodput(payload)
            return msg.OkResponse()

        if isinstance(payload, msg.PerfSnapshotReport):
            # pure telemetry (cumulative counters, latest-SENT-wins) —
            # no journal frame, same contract as GoodputLedgerReport
            m.collect_perf(payload)
            return msg.OkResponse()

        if isinstance(payload, msg.PolicyDecisionReport):
            decision = m.admit_policy_decision(payload.decision)
            resp = msg.PolicyDecisionAck(decision_id=decision.decision_id,
                                         applied=True)
            # decisions change durable protection knobs: the frame must
            # outlive this master before the ack leaves, and a retry
            # crossing a restart must replay the ack, not re-admit a
            # duplicate decision_id
            self._journal("policy", {"decision": decision},
                          idem=idem, resp=resp)
            return resp

        if isinstance(payload, msg.ServeSubmitRequest):
            accepted = m.serve_queue.submit(payload.requests)
            resp = msg.ServeSubmitAck(
                accepted=accepted,
                queue_depth=m.serve_queue.summary().queue_depth)
            # a submitted request must survive this master: the ack is
            # the client's permission to stop retrying, so the frame is
            # durable first, and a retry crossing a restart replays the
            # ack instead of double-enqueueing
            self._journal("serve_submit",
                          {"requests": list(payload.requests)},
                          idem=idem, resp=resp)
            return resp

        if isinstance(payload, msg.ServeResultReport):
            m.serve_queue.complete(payload.results)
            resp = msg.OkResponse()
            # results release leases and are what drain waits on — the
            # same durability bar as task_result
            self._journal("serve_result",
                          {"results": list(payload.results),
                           "node_id": node_id},
                          idem=idem, resp=resp)
            return resp

        if isinstance(payload, msg.ServeStatsReport):
            # pure telemetry (cumulative snapshot, latest-wins) — no
            # journal frame; a master restart just waits for the next one
            m.collect_serve_stats(payload)
            return msg.OkResponse()

        if isinstance(payload, msg.MeshTransitionPhaseReport):
            # survivor phase ack: journaled + idem (a retry crossing a
            # master restart must replay the recorded accept/reject, not
            # double-ack), journal-BEFORE-apply so the ack is durable
            # before it can advance the ladder
            event = m.mesh.ack_event(payload.node_id,
                                     payload.transition_id, payload.phase,
                                     payload.ok, payload.detail)
            if event is None:
                # stale tid / wrong phase / not a survivor — tell the
                # worker to re-poll, nothing to journal
                resp = msg.OkResponse(success=False,
                                      reason="stale transition or phase")
                return resp
            resp = msg.OkResponse()
            self._journal("mesh_transition", event, idem=idem, resp=resp)
            m.mesh.apply(event)
            m.mesh_maybe_advance()
            return resp

        if isinstance(payload, msg.DiagnosisReport):
            return m.diagnosis_manager.collect_report(payload)

        raise ValueError(f"unknown report message: {type(payload).__name__}")


def create_master_service(job_master, host: str = "0.0.0.0",
                          port: int = 0) -> RpcServer:
    """Parity: reference servicer.py:630 create_master_service."""
    servicer = MasterServicer(job_master)
    return RpcServer(servicer.handle, host=host, port=port,
                     epoch_provider=lambda: getattr(job_master, "epoch", 1))

"""ScalePlan + PodScaler: apply scale decisions to the platform.

Parity: reference `master/scaler/base_scaler.py` (ScalePlan),
`master/scaler/pod_scaler.py:77` (`PodScaler`, `_periodic_create_pod` :372,
`_create_pod` :399 — a retry queue so transient platform errors don't drop
nodes), and `scaler/elasticjob_scaler.py` (CRD-patching variant is the k8s
backend's concern here).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Dict, List, Optional

from ..common.log import get_logger
from ..common.node import Node
from ..scheduler.base import NodeSpec, SchedulerClient
from .job_manager import Scaler

logger = get_logger("scaler")


@dataclasses.dataclass
class ScalePlan:
    """A batch scale decision (parity base_scaler.py ScalePlan)."""

    launch_nodes: List[NodeSpec] = dataclasses.field(default_factory=list)
    remove_nodes: List[Node] = dataclasses.field(default_factory=list)
    # desired replica count per node type ("" = unchanged)
    node_group_replicas: Dict[str, int] = dataclasses.field(
        default_factory=dict)

    def empty(self) -> bool:
        return not (self.launch_nodes or self.remove_nodes
                    or self.node_group_replicas)


class PodScaler(Scaler):
    """Drives a SchedulerClient; failed creates go to a retry queue.

    Works identically over the fake, subprocess, and k8s backends — the
    platform difference lives entirely in the client.
    """

    def __init__(self, client: SchedulerClient,
                 spec_factory=None, retry_interval: float = 3.0,
                 max_create_retries: int = 5):
        self._client = client
        # node -> NodeSpec (command/env/image); default carries resources only
        self._spec_factory = spec_factory or self._default_spec
        self._retry_q: "queue.Queue[tuple]" = queue.Queue()
        self._retry_interval = retry_interval
        self._max_retries = max_create_retries
        self._stopped = threading.Event()
        self._retry_thread: Optional[threading.Thread] = None

    @staticmethod
    def _default_spec(node: Node) -> NodeSpec:
        return NodeSpec(node_type=node.type, node_id=node.id,
                        rank_index=node.rank_index or 0,
                        resource=node.config_resource,
                        relaunch_count=node.relaunch_count)

    def spec_for(self, node: Node) -> NodeSpec:
        return self._spec_factory(node)

    # ----------------------------------------------------------------- plan

    def scale(self, plan: ScalePlan):
        """Parity: PodScaler.scale (pod_scaler.py:163)."""
        for node in plan.remove_nodes:
            self._delete(node)
        for spec in plan.launch_nodes:
            self._create(spec, attempt=0)

    def scale_up(self, node: Node):
        self._create(self._spec_factory(node), attempt=0)

    def scale_down(self, node: Node):
        self._delete(node)

    # ------------------------------------------------------------- internals

    def _create(self, spec: NodeSpec, attempt: int):
        ok = False
        try:
            ok = self._client.create_node(spec)
        except Exception:  # noqa: BLE001
            logger.exception("create_node raised for %s-%d",
                             spec.node_type, spec.node_id)
        if not ok:
            if attempt + 1 >= self._max_retries:
                logger.error("giving up creating %s-%d after %d attempts",
                             spec.node_type, spec.node_id, attempt + 1)
                return
            self._ensure_retry_thread()
            self._retry_q.put((time.monotonic() + self._retry_interval, spec,
                               attempt + 1))

    def _delete(self, node: Node):
        try:
            self._client.delete_node(node.type, node.id)
        except Exception:  # noqa: BLE001
            logger.exception("delete_node raised for %s", node)

    def _ensure_retry_thread(self):
        if self._retry_thread is None or not self._retry_thread.is_alive():
            self._retry_thread = threading.Thread(
                target=self._retry_loop, daemon=True,
                name="dwt-pod-scaler-retry")
            self._retry_thread.start()

    def _retry_loop(self):
        """Parity: `_periodic_create_pod` pod_scaler.py:372."""
        while not self._stopped.is_set():
            try:
                due, spec, attempt = self._retry_q.get(timeout=1.0)
            except queue.Empty:
                continue
            delay = due - time.monotonic()
            if delay > 0:
                if self._stopped.wait(delay):
                    return
            self._create(spec, attempt)

    def stop(self):
        self._stopped.set()

"""Metrics collection + Prometheus-style export.

Parity: reference `master/stats/job_collector.py` (JobMetricCollector),
`master/stats/reporter.py` (StatsReporter local/Brain) and the xpu_timer
Prometheus endpoint intent (`atorch/dev/xpu_timer/common/manager.cc` — bvar/
brpc exporter of kernel/collective timings).

One process-wide `MetricRegistry` (gauges + counters + bounded histograms)
that any subsystem writes into (SpeedMonitor throughput, agent resource
reports, checkpoint timings, relaunch counts); a `PrometheusExporter`
serves it as text/plain exposition format over HTTP so standard scrapers
work against the master.
"""

from __future__ import annotations

import http.server
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common.log import get_logger

logger = get_logger("metrics")

_LabelKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Optional[Dict[str, str]]) -> _LabelKey:
    return tuple(sorted((labels or {}).items()))


def _escape_label_value(v: str) -> str:
    """Exposition-format label escaping: backslash, quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(key: _LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
    return "{" + inner + "}"


#: default histogram bucket upper bounds (seconds-oriented, exponential);
#: rendered cumulatively with a trailing +Inf per the exposition format
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   30.0, 60.0, 300.0, 600.0)


class MetricRegistry:
    """Thread-safe gauges/counters/histograms with labels."""

    def __init__(self):
        self._lock = threading.Lock()
        self._gauges: Dict[str, Dict[_LabelKey, float]] = {}
        self._counters: Dict[str, Dict[_LabelKey, float]] = {}
        self._hists: Dict[str, Dict[_LabelKey, List[float]]] = {}
        self._buckets: Dict[str, Tuple[float, ...]] = {}
        self._help: Dict[str, str] = {}

    def gauge(self, name: str, value: float,
              labels: Optional[Dict[str, str]] = None, help: str = ""):
        with self._lock:
            self._gauges.setdefault(name, {})[_labels_key(labels)] = value
            if help:
                self._help[name] = help

    def inc(self, name: str, value: float = 1.0,
            labels: Optional[Dict[str, str]] = None, help: str = ""):
        with self._lock:
            d = self._counters.setdefault(name, {})
            k = _labels_key(labels)
            d[k] = d.get(k, 0.0) + value
            if help:
                self._help[name] = help

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, str]] = None, help: str = "",
                max_samples: int = 1000,
                buckets: Optional[Tuple[float, ...]] = None):
        with self._lock:
            d = self._hists.setdefault(name, {})
            k = _labels_key(labels)
            samples = d.setdefault(k, [])
            samples.append(value)
            if len(samples) > max_samples:
                del samples[:len(samples) - max_samples]
            if buckets is not None:
                self._buckets[name] = tuple(sorted(buckets))
            if help:
                self._help[name] = help

    def get_gauge(self, name: str,
                  labels: Optional[Dict[str, str]] = None
                  ) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name, {}).get(_labels_key(labels))

    def drop_gauge(self, name: str):
        """Remove every series of a gauge family — for windowed metrics
        whose label sets change between publishes (stale series would
        otherwise export forever and grow cardinality unboundedly)."""
        with self._lock:
            self._gauges.pop(name, None)

    def get_counter(self, name: str,
                    labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return self._counters.get(name, {}).get(_labels_key(labels), 0.0)

    def render(self) -> str:
        """Prometheus text exposition format."""
        out = []
        with self._lock:
            for name, series in sorted(self._gauges.items()):
                if name in self._help:
                    out.append(f"# HELP {name} {self._help[name]}")
                out.append(f"# TYPE {name} gauge")
                for k, v in series.items():
                    out.append(f"{name}{_fmt_labels(k)} {v}")
            for name, series in sorted(self._counters.items()):
                if name in self._help:
                    out.append(f"# HELP {name} {self._help[name]}")
                out.append(f"# TYPE {name} counter")
                for k, v in series.items():
                    out.append(f"{name}_total{_fmt_labels(k)} {v}")
            for name, series in sorted(self._hists.items()):
                if name in self._help:
                    out.append(f"# HELP {name} {self._help[name]}")
                out.append(f"# TYPE {name} histogram")
                bounds = self._buckets.get(name, DEFAULT_BUCKETS)
                for k, samples in series.items():
                    if not samples:
                        continue
                    s = sorted(samples)
                    # cumulative bucket counts, non-decreasing by
                    # construction, closed by the mandatory +Inf bucket
                    cum = 0
                    i = 0
                    for le in bounds:
                        while i < len(s) and s[i] <= le:
                            i += 1
                        cum = i
                        bk = k + (("le", repr(float(le))),)
                        out.append(
                            f"{name}_bucket"
                            f"{_fmt_labels(tuple(sorted(bk)))} {cum}")
                    bk = k + (("le", "+Inf"),)
                    out.append(f"{name}_bucket"
                               f"{_fmt_labels(tuple(sorted(bk)))} {len(s)}")
                    out.append(f"{name}_count{_fmt_labels(k)} {len(s)}")
                    out.append(f"{name}_sum{_fmt_labels(k)} {sum(s)}")
        return "\n".join(out) + "\n"


_REGISTRY: Optional[MetricRegistry] = None
_REG_LOCK = threading.Lock()


def get_registry() -> MetricRegistry:
    global _REGISTRY
    with _REG_LOCK:
        if _REGISTRY is None:
            _REGISTRY = MetricRegistry()
        return _REGISTRY


class JobMetricCollector:
    """Master-side collector wiring job state into the registry.

    Parity: reference JobMetricCollector (stats/job_collector.py:185) —
    collects step/speed/node-resource/ckpt metrics for reporting.
    """

    def __init__(self, job_name: str = "dwt",
                 registry: Optional[MetricRegistry] = None):
        self.job = job_name
        self.reg = registry or get_registry()

    def collect_global_step(self, step: int, timestamp: float = 0.0):
        self.reg.gauge("dwt_job_global_step", step, {"job": self.job},
                       help="latest reported global step")

    def collect_speed(self, steps_per_sec: float, tokens_per_sec: float = 0):
        self.reg.gauge("dwt_job_steps_per_second", steps_per_sec,
                       {"job": self.job}, help="training throughput")
        if tokens_per_sec:
            self.reg.gauge("dwt_job_tokens_per_second", tokens_per_sec,
                           {"job": self.job})

    def collect_node_resource(self, node_id: int, cpu: float,
                              memory_mb: float):
        labels = {"job": self.job, "node": str(node_id)}
        self.reg.gauge("dwt_node_cpu_cores", cpu, labels)
        self.reg.gauge("dwt_node_memory_mb", memory_mb, labels)

    def collect_ckpt_timing(self, kind: str, seconds: float):
        """kind: 'blocking' | 'persist' | 'restore'."""
        self.reg.observe("dwt_ckpt_seconds", seconds,
                         {"job": self.job, "kind": kind},
                         help="checkpoint stage timings")

    def collect_node_event(self, event: str):
        """event: 'relaunch' | 'failure' | 'scale_up' | 'scale_down'."""
        self.reg.inc("dwt_node_events", 1.0,
                     {"job": self.job, "event": event},
                     help="node lifecycle events")


class PrometheusExporter:
    """Minimal /metrics HTTP endpoint (no deps)."""

    def __init__(self, port: int = 0,
                 registry: Optional[MetricRegistry] = None):
        self.registry = registry or get_registry()
        reg = self.registry

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = reg.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request logging
                pass

        self._server = http.server.ThreadingHTTPServer(("0.0.0.0", port),
                                                       Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="dwt-prometheus")
        self._thread.start()
        logger.info("prometheus exporter on :%d/metrics", self.port)

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

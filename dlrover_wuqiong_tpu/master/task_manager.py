"""Dynamic data sharding: shard queue, dispatch, recovery of failed-worker shards.

Parity: reference `dlrover/python/master/shard/task_manager.py` (TaskManager :37,
new_dataset :59, doing/done queues) + `{batch,streaming}_dataset_manager.py`.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..common.constants import TaskType
from ..common.log import get_logger
from .dataset_splitter import DatasetSplitter, Shard, new_dataset_splitter

logger = get_logger("task_manager")


@dataclass
class DatasetTask:
    task_id: int
    task_type: str
    shard: Shard


@dataclass
class DoingTask:
    task: DatasetTask
    node_id: int
    start_time: float


class DatasetManager:
    """Todo/doing/done bookkeeping for one named dataset."""

    def __init__(self, task_type: str, batch_size: int,
                 splitter: DatasetSplitter):
        self.task_type = task_type
        self.batch_size = batch_size
        self.splitter = splitter
        self.todo: List[DatasetTask] = []
        self.doing: Dict[int, DoingTask] = {}
        self._task_id = 0
        self._completed_step = 0

    def create_tasks(self):
        self.splitter.create_shards()
        for shard in self.splitter.get_shards():
            self.todo.append(DatasetTask(self._task_id, self.task_type, shard))
            self._task_id += 1

    def get_task(self, node_id: int) -> Optional[DatasetTask]:
        if not self.todo:
            if not self.splitter.epoch_finished():
                self.create_tasks()
        if not self.todo:
            return None
        task = self.todo.pop(0)
        self.doing[task.task_id] = DoingTask(task, node_id, time.time())
        return task

    def report_task_done(self, task_id: int, success: bool) -> bool:
        doing = self.doing.pop(task_id, None)
        if doing is None:
            return False
        if not success:
            self.todo.insert(0, doing.task)
            return False
        return True

    def recover_node_tasks(self, node_id: int) -> int:
        """Re-queue shards a dead worker was processing (shard-level recovery)."""
        recovered = [tid for tid, d in self.doing.items()
                     if d.node_id == node_id]
        for tid in recovered:
            doing = self.doing.pop(tid)
            self.todo.insert(0, doing.task)
        if recovered:
            logger.info("recovered %d in-flight shards from node %s",
                        len(recovered), node_id)
        return len(recovered)

    def completed(self) -> bool:
        return (not self.todo and not self.doing
                and self.splitter.epoch_finished())

    def to_checkpoint(self) -> Dict:
        return {
            "splitter": self.splitter.to_checkpoint(),
            "task_type": self.task_type,
            "batch_size": self.batch_size,
            "todo": [[t.shard.start, t.shard.end, t.shard.record_indices]
                     for t in self.todo]
                    + [[d.task.shard.start, d.task.shard.end,
                        d.task.shard.record_indices]
                       for d in self.doing.values()],
        }

    @classmethod
    def from_checkpoint(cls, data: Dict) -> "DatasetManager":
        splitter = DatasetSplitter.from_checkpoint(data["splitter"])
        mgr = cls(data["task_type"], data["batch_size"], splitter)
        for start, end, indices in data.get("todo", []):
            mgr.todo.append(
                DatasetTask(mgr._task_id, mgr.task_type,
                            Shard(splitter.dataset_name, start, end,
                                  indices or [])))
            mgr._task_id += 1
        return mgr

    # ------------------------------------------------------- journal replay

    def export_state(self) -> Dict:
        """Exact snapshot for the master journal: unlike `to_checkpoint`
        (worker-facing, merges doing into todo and renumbers), this keeps
        task IDS and the doing map so a restarted master can still match a
        worker's in-flight `report_task_result` — the no-double-train
        invariant (master/journal.py)."""
        return {
            "splitter": self.splitter.to_checkpoint(),
            "task_type": self.task_type,
            "batch_size": self.batch_size,
            "next_task_id": self._task_id,
            "todo": [[t.task_id, t.shard.start, t.shard.end,
                      t.shard.record_indices] for t in self.todo],
            "doing": [[d.task.task_id, d.node_id, d.task.shard.start,
                       d.task.shard.end, d.task.shard.record_indices]
                      for d in self.doing.values()],
        }

    @classmethod
    def from_state(cls, data: Dict) -> "DatasetManager":
        splitter = DatasetSplitter.from_checkpoint(data["splitter"])
        mgr = cls(data["task_type"], data["batch_size"], splitter)
        mgr._task_id = int(data.get("next_task_id", 0))
        name = splitter.dataset_name
        for tid, start, end, indices in data.get("todo", []):
            mgr.todo.append(DatasetTask(
                tid, mgr.task_type, Shard(name, start, end, indices or [])))
        for tid, node_id, start, end, indices in data.get("doing", []):
            mgr.doing[tid] = DoingTask(
                DatasetTask(tid, mgr.task_type,
                            Shard(name, start, end, indices or [])),
                node_id, time.time())
        return mgr

    def replay_dispatch(self, task_id: int, node_id: int, start: int,
                        end: int, indices: Optional[List[int]] = None):
        """Re-apply a journaled `get_task` dispatch: move the task from
        todo to doing(node).  Shard creation on epoch rollover is
        reproduced (splitter shuffles are seeded, dataset_splitter.py),
        and a task the replayed todo does not hold is synthesized from the
        journal's own shard payload — the journal is authoritative."""
        if task_id in self.doing:
            return
        task = self._pop_todo(task_id)
        if task is None and not self.todo \
                and not self.splitter.epoch_finished():
            self.create_tasks()  # the rollover get_task() triggered live
            task = self._pop_todo(task_id)
        if task is None:
            task = DatasetTask(
                task_id, self.task_type,
                Shard(self.splitter.dataset_name, start, end, indices or []))
            # drop any todo entry covering the same range — it IS this task
            self.todo = [t for t in self.todo
                         if not (t.shard.start == start
                                 and t.shard.end == end)]
        self._task_id = max(self._task_id, task_id + 1)
        self.doing[task_id] = DoingTask(task, node_id, time.time())

    def _pop_todo(self, task_id: int) -> Optional[DatasetTask]:
        for i, t in enumerate(self.todo):
            if t.task_id == task_id:
                return self.todo.pop(i)
        return None


class TaskManager:
    """Dispatches dataset shards to workers; detects task hang.

    Parity: reference task_manager.py:37 (+ `reset_worker_start_task_time`
    hang signal used by the diagnosis subsystem).
    """

    def __init__(self, worker_restart_timeout: float = 0.0):
        self._lock = threading.Lock()
        self._datasets: Dict[str, DatasetManager] = {}
        self._worker_start_task_time: Dict[int, float] = {}
        self._task_timeout_callbacks: List[Callable] = []
        self._worker_restart_timeout = worker_restart_timeout
        self.speed_monitor = None  # wired by the master

    def new_dataset(self, batch_size: int, dataset_size: int,
                    dataset_name: str, num_epochs: int = 1,
                    shuffle: bool = False,
                    num_minibatches_per_shard: int = 2,
                    storage_type: str = "",
                    task_type: str = TaskType.TRAINING) -> bool:
        """Create the dataset; returns False when it already exists (the
        journal records only the first creation)."""
        with self._lock:
            if dataset_name in self._datasets:
                return False
            splitter = new_dataset_splitter(
                storage_type, shuffle, dataset_size, batch_size, num_epochs,
                num_minibatches_per_shard, dataset_name)
            mgr = DatasetManager(task_type, batch_size, splitter)
            mgr.create_tasks()
            self._datasets[dataset_name] = mgr
            logger.info("new dataset %s: size=%d shards=%d", dataset_name,
                        dataset_size, len(mgr.todo))
            return True

    def get_dataset_task(self, node_id: int,
                         dataset_name: str) -> Optional[DatasetTask]:
        with self._lock:
            mgr = self._datasets.get(dataset_name)
            if mgr is None:
                return None
            task = mgr.get_task(node_id)
            if task is not None:
                self._worker_start_task_time[node_id] = time.time()
            return task

    def report_dataset_task(self, node_id: int, dataset_name: str,
                            task_id: int, success: bool) -> bool:
        with self._lock:
            mgr = self._datasets.get(dataset_name)
            if mgr is None:
                return False
            self._worker_start_task_time[node_id] = time.time()
            return mgr.report_task_done(task_id, success)

    def recover_tasks(self, node_id: int):
        with self._lock:
            for mgr in self._datasets.values():
                mgr.recover_node_tasks(node_id)

    def finished(self, dataset_name: Optional[str] = None) -> bool:
        with self._lock:
            if dataset_name:
                mgr = self._datasets.get(dataset_name)
                return mgr.completed() if mgr else True
            return all(m.completed() for m in self._datasets.values())

    def reset_worker_start_task_time(self, node_id: int):
        with self._lock:
            self._worker_start_task_time[node_id] = time.time()

    def task_hanged(self, timeout: float = 1800.0) -> bool:
        """True if every worker with in-flight tasks is silent past timeout."""
        with self._lock:
            doing_nodes = set()
            for mgr in self._datasets.values():
                doing_nodes.update(d.node_id for d in mgr.doing.values())
            if not doing_nodes:
                return False
            now = time.time()
            return all(
                now - self._worker_start_task_time.get(nid, now) > timeout
                for nid in doing_nodes)

    # ------------------------------------------------------- journal replay

    def export_state(self) -> Dict:
        with self._lock:
            return {name: mgr.export_state()
                    for name, mgr in self._datasets.items()}

    def restore_state(self, data: Dict):
        with self._lock:
            for name, mgr_data in data.items():
                self._datasets[name] = DatasetManager.from_state(mgr_data)

    def replay_dispatch(self, dataset_name: str, task_id: int, node_id: int,
                        start: int, end: int,
                        indices: Optional[List[int]] = None):
        with self._lock:
            mgr = self._datasets.get(dataset_name)
            if mgr is not None:
                mgr.replay_dispatch(task_id, node_id, start, end, indices)

    def replay_task_result(self, dataset_name: str, task_id: int,
                           success: bool):
        with self._lock:
            mgr = self._datasets.get(dataset_name)
            if mgr is not None:
                mgr.report_task_done(task_id, success)

    def get_dataset_checkpoint(self, dataset_name: str) -> str:
        with self._lock:
            mgr = self._datasets.get(dataset_name)
            if mgr is None:
                return ""
            return json.dumps(mgr.to_checkpoint())

    def restore_dataset_from_checkpoint(self, content: str) -> bool:
        try:
            data = json.loads(content)
            mgr = DatasetManager.from_checkpoint(data)
            with self._lock:
                self._datasets[mgr.splitter.dataset_name] = mgr
            return True
        except (ValueError, KeyError) as e:
            logger.warning("failed to restore dataset checkpoint: %s", e)
            return False

"""Warm-standby master: tail the primary's journal, promote on lease expiry.

Parity: the reference has NO master HA — `dlrover/python/master/main.py`
runs one process and a dead master is a dead job until kubernetes
reschedules it.  Redesign for the SPARe-class fleets PAPERS.md targets:
the control plane must not be a SPOF, and Chameleon-style real-time
fault reaction is hollow if the policy brain itself disappears for a
restart window.  This module is the standby half of ISSUE 20; the
leader half (lease heartbeat, peer fence, promotion) lives on
JobMaster (master/master.py).

Mechanics — everything rides machinery that already exists:

- **Shipping is a PULL** over the normal typed-JSON RPC plane: the
  tailer polls the POLLING-class `fetch_journal` verb (never journaled,
  never idem — a fetch that journaled would make shipping feed itself)
  from its OWN durable seq, so a lost response, a torn batch tail or a
  compaction race all resolve the same way: re-fetch.  Frames are
  ingested VERBATIM (`MasterJournal.ingest_frames` — whole frames only,
  contiguity enforced) so the standby's log is a byte-prefix of the
  primary's, which is exactly what makes the merged incident timeline's
  (epoch, seq) dedup exact and promotion "apply the last batch".
- **State folds through the SAME replay path** a restarted master uses:
  every adopted frame goes through `JobMaster._apply_entry`, the
  snapshot handoff (compaction outran the fetch) through
  `_restore_snapshot`.  There is no second state machine to drift.
- **Liveness is a journal artifact**: the leader heartbeats ``lease``
  frames into its own journal; the standby arms its expiry clock only
  after the FIRST lease frame arrives (a primary run without
  ``--lease-ttl`` makes the standby a pure mirror that never promotes —
  fleet_bench attaches one exactly that way).  Expiry is measured on
  the local monotonic clock from the moment a lease frame is ADOPTED,
  never on the frame's wall ``ts`` (clock skew must not fail over).
- **Promotion is fenced**: a final drain narrows the lost-tail window,
  then `JobMaster.promote_to_leader` journals the ``failover`` frame
  and re-opens the epoch strictly above anything the old primary could
  have issued (observed+2: a naively revived corpse lands at +1).  If
  the final drain adopts a FRESH lease frame the primary is alive after
  all — the tailer disarms and keeps mirroring.

Crash matrix (README "Surviving the master" carries the full table):
the primary dying before its next lease frame costs the standby at most
ttl + poll of detection; acked-but-unshipped tail frames are lost here
but every client retries them against the new leader under the ORIGINAL
idem key, so they re-apply exactly once under the new epoch.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..common import messages as msg
from ..common.comm import MasterUnreachableError, RpcClient, RpcError
from ..common.log import get_logger
from .master import JobMaster

logger = get_logger("standby")


def _default_poll_s() -> float:
    try:
        return max(0.01, float(os.getenv("DWT_STANDBY_POLL_S", "0.05")))
    except ValueError:
        return 0.05


class StandbyTailer:
    """Fetch→ingest→fold loop against one primary, plus the lease clock."""

    def __init__(self, master: JobMaster, primary_addr: str,
                 lease_ttl_s: float = 0.0,
                 poll_interval_s: Optional[float] = None,
                 max_frames: int = 512):
        if master.journal is None:
            raise ValueError("a standby needs a journal dir to mirror into")
        self.m = master
        self.primary_addr = primary_addr
        self.lease_ttl_s = float(lease_ttl_s)
        self.poll_interval_s = (poll_interval_s if poll_interval_s
                                is not None else _default_poll_s())
        self.max_frames = max(1, int(max_frames))
        # one persistent connection; retries stay SHORT — an unreachable
        # primary is a normal state here (that is the whole point), the
        # lease clock decides what it means
        self._client = RpcClient(primary_addr, node_id=-3,
                                 node_type="standby", timeout=2.0,
                                 retries=2, base_delay_s=0.02,
                                 max_delay_s=0.1)
        # monotonic instant the last lease frame was ADOPTED (0 = never:
        # expiry unarmed, pure-mirror mode)
        self._last_lease_mono = 0.0
        self.frames_folded = 0
        self.snapshots_adopted = 0

    def close(self):
        self._client.close()

    # ------------------------------------------------------------------ poll

    def poll_once(self) -> int:
        """One fetch→ingest→fold round.

        Returns frames adopted this round, or -1 when the primary did
        not answer.  All recovery is "re-fetch from our durable seq":
        duplicates are skipped and the first gap/torn frame stops the
        ingest (journal.ingest_frames), so a torn batch tail shipped
        mid-batch or a compaction racing the pull self-heals on the
        next round.
        """
        from_seq = self.m.journal.group_commit_stats()["durable_seq"]
        try:
            resp = self._client.get(msg.FetchJournalRequest(
                node_id=-3, from_seq=from_seq,
                max_frames=self.max_frames))
        except MasterUnreachableError:
            return -1
        except RpcError:
            logger.exception("fetch_journal answered with an error")
            return -1
        adopted = 0
        snap = bytes(resp.snapshot or b"")
        if snap and int(resp.snapshot_seq) > from_seq:
            # compaction outran the ring AND our seq: adopt the snapshot
            # verbatim, fold its state, then the tail resumes behind it
            try:
                state, seq, _epoch = self.m.journal.ingest_snapshot(snap)
            except (ValueError, OSError):
                logger.exception("snapshot handoff unreadable — refetch")
                return adopted
            if state:
                self.m._restore_snapshot(state)
            self.snapshots_adopted += 1
            adopted += 1
            logger.info("adopted primary snapshot at seq %d", seq)
        for frame in self.m.journal.ingest_frames(
                [bytes(f) for f in (resp.frames or [])]):
            kind = frame.get("kind", "")
            data = frame.get("data", {}) or {}
            if kind == "lease":
                self._last_lease_mono = time.monotonic()
            if kind == "epoch":
                # ingest_frames already advanced journal.epoch; mirror it
                # so our response envelopes match the primary's and a
                # worker probing us pre-promotion sees no spurious bump
                self.m.epoch = max(self.m.epoch,
                                   int(data.get("epoch", 0)))
            else:
                try:
                    self.m._apply_entry(kind, data)
                except Exception:  # noqa: BLE001 — one bad frame must not
                    # stop the mirror (same contract as replay)
                    logger.exception("standby fold: frame kind %r failed",
                                     kind)
            adopted += 1
            self.frames_folded += 1
        return adopted

    def lease_expired(self) -> bool:
        """True once the armed lease clock ran past ttl of silence."""
        if self.lease_ttl_s <= 0 or not self._last_lease_mono:
            return False
        return time.monotonic() - self._last_lease_mono > self.lease_ttl_s

    # ------------------------------------------------------------------- run

    def run(self, stopped: threading.Event,
            max_seconds: Optional[float] = None) -> bool:
        """Tail until promoted or stopped.  Returns True when promoted."""
        start = time.monotonic()
        logger.info("standby tailing %s (poll %.3fs, lease ttl %.2fs)",
                    self.primary_addr, self.poll_interval_s,
                    self.lease_ttl_s)
        while not stopped.wait(self.poll_interval_s):
            if max_seconds and time.monotonic() - start > max_seconds:
                return False
            self.poll_once()
            if not self.lease_expired():
                continue
            # final drain: narrow the lost-tail window to whatever the
            # dying primary never acked (those clients retry to us)
            before = self._last_lease_mono
            for _ in range(16):
                if self.poll_once() <= 0:
                    break
            if self._last_lease_mono > before:
                # a FRESH lease arrived mid-drain — the primary lives;
                # disarm and keep mirroring
                continue
            self.m.promote_to_leader()
            return True
        return False


def run_standby(primary_addr: str, port: int, min_nodes: int,
                max_nodes: int, node_unit: int = 1,
                journal_dir: Optional[str] = None,
                poll_interval: float = 5.0,
                max_seconds: Optional[float] = None,
                lease_ttl_s: float = 0.0,
                policy_engine=None,
                group_commit_max_frames: Optional[int] = None,
                group_commit_max_wait_ms: Optional[float] = None) -> int:
    """Standby process entry (`python -m dlrover_wuqiong_tpu.master
    --standby-of HOST:PORT`): mirror, maybe promote, then lead."""
    jd = journal_dir or os.getenv("DWT_MASTER_JOURNAL_DIR", "")
    if not jd:
        raise ValueError("--standby-of requires --journal-dir (the mirror)")
    master = JobMaster(port=port, min_nodes=min_nodes,
                       max_nodes=max_nodes, node_unit=node_unit,
                       journal_dir=jd, policy_engine=policy_engine,
                       group_commit_max_frames=group_commit_max_frames,
                       group_commit_max_wait_ms=group_commit_max_wait_ms,
                       standby=True, lease_ttl_s=lease_ttl_s)
    master.prepare()
    tailer = StandbyTailer(master, primary_addr,
                           lease_ttl_s=lease_ttl_s)
    start = time.monotonic()
    try:
        promoted = tailer.run(master._stopped, max_seconds=max_seconds)
        if not promoted:
            return 0
        remaining = None
        if max_seconds:
            # the budget covers the whole process, not each phase
            remaining = max(1.0,
                            max_seconds - (time.monotonic() - start))
        return master.run(poll_interval=poll_interval,
                          max_seconds=remaining)
    finally:
        tailer.close()
        master.stop()

"""Global-step throughput tracking & straggler baseline.

Parity: reference `dlrover/python/master/monitor/speed_monitor.py`
(`collect_global_step` :81, `running_speed` :113).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Set, Tuple


class SpeedMonitor:
    def __init__(self, max_records: int = 50):
        self._lock = threading.Lock()
        self._global_step_records: Deque[Tuple[float, int]] = deque(
            maxlen=max_records)
        self._global_step = 0
        self._start_training_time: Optional[float] = None
        self._sample_count = 0
        self._workers: Set[int] = set()
        self._init_time = time.time()
        self._max_speed = 0.0
        # reading before the first set_target_worker_num used to raise
        # AttributeError (never initialized here) — default to 0
        self._target_worker_num = 0

    def set_target_worker_num(self, num: int):
        self._target_worker_num = num

    @property
    def target_worker_num(self) -> int:
        return self._target_worker_num

    def all_worker_joined(self) -> bool:
        """True when every expected worker is running (0 target = never)."""
        with self._lock:
            return (self._target_worker_num > 0 and
                    len(self._workers) >= self._target_worker_num)

    def add_running_worker(self, node_id: int):
        with self._lock:
            self._workers.add(node_id)

    def remove_running_worker(self, node_id: int):
        with self._lock:
            self._workers.discard(node_id)

    @property
    def running_workers(self) -> Set[int]:
        with self._lock:
            return set(self._workers)

    def collect_global_step(self, step: int, timestamp: Optional[float] = None):
        ts = timestamp or time.time()
        with self._lock:
            if self._start_training_time is None:
                self._start_training_time = ts
            self._global_step = max(self._global_step, step)
            self._global_step_records.append((ts, step))
            self._sample_count += 1

    @property
    def completed_global_step(self) -> int:
        with self._lock:
            return self._global_step

    def running_speed(self) -> float:
        """Steps/sec over the record window."""
        with self._lock:
            if len(self._global_step_records) < 2:
                return 0.0
            (t0, s0) = self._global_step_records[0]
            (t1, s1) = self._global_step_records[-1]
            if t1 <= t0:
                return 0.0
            speed = (s1 - s0) / (t1 - t0)
            self._max_speed = max(self._max_speed, speed)
            return speed

    def worker_adjustment_finished(self) -> bool:
        """Has speed stabilized since the last membership change?"""
        return len(self._global_step_records) >= \
            self._global_step_records.maxlen

    def first_step_timestamp(self) -> Optional[float]:
        with self._lock:
            return self._start_training_time

    def reset_running_speed_monitor(self):
        with self._lock:
            self._global_step_records.clear()

    def goodput(self) -> float:
        """Fraction of wall-clock spent at >50% of peak observed speed —
        the north-star metric (BASELINE.md)."""
        with self._lock:
            if self._start_training_time is None or self._max_speed <= 0:
                return 0.0
            elapsed = time.time() - self._start_training_time  # graftlint: disable=wall-clock-duration -- step records carry node-reported wall timestamps (cross-process)
            if elapsed <= 0:
                return 0.0
            # steps completed / (elapsed * peak speed)
            return min(1.0, self._global_step / (elapsed * self._max_speed))

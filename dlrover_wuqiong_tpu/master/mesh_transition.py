"""Fenced in-place mesh transition: hot-swap survivor takeover.

Parity axis: the reference (`dlrover/python/master/node/job_manager.py`
relaunch paths) only knows restart-the-world recovery; ElasWave and
PHOENIX (PAPERS.md) argue the survivors should absorb a dead node's
shards from peer memory instead — no teardown, no storage round trip.
This module is the master-side state machine for that protocol:

    propose → fence → hydrate → cutover → release → done
                                    ↘ aborted (any nack / timeout)

Phase ladder (worker-side work in trainer/hotswap.py):

- **propose**: the policy route said "hotswap" for a dead node; the
  master freezes the transition facts (dead rank, survivors, the fenced
  target round) and HOLDS rendezvous formation — a replacement node
  arriving mid-transition parks in the waiting set and cannot race the
  cutover.  Survivors ack once paused at a FUSION BOUNDARY.
- **fence**: survivors adopt the bumped fencing epoch (the round the
  post-cutover world will carry); acks mean no survivor will dispatch
  into the old world again.
- **hydrate**: survivors pull the dead rank's staged shards from its
  ring-replica holders (checkpoint/replica.py fetch_peer —
  digest-verified before any byte reaches device_put).
- **cutover**: survivors re-shard onto the pre-compiled degraded-mesh
  executable (warm pool / persistent compile cache — zero cold
  compiles) and confirm.
- **release**: master rewrites the rendezvous world WITHOUT the dead
  node (journaled rdzv_world frame, round bumped to the fenced epoch),
  releases the formation hold, and the transition is done.

Durability contract (mirrors brain/policy.py): every event — the
propose, each survivor ack, each phase advance, an abort — is a
``mesh_transition`` journal frame appended BEFORE the new state becomes
visible, so a master SIGKILLed mid-transition replays to exactly the
same phase and the survivors' next poll continues the ladder where it
stopped.  ``apply()`` is therefore a pure state fold shared by the live
path and journal replay; the live path journals first, replay calls
``apply`` alone.  Phase ADVANCEMENT is decided only by the live master
(``advance_event`` after each ack) and journaled as its own frame —
replaying acks never re-advances, the phase frames are authoritative.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..common import messages as msg
from ..common.log import get_logger

logger = get_logger("mesh_transition")

PHASES = ("propose", "fence", "hydrate", "cutover", "release")
TERMINAL = ("done", "aborted")


class MeshTransitionManager:
    """State machine + event log fold for one transition at a time."""

    def __init__(self, timeout_s: float = 120.0):
        self._lock = threading.Lock()
        self._seq = 0
        self._active: Optional[Dict] = None
        self._history: List[Dict] = []
        self.timeout_s = float(timeout_s)
        # monotonic deadline for the ACTIVE transition (live master only
        # — never journaled: a replayed master re-arms a fresh deadline)
        self._deadline = 0.0

    # ---------------------------------------------------------------- reads

    def active(self) -> Optional[Dict]:
        with self._lock:
            if self._active is None or \
                    self._active["phase"] in TERMINAL:
                return None
            return dict(self._active)

    def state_message(self) -> msg.MeshTransitionState:
        """Current (or last terminal) transition as the wire message."""
        with self._lock:
            t = self._active or (self._history[-1] if self._history
                                 else None)
            if t is None:
                return msg.MeshTransitionState()
            return msg.MeshTransitionState(
                transition_id=t["tid"], phase=t["phase"],
                dead_node_id=t["dead_node_id"],
                dead_rank=t["dead_rank"],
                survivors=list(t["survivors"]),
                rdzv_round=t["rdzv_round"],
                fence_epoch=t["fence_epoch"],
                started_at=t["started_at"], reason=t.get("reason", ""))

    # --------------------------------------------------------- event builders
    # Builders allocate/validate under the lock but DO NOT mutate: the
    # caller journals the event (blocking fsync wait — never under this
    # lock) and then folds it in with apply().

    def propose_event(self, dead_node_id: int, dead_rank: int,
                      survivors: List[int], rdzv_round: int,
                      reason: str = "") -> Optional[Dict]:
        with self._lock:
            if self._active is not None and \
                    self._active["phase"] not in TERMINAL:
                return None  # one transition at a time
            if not survivors:
                return None  # nobody left to absorb the shards
            self._seq += 1
            return {"event": "propose", "tid": self._seq,
                    "dead_node_id": int(dead_node_id),
                    "dead_rank": int(dead_rank),
                    "survivors": sorted(int(s) for s in survivors),
                    "rdzv_round": int(rdzv_round),
                    "fence_epoch": int(rdzv_round) + 1,
                    "reason": reason,
                    # persisted cross-process timestamp — wall clock
                    "started_at": time.time()}

    def ack_event(self, node_id: int, tid: int, phase: str, ok: bool,
                  detail: str = "") -> Optional[Dict]:
        with self._lock:
            t = self._active
            if t is None or t["tid"] != tid or t["phase"] in TERMINAL:
                return None
            if phase != t["phase"] or node_id not in t["survivors"]:
                return None
            return {"event": "ack", "tid": tid, "node_id": int(node_id),
                    "phase": phase, "ok": bool(ok), "detail": detail}

    def advance_event(self) -> Optional[Dict]:
        """Phase frame when every survivor acked the current phase."""
        with self._lock:
            t = self._active
            if t is None or t["phase"] in TERMINAL:
                return None
            phase = t["phase"]
            if phase not in PHASES:
                return None
            acked = t["acks"].get(phase, {})
            if any(not ok for ok in acked.values()):
                return self._abort_locked(t, "survivor nacked "
                                          f"phase {phase}")
            if phase == "release":
                # release has no worker-side ack: the master finishes it
                # (world rewrite) and advances immediately
                return {"event": "phase", "tid": t["tid"],
                        "phase": "done"}
            if set(acked) >= set(t["survivors"]):
                nxt = PHASES[PHASES.index(phase) + 1] \
                    if phase != PHASES[-1] else "done"
                return {"event": "phase", "tid": t["tid"], "phase": nxt}
            return None

    def abort_event(self, reason: str) -> Optional[Dict]:
        with self._lock:
            t = self._active
            if t is None or t["phase"] in TERMINAL:
                return None
            return self._abort_locked(t, reason)

    def _abort_locked(self, t: Dict, reason: str) -> Dict:
        return {"event": "abort", "tid": t["tid"], "reason": reason}

    def timed_out(self) -> bool:
        with self._lock:
            return (self._active is not None
                    and self._active["phase"] not in TERMINAL
                    and self._deadline > 0.0
                    and time.monotonic() > self._deadline)

    # ----------------------------------------------------------------- fold

    def apply(self, event: Dict) -> bool:
        """Fold one (journaled) event into state — live path AND replay.

        Pure and deterministic: replaying the journal reproduces the
        exact phase the master died in.  Returns False for events that
        no longer apply (stale tid, unknown survivor) — harmless on
        replay, a client error live."""
        kind = event.get("event", "")
        with self._lock:
            if kind == "propose":
                if self._active is not None and \
                        self._active["phase"] not in TERMINAL:
                    logger.warning("mesh transition %s proposed while %s "
                                   "active — ignored", event.get("tid"),
                                   self._active["tid"])
                    return False
                self._seq = max(self._seq, int(event["tid"]))
                self._active = {
                    "tid": int(event["tid"]), "phase": "propose",
                    "dead_node_id": int(event["dead_node_id"]),
                    "dead_rank": int(event["dead_rank"]),
                    "survivors": list(event["survivors"]),
                    "rdzv_round": int(event["rdzv_round"]),
                    "fence_epoch": int(event["fence_epoch"]),
                    "reason": event.get("reason", ""),
                    "started_at": float(event.get("started_at", 0.0)),
                    "acks": {}}
                self._deadline = time.monotonic() + self.timeout_s
                return True
            t = self._active
            if t is None or t["tid"] != int(event.get("tid", -1)):
                return False
            if kind == "ack":
                t["acks"].setdefault(event["phase"], {})[
                    int(event["node_id"])] = bool(event.get("ok", True))
                return True
            if kind == "phase":
                t["phase"] = event["phase"]
                self._deadline = time.monotonic() + self.timeout_s
                if t["phase"] in TERMINAL:
                    self._finish_locked(t)
                return True
            if kind == "abort":
                t["phase"] = "aborted"
                t["reason"] = event.get("reason", t.get("reason", ""))
                self._finish_locked(t)
                return True
        logger.warning("mesh transition: unknown event %r", kind)
        return False

    def _finish_locked(self, t: Dict):
        self._history.append(t)
        if len(self._history) > 100:
            self._history = self._history[-50:]
        self._active = None
        self._deadline = 0.0

    # ------------------------------------------------------------- snapshot

    def export_state(self) -> Dict:
        with self._lock:
            return {"seq": self._seq,
                    "active": dict(self._active) if self._active else None,
                    "history": [dict(t) for t in self._history]}

    def restore_state(self, data: Dict):
        with self._lock:
            self._seq = max(self._seq, int(data.get("seq", 0)))
            active = data.get("active")
            self._active = dict(active) if active else None
            self._history = [dict(t) for t in data.get("history", [])]
            if self._active is not None:
                self._deadline = time.monotonic() + self.timeout_s
